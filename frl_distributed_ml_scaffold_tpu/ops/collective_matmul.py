"""Latency-hiding collective matmul pair (SURVEY C6; the TP analogue of
parallel/fsdp_overlap.py's explicit FSDP schedule).

Under plain GSPMD tensor parallelism the per-layer ``model``-axis
collectives (the Megatron f/g pair around QKV/out and fc_in/fc_out) are
monolithic ops serialized against the matmuls they feed — fully exposed on
every layer's critical path. "Scalable Training of Language Models using
JAX pjit and TPUv4" (PAPERS.md) decomposes each matmul+collective into
per-shard blocks chained by ``ppermute`` so each block's communication
rides under the previous block's compute; "Memory-efficient array
redistribution through portable collective communication" gives the same
blockwise-ring framing for the transpose path. This module is that pair,
written per-shard (callers wrap it in ``shard_map`` — see
parallel/tp_overlap.py):

- ``all_gather_matmul``: ``x`` sharded along a chunk dim (sequence for the
  GPT stack, batch for ViT) times a column-split ``w``. A *bidirectional*
  ring — each step multiplies the resident chunk while the next chunks
  stream in from both neighbors, using both directions of the ICI links —
  produces the gathered-times-split result without ever materializing the
  gathered activation as the output of one monolithic collective.
- ``matmul_reduce_scatter``: its transpose. Partial products accumulate
  into chunk accumulators that rotate around the ring (again both
  directions, split along the output features), so each hop's partial-sum
  transfer hides under the next chunk's matmul; the full partial-product
  tensor (the allreduce input GSPMD would build) never exists.

Each op carries a ``jax.custom_vjp`` making the backward of one the
forward schedule of the other (the gather's transpose IS the
reduce-scatter), with the weight gradient accumulated blockwise inside the
same ring — so no full-size gathered activation is saved or rebuilt
monolithically in either direction.

Low-precision fast path (``lowp="int8" | "fp8_e4m3" | "fp8_e5m2"``,
ROADMAP item 5): the rings are bandwidth-bound, so shrinking the bytes
they move is a compounding win on top of the overlap itself. With
``lowp`` set, every ``ppermute`` moves QUANTIZED payloads
(ops/quantization.py): streamed chunks are quantized ONCE per-tensor
before entering the ring and ride the wire as 1-byte elements next to
their scalar scale; rotating partial-sum accumulators are re-quantized
per hop (error ~qmax⁻¹ per hop, tolerance-gated in
tests/test_low_precision.py); and the matmul at each visit runs in low
precision against the per-channel-quantized resident weight (int8 on the
MXU's integer path, exact int32 accumulation). Gradients take the
straight-through path: the custom VJPs keep their full-precision
residuals and blockwise-dw structure, but the backward rings' own
transfers are quantized the same way — 4x fewer bytes on the model-axis
collective-permute class at fp32 (2x at bf16), pinned by graft-lint's
per-dtype collective census
(``analysis.pins.assert_collective_bytes_within``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from frl_distributed_ml_scaffold_tpu.dist import collectives
from frl_distributed_ml_scaffold_tpu.ops.quantization import (
    dequantize,
    qdot,
    quantize,
    resolve_lowp,
)


def _ring_perms(n: int):
    """(forward, backward) neighbor permutations: src -> src+1 / src-1."""
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    return fwd, bwd


def _take(a, start, length, axis):
    return lax.dynamic_slice_in_dim(a, start, length, axis=axis)


def _put(a, update, start, axis):
    return lax.dynamic_update_slice_in_dim(a, update, start, axis=axis)


def _mm(x, w, precision):
    """Contract x's last dim with w's first: [..., K] x [K, M] -> [..., M]."""
    return lax.dot_general(
        x, w, (((x.ndim - 1,), (0,)), ((), ())), precision=precision
    )


def _wgrad(chunk, stat, order, precision):
    """Blockwise weight-grad contribution: contract every non-feature dim.

    ``order="lhs"`` -> chunk^T @ stat (the all-gather-matmul's dw, [K, M]);
    ``order="rhs"`` -> stat^T @ chunk (the reduce-scatter's dw, [M, K]).
    Accumulated in fp32: the monolithic dot this replaces reduces on the
    MXU in fp32; a bf16 chain of n partial adds would not.
    """
    a, b = (chunk, stat) if order == "lhs" else (stat, chunk)
    nb = a.ndim - 1
    return lax.dot_general(
        a,
        b,
        (((tuple(range(nb)),) * 2), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32,
    )


def _stream_ring(
    x,
    axis_name: str,
    chunk_axis: int,
    *,
    w=None,
    stationary=None,
    wgrad_order: str = "lhs",
    return_full: bool = False,
    precision=None,
    lowp: str | None = None,
):
    """Bidirectional ppermute ring over ``x``'s shards.

    Every shard's chunk visits every device (split in half along
    ``chunk_axis``, one half streaming each direction so both link
    directions carry traffic). Per visiting chunk ``c`` (the shard
    originally resident on device ``c``), optionally:

    - ``w``:          y[rows c] = chunk @ w        (all-gather-matmul)
    - ``return_full``: full[rows c] = chunk        (assembled gather)
    - ``stationary``:  dw += wgrad(chunk, stationary[rows c])

    With ``lowp`` set, each chunk is quantized per-tensor ONCE before
    entering the ring and the hops move (1-byte payload, scalar scale)
    pairs; the visit matmul runs quantized against the per-channel
    quantized resident ``w``, and the ``full``/wgrad consumers see the
    dequantized values (every rank reconstructs the identical array —
    the quantization error is applied once, at the source).

    Returns ``(y, full, dw)`` with unused slots ``None``.
    """
    # ``lowp`` is a schedule attribute (parallel/schedule.py): accept any
    # knob spelling ("off"/"none"/None/format) via the shared vocabulary.
    lowp = resolve_lowp(lowp)
    n = collectives.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    tc = x.shape[chunk_axis]
    gathered = list(x.shape)
    gathered[chunk_axis] = n * tc

    y = full = dw = None
    if w is not None:
        y_shape = gathered[:-1] + [w.shape[-1]]
        y = jnp.zeros(y_shape, jnp.result_type(x.dtype, w.dtype))
    if return_full:
        full = jnp.zeros(gathered, x.dtype)
    if stationary is not None:
        k, m = x.shape[-1], stationary.shape[-1]
        shape = (k, m) if wgrad_order == "lhs" else (m, k)
        dw = jnp.zeros(shape, jnp.float32)

    q_w = s_w = None
    if lowp is not None and w is not None:
        # Per-output-channel weight scales: the resident split never
        # moves, so its quantization is paid once per ring.
        q_w, s_w = quantize(w, lowp, channel_axes=(w.ndim - 1,))

    fwd, bwd = _ring_perms(n)
    half = tc // 2
    bidir = n > 1 and tc % 2 == 0 and tc >= 2

    def pack(chunk):
        """Chunk -> wire payload: identity, or (quantized, scale)."""
        if lowp is None:
            return chunk
        return quantize(chunk, lowp)

    def visit(y, full, dw, payload, c, off):
        if lowp is None:
            chunk, mm = payload, lambda: _mm(payload, w, precision)
        else:
            q_c, s_c = payload
            chunk = dequantize(q_c, s_c, x.dtype)
            mm = lambda: qdot(
                q_c, s_c, q_w, s_w[0],
                (((q_c.ndim - 1,), (0,)), ((), ())),
            ).astype(y.dtype)
        start = c * tc + off
        if w is not None:
            y = _put(y, mm().astype(y.dtype), start, chunk_axis)
        if return_full:
            full = _put(full, chunk, start, chunk_axis)
        if stationary is not None:
            stat_c = _take(
                stationary, start, chunk.shape[chunk_axis], chunk_axis
            )
            dw = dw + _wgrad(chunk, stat_c, wgrad_order, precision)
        return y, full, dw

    def hop(payload, perm):
        if lowp is None:
            return lax.ppermute(payload, axis_name, perm)
        q_c, s_c = payload
        return (
            lax.ppermute(q_c, axis_name, perm),
            lax.ppermute(s_c, axis_name, perm),
        )

    if bidir:
        lo = pack(_take(x, 0, half, chunk_axis))
        hi = pack(_take(x, half, tc - half, chunk_axis))
        c_lo = idx
        c_hi = idx
        for step in range(n):
            y, full, dw = visit(y, full, dw, lo, c_lo, 0)
            y, full, dw = visit(y, full, dw, hi, c_hi, half)
            if step < n - 1:
                # lo rides src->src+1 (each device receives from its left
                # neighbor), hi rides the opposite direction: after s hops
                # this device holds chunks idx-s and idx+s.
                lo = hop(lo, fwd)
                hi = hop(hi, bwd)
                c_lo = (c_lo - 1) % n
                c_hi = (c_hi + 1) % n
    else:
        payload = pack(x)
        c = idx
        for step in range(n):
            y, full, dw = visit(y, full, dw, payload, c, 0)
            if step < n - 1:
                payload = hop(payload, fwd)
                c = (c - 1) % n
    if dw is not None:
        target = jnp.result_type(
            x.dtype, stationary.dtype if stationary is not None else x.dtype
        )
        dw = dw.astype(target)
    return y, full, dw


def _rotating_ring(
    y, w, axis_name: str, chunk_axis: int, *, extra=None, precision=None,
    lowp: str | None = None,
):
    """Rotating-accumulator ring: ``z`` chunk ``c`` = sum over devices j of
    ``y_j[rows c] @ w_j`` (+ ``extra_j[rows c]``), ending on device ``c``.

    Bidirectional: the accumulator is split in half along the OUTPUT
    feature dim, one half circulating each direction, so each hop moves
    half-size messages on both links while the next chunk's matmul runs.

    With ``lowp``, the contributing matmuls run quantized (per-tensor
    chunk x per-channel resident weight) and each hop re-quantizes the
    partial-sum accumulator for the wire — the one place the fast path
    pays repeated quantization (n-1 hops of ~qmax⁻¹ relative noise on
    the running sum; the accumulator itself stays fp32 between hops).
    """
    lowp = resolve_lowp(lowp)
    n = collectives.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    tc = y.shape[chunk_axis] // n
    d = w.shape[-1]
    fwd, bwd = _ring_perms(n)
    out_dtype = jnp.result_type(y.dtype, w.dtype)

    q_w = s_w = None
    if lowp is not None:
        q_w, s_w = quantize(w, lowp, channel_axes=(w.ndim - 1,))

    def contrib(c, col0, cols):
        y_c = _take(y, c * tc, tc, chunk_axis)
        if lowp is None:
            part = _mm(y_c, w[:, col0 : col0 + cols], precision)
        else:
            q_c, s_c = quantize(y_c, lowp)
            part = qdot(
                q_c, s_c, q_w[:, col0 : col0 + cols],
                s_w[0, col0 : col0 + cols],
                (((q_c.ndim - 1,), (0,)), ((), ())),
            )
        if extra is not None:
            part = part + lax.slice_in_dim(
                _take(extra, c * tc, tc, chunk_axis), col0, col0 + cols, axis=-1
            ).astype(part.dtype)
        return part

    def hop(acc, perm):
        if lowp is None:
            return lax.ppermute(acc, axis_name, perm)
        q_a, s_a = quantize(acc, lowp)
        return dequantize(
            lax.ppermute(q_a, axis_name, perm),
            lax.ppermute(s_a, axis_name, perm),
            acc.dtype,
        )

    bidir = n > 1 and d % 2 == 0 and d >= 2
    if bidir:
        dh = d // 2
        acc_lo = acc_hi = None
        for step in range(n):
            c_lo = (idx - 1 - step) % n
            c_hi = (idx + 1 + step) % n
            p_lo = contrib(c_lo, 0, dh)
            p_hi = contrib(c_hi, dh, d - dh)
            acc_lo = p_lo if acc_lo is None else acc_lo + p_lo
            acc_hi = p_hi if acc_hi is None else acc_hi + p_hi
            if step < n - 1:
                # acc for chunk c walks c+1, c+2, ..., ending home at c
                # (and mirrored for the other half).
                acc_lo = hop(acc_lo, fwd)
                acc_hi = hop(acc_hi, bwd)
        z = jnp.concatenate([acc_lo, acc_hi], axis=-1)
    else:
        acc = None
        for step in range(n):
            c = (idx - 1 - step) % n
            p = contrib(c, 0, d)
            acc = p if acc is None else acc + p
            if step < n - 1:
                acc = hop(acc, fwd)
        z = acc
    return z.astype(out_dtype)


# ------------------------------------------------------------------ public


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def all_gather_matmul(x, w, axis_name, chunk_axis, return_full=False,
                      precision=None, lowp=None):
    """Per-shard blockwise all-gather-matmul (call inside ``shard_map``).

    ``x``: this shard's slice along ``chunk_axis``; ``w``: this shard's
    column split ``[K, M_local]``. Returns ``y = gather(x) @ w`` (gathered
    along ``chunk_axis``, still column-split), and with
    ``return_full=True`` also the assembled gather of ``x`` itself — for
    consumers that share the streamed chunks (the fused QKV projection)
    without paying a second ring.

    ``lowp``: quantize the ring (module docstring) — chunks stream as
    1-byte payloads + scales, the visit matmuls run in low precision, and
    ``full`` is assembled from the dequantized chunks (so every sibling
    consumer sees the same once-quantized values).

    Backward: the activation gradient is the transpose schedule
    (``matmul_reduce_scatter`` of ``dy @ w^T``, folding the full-copy
    cotangent into the same rotating accumulators) and ``dw`` accumulates
    blockwise while the chunks stream again — the gathered ``x`` is never
    saved. Under ``lowp`` the backward rings' transfers quantize too
    (straight-through: the residuals stay full precision).
    """
    y, full, _ = _stream_ring(
        x, axis_name, chunk_axis, w=w, return_full=return_full,
        precision=precision, lowp=lowp,
    )
    return (y, full) if return_full else y


def _agm_fwd(x, w, axis_name, chunk_axis, return_full, precision, lowp):
    y, full, _ = _stream_ring(
        x, axis_name, chunk_axis, w=w, return_full=return_full,
        precision=precision, lowp=lowp,
    )
    return ((y, full) if return_full else y), (x, w)


def _agm_bwd(axis_name, chunk_axis, return_full, precision, lowp, res, ct):
    x, w = res
    dy, dfull = ct if return_full else (ct, None)
    # dw rides a fresh chunk stream (the backward's re-gather — gathered x
    # is never a residual); dx is the sibling op's rotating ring over
    # dy @ w^T, with the gathered-copy cotangent summed into the same
    # accumulators (its transpose is exactly a reduce-scatter).
    _, _, dw = _stream_ring(
        x, axis_name, chunk_axis, stationary=dy, wgrad_order="lhs",
        precision=precision, lowp=lowp,
    )
    dx = _rotating_ring(
        dy, w.T, axis_name, chunk_axis, extra=dfull, precision=precision,
        lowp=lowp,
    )
    return dx.astype(x.dtype), dw.astype(w.dtype)


all_gather_matmul.defvjp(_agm_fwd, _agm_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def matmul_reduce_scatter(y, w, axis_name, chunk_axis, precision=None,
                          lowp=None):
    """Per-shard blockwise matmul-reduce-scatter (call inside ``shard_map``).

    ``y``: gathered-along-``chunk_axis``, feature-split ``[..., M_local]``
    input; ``w``: this shard's row split ``[M_local, K]``. Returns this
    shard's chunk of ``sum_shards(y @ w)`` — the Megatron row-parallel
    output, reduced AND scattered by the rotating ring instead of a
    monolithic allreduce. ``lowp`` quantizes the contributing matmuls and
    the per-hop accumulator transfers (module docstring).

    Backward: ``dy`` is the sibling ``all_gather_matmul`` schedule over the
    incoming chunk cotangents times ``w^T``, and ``dw`` accumulates
    blockwise against the SAME streamed chunks — one ring serves both.
    """
    return _rotating_ring(
        y, w, axis_name, chunk_axis, precision=precision, lowp=lowp
    )


def _mrs_fwd(y, w, axis_name, chunk_axis, precision, lowp):
    return (
        _rotating_ring(
            y, w, axis_name, chunk_axis, precision=precision, lowp=lowp
        ),
        (y, w),
    )


def _mrs_bwd(axis_name, chunk_axis, precision, lowp, res, dz):
    y, w = res
    dy, _, dw = _stream_ring(
        dz,
        axis_name,
        chunk_axis,
        w=w.T,
        stationary=y,
        wgrad_order="rhs",
        precision=precision,
        lowp=lowp,
    )
    return dy.astype(y.dtype), dw.astype(w.dtype)


matmul_reduce_scatter.defvjp(_mrs_fwd, _mrs_bwd)
