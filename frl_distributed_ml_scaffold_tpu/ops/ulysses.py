"""Ulysses-style sequence parallelism (SURVEY C8): all_to_all resharding.

The alternative long-context scheme: instead of rotating K/V (ring), one
``all_to_all`` over the ``seq`` axis converts sequence-sharded activations
into head-sharded ones — each shard then holds the FULL sequence for a
subset of heads, runs ordinary dense attention locally, and a second
``all_to_all`` converts back. Two collectives per attention call vs. the
ring's n-1 hops: cheaper at moderate sequence lengths, but requires
num_heads % seq_axis == 0 and O(T²/n) score memory per shard.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.dist.mesh import BATCH_AXES, current_mesh_env
from frl_distributed_ml_scaffold_tpu.ops.ring_attention import dense_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    causal: bool = True,
    interpret: bool | None = None,
) -> jax.Array:
    """(B, T, H, D) attention, T sharded over ``axis_name`` (SP-Ulysses).

    The local full-sequence attention after the all_to_all runs through the
    fused flash kernel on TPU (dense on untileable shapes / other backends),
    so Ulysses' per-shard memory is O(block), not O(T²/n).
    """
    env = current_mesh_env()
    if env is None or env.axis_size(axis_name) == 1:
        return dense_attention(q, k, v, causal=causal)

    n = env.axis_size(axis_name)
    tp = env.axis_size("model")
    # The shard_map spec below shards heads over "model" too, so the
    # divisibility that matters is of the *local* (per-TP-shard) head count.
    if q.shape[2] % tp != 0 or (q.shape[2] // tp) % n != 0:
        raise ValueError(
            f"ulysses needs num_heads/model_axis ({q.shape[2]}/{tp}) "
            f"divisible by seq axis ({n}); use ring attention instead"
        )

    spec = P(BATCH_AXES, axis_name, "model", None)
    inner = partial(
        _ulysses_shard_fn, axis_name=axis_name, causal=causal, interpret=interpret
    )
    from frl_distributed_ml_scaffold_tpu.dist.mesh import shard_map_compat

    return shard_map_compat(
        inner,
        mesh=env.mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)


def _ulysses_shard_fn(q, k, v, *, axis_name: str, causal: bool, interpret):
    from frl_distributed_ml_scaffold_tpu.ops.flash_attention import (
        local_flash_attention,
    )

    # seq-sharded (B, T/n, H, D) -> head-sharded (B, T, H/n, D)
    def to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = local_flash_attention(qh, kh, vh, causal=causal, interpret=interpret)
    return to_seq(out)
