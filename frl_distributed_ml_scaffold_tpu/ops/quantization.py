"""Scaled low-precision (int8 / fp8) quantization primitives.

ROADMAP item 5's common substrate: both low-precision consumers — the
collective-matmul rings (ops/collective_matmul.py ``lowp=``) and the
quantized KV cache (models/gpt.py ``kv_cache_quant``) — are symmetric
scaled-integer/fp8 schemes built from the three functions here:

- ``quantize``: ``x ≈ q * scale`` with ``q`` in the target format and
  ``scale = max|x| / qmax`` over everything except the kept channel axes.
  Per-tensor (``channel_axes=None``) for streamed ring chunks — one
  scalar rides the wire next to each chunk — and per-channel for weights
  (output features keep their own dynamic range) and the KV cache (each
  written token's heads quantize independently, so a cache entry is
  never re-quantized after it lands).
- ``dequantize``: the exact inverse map back to a float dtype.
- ``qdot`` / ``quantized_matmul``: the scaled matmul. int8 contracts on
  the integer unit (``preferred_element_type=int32`` — the MXU's native
  int8 path on TPU, exact on every backend) and applies
  ``scale_lhs * scale_rhs`` to the fp32 result; fp8 upcasts in-register
  and contracts with fp32 accumulation. ``quantized_matmul`` carries a
  straight-through ``custom_vjp``: the forward computes in low precision,
  the backward differentiates as if the quantizers were identity (the
  full-precision operands are the residuals) — bf16/fp32 master weights,
  low-precision compute, standard STE training semantics.

Formats: ``int8`` (the default — 1 byte, exact integer accumulation),
``fp8_e4m3`` (1 byte, wider dynamic range per element, for
activation-heavy tensors), ``fp8_e5m2`` (gradient-flavored range). The
format string is the one vocabulary every knob speaks
(``parallel.low_precision``, ``model.kv_cache_quant``,
``collective_matmul(..., lowp=)``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

#: format name -> (storage dtype, largest representable magnitude).
LOWP_FORMATS: dict[str, tuple] = {
    "int8": (jnp.int8, 127.0),
    "fp8_e4m3": (jnp.float8_e4m3fn, 448.0),
    "fp8_e5m2": (jnp.float8_e5m2, 57344.0),
}


def lowp_dtype(fmt: str):
    """Storage dtype of a low-precision format name (KeyError on typos,
    listing the vocabulary — every knob funnels through here)."""
    if fmt not in LOWP_FORMATS:
        raise KeyError(
            f"unknown low-precision format {fmt!r} "
            f"(known: {sorted(LOWP_FORMATS)})"
        )
    return LOWP_FORMATS[fmt][0]


def resolve_lowp(value) -> str | None:
    """Normalize any knob spelling of "which low-precision format" to a
    canonical format name or None.

    The overlap-schedule layer (parallel/schedule.py) declares ``lowp``
    as a transfer attribute whose off spellings are ``None``/"none"/"off";
    the ring ops and the TpHooks pass whatever the schedule carries
    straight through here, so every consumer speaks one vocabulary.
    Objects carrying a ``.lowp`` attribute (schedule rules) resolve to
    that attribute. Unknown format names raise the ``lowp_dtype``
    KeyError with the vocabulary listed.
    """
    if value is not None and hasattr(value, "lowp"):
        value = value.lowp
    if value is None or value in ("none", "off", ""):
        return None
    lowp_dtype(value)  # KeyError (with the vocabulary) on typos
    return value


def qmax(fmt: str) -> float:
    """Largest representable magnitude of a format."""
    lowp_dtype(fmt)
    return LOWP_FORMATS[fmt][1]


def quantize(
    x: jax.Array,
    fmt: str,
    channel_axes: tuple[int, ...] | int | None = None,
    *,
    scale_dtype=jnp.float32,
):
    """Symmetric scaled quantization: returns ``(q, scale)`` with
    ``x ≈ q * scale``.

    ``channel_axes`` are the axes that KEEP independent scales (the
    max-abs reduction runs over all the others); ``None`` means
    per-tensor. The scale keeps reduced axes as size-1 dims so
    ``q * scale`` broadcasts back without bookkeeping (callers that
    store scales squeeze them explicitly).
    """
    dtype, m = lowp_dtype(fmt), qmax(fmt)
    if channel_axes is None:
        reduce_axes = tuple(range(x.ndim))
    else:
        if isinstance(channel_axes, int):
            channel_axes = (channel_axes,)
        keep = {a % x.ndim for a in channel_axes}
        reduce_axes = tuple(a for a in range(x.ndim) if a not in keep)
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=reduce_axes,
                   keepdims=True)
    # All-zero slices quantize to zeros with scale 1 (never divide by 0).
    scale = jnp.where(amax > 0.0, amax / m, 1.0).astype(jnp.float32)
    y = x.astype(jnp.float32) / scale
    if fmt == "int8":
        q = jnp.clip(jnp.round(y), -m, m).astype(dtype)
    else:
        q = jnp.clip(y, -m, m).astype(dtype)
    return q, scale.astype(scale_dtype)


def dequantize(q: jax.Array, scale: jax.Array, dtype=jnp.float32):
    """Inverse of ``quantize``: ``q * scale`` in the requested dtype
    (``scale`` must broadcast against ``q`` — keepdims scales do, stored
    squeezed scales need their trailing dim back first)."""
    return (q.astype(jnp.float32) * scale.astype(jnp.float32)).astype(dtype)


def qdot(q_lhs, s_lhs, q_rhs, s_rhs, dimension_numbers, *,
         preferred=jnp.float32):
    """Scaled low-precision contraction: both operands already quantized.

    int8 operands contract on the integer path (int32 accumulation —
    exact, and the TPU MXU's native 8-bit mode); fp8 upcasts to fp32 in
    register. The result is rescaled by ``s_lhs * s_rhs``, so the scale
    layouts must broadcast against the contraction OUTPUT (per-tensor
    scales always do; per-channel rhs scales must live on kept dims).
    """
    if q_lhs.dtype == jnp.int8 and q_rhs.dtype == jnp.int8:
        raw = lax.dot_general(
            q_lhs, q_rhs, dimension_numbers,
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)
    else:
        raw = lax.dot_general(
            q_lhs.astype(jnp.float32), q_rhs.astype(jnp.float32),
            dimension_numbers, preferred_element_type=preferred,
        )
    return raw * (s_lhs.astype(jnp.float32) * s_rhs.astype(jnp.float32))


def _qmm_fwd_impl(x, w, fmt):
    """[..., K] x [K, M] low-precision matmul: per-tensor x scale,
    per-output-channel w scale."""
    q_x, s_x = quantize(x, fmt)
    q_w, s_w = quantize(w, fmt, channel_axes=(1,))  # scale [1, M]
    dims = (((x.ndim - 1,), (0,)), ((), ()))
    # s_x is all-size-1 (broadcasts anywhere); s_w [1, M] broadcasts onto
    # the [..., M] result's feature dim.
    y = qdot(q_x, jnp.squeeze(s_x), q_w, s_w[0], dims)
    return y.astype(jnp.result_type(x.dtype, w.dtype))


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def quantized_matmul(x, w, fmt: str):
    """Straight-through scaled low-precision matmul ``[..., K] @ [K, M]``.

    Forward: quantized compute (``qdot``). Backward: the quantizers are
    treated as identity (STE) — gradients are the plain matmul's, taken
    against the full-precision residuals, so master weights keep
    full-precision updates while the forward pays low-precision compute
    and (inside the rings) low-precision communication.
    """
    return _qmm_fwd_impl(x, w, fmt)


def _qmm_fwd(x, w, fmt):
    return _qmm_fwd_impl(x, w, fmt), (x, w)


def _qmm_bwd(fmt, res, dy):
    x, w = res
    dims_dx = (((x.ndim - 1,), (1,)), ((), ()))
    dx = lax.dot_general(dy, w, dims_dx)  # dy @ w^T
    nb = x.ndim - 1
    dw = lax.dot_general(
        x, dy, ((tuple(range(nb)),) * 2, ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dx.astype(x.dtype), dw.astype(w.dtype)


quantized_matmul.defvjp(_qmm_fwd, _qmm_bwd)
