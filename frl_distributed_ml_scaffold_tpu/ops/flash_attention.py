"""Pallas TPU flash attention: fused blockwise causal attention kernel.

SURVEY §7 names the custom-kernel tier as the framework's "native" layer on
TPU (the CUDA-kernel equivalent). This is that tier's centerpiece: a
flash-attention forward + backward written directly against the Mosaic/TPU
pipeline via ``pl.pallas_call``:

- **Forward**: online-softmax with K/V streamed block-by-block through an
  inner grid dimension — VMEM residency is O(block·D), independent of T, so
  context length is bounded by HBM, not VMEM. The per-row logsumexp (a
  lane-1 (B, H, T, 1) array — the only extra HBM traffic) is saved for the
  backward. Running max/denominator/accumulator live in VMEM scratch that
  persists across the inner grid steps (TPU grids iterate sequentially).
- **Backward**: custom VJP with two kernels — one producing dQ (inner grid
  over K/V blocks), one producing dK/dV (inner grid over Q/dO blocks) — the
  flash-attention-2 split so each output block has a single writer. The row
  term ``delta = rowsum(dO·O)`` is computed in-VMEM from tiles already
  resident instead of being broadcast through HBM.
- **Causality**: blocks strictly above the diagonal skip their compute via
  ``pl.when`` (the MXU work — the dominant cost — is elided; only the
  block DMA is not).

Layout: kernels run in (B, H, T, D) — Mosaic requires the (sublane, lane)
pair to be the (T-block, D) tile — with the public API staying (B, T, H, D);
the wrapper's transposes fuse into the surrounding projection matmuls. All
matmuls run bf16-multiply/fp32-accumulate (``preferred_element_type``),
softmax math in fp32 — the same numerics contract as ``dense_attention``,
which the tests assert equivalence against.

On non-TPU backends the kernels run in Pallas interpreter mode so the CPU
test suite exercises the exact same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1.0e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


_warned: set[str] = set()


def _warn_fallback(msg: str) -> None:
    """Log each distinct fallback reason once — silent perf cliffs are the
    review-flagged failure mode; a log line per step would be the other."""
    if msg not in _warned:
        _warned.add(msg)
        from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

        get_logger().warning(msg)


#: T at and above which the auto block size steps up to 1024x1024
#: (tools/flash_sweep.py on-chip ladder, 2026-07-30: +21%/+37%/+39% over
#: 512x512 at T=16k/32k/64k).
_LONG_T_BLOCKS = 16384


def _pick_block(t: int, preferred: int) -> int | None:
    """Largest power-of-two block <= preferred that divides t.

    Only power-of-two candidates: anything else risks a sublane-misaligned
    tile that Mosaic rejects at compile time — untileable T falls back to
    dense attention instead.
    """
    for b in (1024, 512, 256, 128, 64, 32, 16, 8):
        if b <= preferred and t % b == 0:
            return b
    return None


def _causal_mask(s, i, j, block_q, block_k):
    qpos = i * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = j * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(qpos >= kpos, s, _NEG_INF)


def _dot(a, b, *, trans_b=False, trans_a=False):
    """MXU matmul, fp32 accumulate."""
    dims = (((0,) if trans_a else (1,), (1,) if trans_b else (0,)), ((), ()))
    return lax.dot_general(a, b, dimension_numbers=dims,
                           preferred_element_type=jnp.float32)


# --------------------------------------------------------------------- fwd


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                *, block_q, block_k, causal, scale):
    i, j = pl.program_id(2), pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Blocks strictly above the causal diagonal contribute nothing.
    live = (j * block_k <= (i + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0, 0, :, :]  # (Bq, D)
        k_blk = k_ref[0, 0, :, :]  # (Bk, D)
        v_blk = v_ref[0, 0, :, :]
        s = _dot(q, k_blk, trans_b=True) * scale  # (Bq, Bk)
        if causal:
            s = _causal_mask(s, i, j, block_q, block_k)
        m = m_ref[:]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + _dot(p.astype(v_blk.dtype), v_blk)

    @pl.when(j == n_k - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, 0, :, :] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = m_ref[:] + jnp.log(l_safe)


def _clamp_j(causal, block_q, block_k):
    """KV index map for causal grids: clamp j to the diagonal block so
    programs above the diagonal reference the block already resident —
    their compute is skipped by ``pl.when`` and no DMA fires."""
    if not causal:
        return lambda b_, h_, i, j: (b_, h_, j, 0)
    return lambda b_, h_, i, j: (
        b_, h_, jnp.minimum(j, ((i + 1) * block_q - 1) // block_k), 0
    )


def _clamp_i(causal, block_q, block_k):
    """Q-side index map for the dkv grid (outer j over K blocks): clamp i
    up to the first Q block that reaches the diagonal."""
    if not causal:
        return lambda b_, h_, j, i: (b_, h_, i, 0)
    return lambda b_, h_, j, i: (
        b_, h_, jnp.maximum(i, (j * block_k) // block_q), 0
    )


def _fwd(q, k, v, *, causal, block_q, block_k, interpret):
    """q, k, v in kernel layout (B, H, T, D)."""
    b, h, t, d = q.shape
    scale = 1.0 / np.sqrt(d)
    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d), _clamp_j(causal, block_q, block_k))
    lse_spec = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, scale=scale),
        grid=(b, h, t // block_q, t // block_k),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[q_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denom
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------- bwd


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref,
                   dq_acc_ref, delta_ref, *, block_q, block_k, causal, scale):
    i, j = pl.program_id(2), pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        o = o_ref[0, 0, :, :].astype(jnp.float32)
        delta_ref[:] = (do * o).sum(axis=-1, keepdims=True)  # (Bq, 1)

    live = (j * block_k <= (i + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0, 0, :, :]
        k_blk = k_ref[0, 0, :, :]
        v_blk = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :, :]  # (Bq, 1)
        s = _dot(q, k_blk, trans_b=True) * scale
        if causal:
            s = _causal_mask(s, i, j, block_q, block_k)
        p = jnp.exp(s - lse)  # exact probabilities — no rescaling needed
        dp = _dot(do, v_blk.astype(jnp.float32), trans_b=True)
        ds = p * (dp - delta_ref[:]) * scale
        dq_acc_ref[:] += _dot(ds.astype(k_blk.dtype), k_blk)

    @pl.when(j == n_k - 1)
    def _finish():
        dq_ref[0, 0, :, :] = dq_acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                    *, block_q, block_k, causal, scale):
    j, i = pl.program_id(2), pl.program_id(3)
    n_q = pl.num_programs(3)

    @pl.when(i == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    live = ((i + 1) * block_q - 1 >= j * block_k) if causal else True

    @pl.when(live)
    def _step():
        k_blk = k_ref[0, 0, :, :]  # (Bk, D)
        v_blk = v_ref[0, 0, :, :]
        q = q_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        o = o_ref[0, 0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :, :]
        delta = (do * o).sum(axis=-1, keepdims=True)  # (Bq, 1)
        s = _dot(q, k_blk, trans_b=True) * scale
        if causal:
            s = _causal_mask(s, i, j, block_q, block_k)
        p = jnp.exp(s - lse)  # (Bq, Bk)
        dv_acc_ref[:] += _dot(p, do, trans_a=True)
        dp = _dot(do, v_blk.astype(jnp.float32), trans_b=True)
        ds = p * (dp - delta) * scale  # (Bq, Bk)
        dk_acc_ref[:] += _dot(ds, q.astype(jnp.float32), trans_a=True)

    @pl.when(i == n_q - 1)
    def _finish():
        dk_ref[0, 0, :, :] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc_ref[:].astype(dv_ref.dtype)


def _bwd(causal, block_q, block_k, interpret, residuals, dout):
    q, k, v, o, lse = residuals
    b, h, t, d = q.shape
    scale = 1.0 / np.sqrt(d)
    n_q, n_k = t // block_q, t // block_k

    # dq: outer grid over Q blocks, inner over K/V blocks.
    qi_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kj_spec = pl.BlockSpec((1, 1, block_k, d), _clamp_j(causal, block_q, block_k))
    lse_i = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, scale=scale),
        grid=(b, h, n_q, n_k),
        in_specs=[qi_spec, kj_spec, kj_spec, qi_spec, qi_spec, lse_i],
        out_specs=qi_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),  # dq accumulator
            pltpu.VMEM((block_q, 1), jnp.float32),  # delta row term
        ],
        interpret=interpret,
    )(q, k, v, o, dout, lse)

    # dk/dv: outer grid over K blocks, inner over Q/dO blocks.
    qi2 = pl.BlockSpec((1, 1, block_q, d), _clamp_i(causal, block_q, block_k))
    kj2 = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, j, i: (b_, h_, j, 0))
    _ci = _clamp_i(causal, block_q, block_k)
    lse_i2 = pl.BlockSpec((1, 1, block_q, 1), _ci)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                          causal=causal, scale=scale),
        grid=(b, h, n_k, n_q),
        in_specs=[qi2, kj2, kj2, qi2, qi2, lse_i2],
        out_specs=[kj2, kj2],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),  # dk accumulator
            pltpu.VMEM((block_k, d), jnp.float32),  # dv accumulator
        ],
        interpret=interpret,
    )(q, k, v, o, dout, lse)
    return dq, dk, dv


# ----------------------------------------------------- block-level (ring)
#
# Mid-level API used by ring attention (ops/ring_attention.py): attention of
# a local Q block against ONE K/V block, exposing the per-row logsumexp so
# the caller can merge blocks (ring hops) exactly. The pallas kernels above
# already have precisely these semantics — ``_fwd`` returns (o, lse) and
# ``_bwd`` consumes the *global* lse (p = exp(s - lse) yields the exact
# probabilities for any sub-block once lse covers the full row) — so the
# ring's per-hop compute is the same fused kernel as single-device flash.
# Dense fallbacks (identical numerics, with lse) cover untileable shapes and
# non-TPU backends.


def _dense_fwd_lse(q, k, v, *, causal):
    """(B, H, Tq, D) x (B, H, Tk, D) -> (o, lse[B, H, Tq, 1]); fp32 softmax,
    bf16-multiply/fp32-accumulate matmuls — the kernel's numerics contract."""
    d = q.shape[-1]
    scale = 1.0 / np.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        qpos = lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        kpos = lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where((qpos >= kpos)[None, None], s, _NEG_INF)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.maximum(p.sum(axis=-1, keepdims=True), 1e-30)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return (acc / l).astype(q.dtype), m + jnp.log(l)


def _dense_bwd_lse(q, k, v, o, lse, do, *, causal):
    """Dense mirror of the pallas backward: exact p from the global lse."""
    d = q.shape[-1]
    scale = 1.0 / np.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        qpos = lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        kpos = lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where((qpos >= kpos)[None, None], s, _NEG_INF)
    p = jnp.exp(s - lse)  # masked entries: exp(-inf - lse) == 0
    do32 = do.astype(jnp.float32)
    delta = (do32 * o.astype(jnp.float32)).sum(axis=-1, keepdims=True)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, do32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", do32, v.astype(jnp.float32))
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds.astype(k.dtype), k,
                    preferred_element_type=jnp.float32)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _auto_block(t: int) -> int:
    """Length-adaptive preferred block (the measured v5e optimum): 512 at
    short T, 1024 from ``_LONG_T_BLOCKS`` up — shared by the public entry
    AND the ring/Ulysses per-hop kernels, whose local T is exactly the
    long-context regime the sweep measured."""
    return 1024 if t >= _LONG_T_BLOCKS else 512


def _block_tileable(q, k) -> tuple[int, int] | None:
    tq, tk, d = q.shape[2], k.shape[2], q.shape[3]
    if tq != tk or d % 32 != 0:
        return None
    bq = _pick_block(tq, min(_auto_block(tq), tq))
    bk = _pick_block(tk, min(_auto_block(tk), tk))
    return (bq, bk) if bq and bk else None


def _block_route(q, k, interpret):
    """(blocks, interpret) — blocks=None means take the dense path."""
    blocks = _block_tileable(q, k)
    if interpret is None:
        # Pallas interpreter mode is far slower than the identical-numerics
        # dense math — off-TPU it is opt-in (tests force interpret=True).
        if _interpret_default():
            return None, None
        interpret = False
    return blocks, interpret


def local_flash_attention(q, k, v, *, causal, interpret=None):
    """Differentiable fused attention on LOCAL (B, T, H, D) arrays — for
    callers already inside a shard_map region (Ulysses), where the public
    ``flash_attention`` wrapper's own shard_map must not re-wrap. Falls back
    to dense on untileable shapes / non-TPU, like the public entry point.
    """
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import dense_attention

    qT = q.transpose(0, 2, 1, 3)
    kT, vT = k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3)
    blocks, interpret = _block_route(qT, kT, interpret)
    if blocks is None:
        if not _interpret_default():
            # On TPU this is a real perf/memory cliff (O(T_local²) dense
            # instead of the fused kernel) — same warn-once contract as the
            # public wrapper. Off-TPU dense is the intended default.
            _warn_fallback(
                "local_flash_attention falling back to dense: shape "
                f"(T={q.shape[1]}, head_dim={q.shape[3]}) is not tileable"
            )
        return dense_attention(q, k, v, causal=causal)
    bq, bk = blocks
    return _flash(qT, kT, vT, causal, bq, bk, interpret).transpose(0, 2, 1, 3)


def block_attention_fwd(q, k, v, *, causal, interpret=None):
    """One-block attention in kernel layout (B, H, T, D) -> (o, lse).

    ``causal`` here means Q and K share a position origin (the ring's
    diagonal hop); off-diagonal hops pass ``causal=False``. Routes to the
    pallas kernel when the shapes tile (and the backend is TPU or
    ``interpret`` is forced), else to the identical-numerics dense path.
    """
    blocks, interpret = _block_route(q, k, interpret)
    if blocks is None:
        return _dense_fwd_lse(q, k, v, causal=causal)
    bq, bk = blocks
    return _fwd(q, k, v, causal=causal, block_q=bq, block_k=bk,
                interpret=interpret)


def block_attention_bwd(q, k, v, o, lse, do, *, causal, interpret=None):
    """Per-block gradients given the GLOBAL per-row lse -> (dq, dk, dv).

    Because ``p = exp(s - lse)`` with the row's full-sequence lse gives the
    exact attention probabilities restricted to this block, summing these
    per-block grads over all visible blocks reproduces the full-attention
    gradient — the identity the ring backward is built on.
    """
    blocks, interpret = _block_route(q, k, interpret)
    if blocks is None:
        return _dense_bwd_lse(q, k, v, o, lse, do, causal=causal)
    bq, bk = blocks
    return _bwd(causal, bq, bk, interpret, (q, k, v, o, lse), do)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    o, _ = _fwd(q, k, v, causal=causal, block_q=block_q, block_k=block_k,
                interpret=interpret)
    return o


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, causal=causal, block_q=block_q, block_k=block_k,
                  interpret=interpret)
    return o, (q, k, v, o, lse)


_flash.defvjp(_flash_fwd, _bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """(B, T, H, D) fused flash attention; drop-in for ``dense_attention``.

    Default blocks are the measured v5e optimum at LM shapes, and they are
    length-adaptive (``None`` = auto). At T=1024 ([4,1024,16,64] sweeps,
    2026-07-30): (512, 512) runs the fwd+bwd call ~20% faster than the
    previous (256, 256) — larger blocks amortize the VMEM revolving and
    keep the MXU fed — and (1024, 1024) measures equal within noise, so
    the smaller VMEM footprint wins at short T. At long T the balance
    flips: the on-chip ladder (tools/flash_sweep.py, 64k, 2026-07-30)
    measures (1024, 1024) at +21%/+37%/+39% over (512, 512) at
    T=16k/32k/64k (59.4 vs 42.6 TFLOPs at 64k), so auto selects
    1024x1024 from T>=16k. ``_pick_block`` clamps both to the sequence
    length so shorter/odd shapes still tile.

    Falls back to ``dense_attention`` when T doesn't tile (no power-of-two
    block divides it) or the head dim isn't sublane-aligned — the numerics
    contract is identical, so the fallback is silent by design.
    """
    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        BATCH_AXES,
        current_mesh_env,
    )
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import dense_attention

    # Seq-axis routing first, before any backend/tileability fallback, so
    # the behavior is identical on CPU simulation and real TPU: a flash call
    # under a sequence-sharded mesh delegates to ring attention, whose
    # per-hop compute is this very kernel (block_attention_fwd/_bwd below) —
    # flash + SP compose rather than conflict.
    from frl_distributed_ml_scaffold_tpu.ops.ring_attention import ring_attention

    env = current_mesh_env()
    if env is not None and env.axis_size("seq") > 1:
        return ring_attention(
            q, k, v, axis_name="seq", causal=causal, interpret=interpret
        )

    t, d = q.shape[1], q.shape[3]
    if block_q is None:
        block_q = _auto_block(t)
    if block_k is None:
        block_k = _auto_block(t)
    bq = _pick_block(t, min(block_q, t))
    bk = _pick_block(t, min(block_k, t))
    if bq is None or bk is None or d % 32 != 0:
        _warn_fallback(
            f"flash_attention falling back to dense: shape (T={t}, head_dim="
            f"{d}) is not tileable (need a power-of-two divisor of T and "
            f"head_dim % 32 == 0)"
        )
        return dense_attention(q, k, v, causal=causal)
    if interpret is None:
        if _interpret_default():
            # Pallas interpreter mode is orders of magnitude slower than the
            # identical-numerics dense path — only tests (which pass
            # interpret=True explicitly) should ever run it.
            _warn_fallback(
                "flash_attention falling back to dense on non-TPU backend "
                f"({jax.default_backend()}); pass interpret=True to force "
                "the Pallas interpreter"
            )
            return dense_attention(q, k, v, causal=causal)
        interpret = False

    def _call(q, k, v):
        # Kernel layout is (B, H, T, D); these transposes sit against the
        # QKV projection reshapes and fuse in XLA.
        qT, kT, vT = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        o = _flash(qT, kT, vT, causal, bq, bk, interpret)
        return o.transpose(0, 2, 1, 3)

    if env is None:
        return _call(q, k, v)
    # Under a mesh, GSPMD cannot partition an opaque pallas_call — an
    # unwrapped kernel would silently all-gather and run replicated. Flash
    # attention is independent per (batch, head), so shard_map over the
    # batch axes and the TP head axis keeps it fully local (same mechanism
    # as the ring/Ulysses siblings). Sequence sharding is ring attention's
    # job, not this kernel's (validated above).
    from frl_distributed_ml_scaffold_tpu.dist.mesh import shard_map_compat

    spec = jax.sharding.PartitionSpec(BATCH_AXES, None, "model", None)
    return shard_map_compat(
        _call,
        mesh=env.mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)
