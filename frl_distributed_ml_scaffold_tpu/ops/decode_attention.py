"""Pallas TPU flash-decode attention: fused split-KV single-token decode.

The serving-side sibling of ``ops/flash_attention.py``. Training attention
streams K/V blocks under a [T, T] score tile; at decode the query is ONE
token per sequence, so the kernel shape flips: scores are a [H, S] strip
and the win is (i) never materializing the [B, H, S] probability tensor in
HBM and (ii) never *reading* cache rows past the occupied prefix. The
kernel is a split-KV partial-softmax: the cache length S is tiled into
``block_k`` chunks walked by the inner grid dimension (TPU grids iterate
sequentially, so the running max / denominator / accumulator live in VMEM
scratch and the chunk merge is the standard online-softmax log-sum-exp
rescale — numerically the same merge the flash kernel and the ring hops
use).

Length masking is first-class, not an afterthought: the per-row occupancy
``kv_len`` rides the scalar-prefetch channel (``PrefetchScalarGridSpec``),
so it is available to the *index maps* — chunks entirely past a row's
occupancy clamp their DMA to the last live chunk and skip their compute via
``pl.when``. A bucketed cache (serving/engine.py) bounds the worst case;
the length clamp means a request at occupancy 70 in a 512-bucket reads ~70
rows of cache, not 512 and not ``config.seq_len``.

Decode is inference-only, so there is no VJP — the kernel is forward-only,
which also keeps the router trivially compatible with ``lax.scan`` decode
loops.

Layout: public API is cache layout — q ``[B, H, D]`` (the single token's
heads), k/v ``[B, S, H, D]`` (exactly how models/gpt.py stores the cache),
``kv_len [B]`` int32. The kernel internally runs ``[B, H, S, D]`` like its
training sibling.

On non-TPU backends the kernel runs under the Pallas interpreter when
``interpret=True`` is forced (tests); the default off-TPU path is the
identical-numerics ``dense_decode_attention`` — the same silent-fallback
contract as ``flash_attention`` / ``fused_bn``.

Quantized KV cache (``model.kv_cache_quant``, ROADMAP item 5): decode is
HBM-bandwidth-bound and the cache is what it reads, so K/V may arrive
here quantized — 1-byte elements (int8 / fp8, ops/quantization.py) plus
per-(row, position, head) scales. The kernel dequantizes PER SPLIT-KV
CHUNK in VMEM: the int8 chunk is upcast in-register and the scale folds
into the score strip after the dot (scale-per-position factors out of
the contraction over head_dim), so the full-precision cache never exists
in HBM — not at ``[B, S, H, D]``, not per step. The dense fallback keeps
the same property by streaming bounded chunks through an online-softmax
``lax.scan`` (``dense_decode_attention_quant``); graft-lint pins that no
wide-dtype cache-shaped intermediate materializes in a quantized decode
step.

Paged KV cache (ISSUE 10, ROADMAP item 1): the serving engine stores K/V
in a fixed POOL of fixed-size blocks ``[N, bs, H, D]`` shared by every
slot, with a per-row block table ``[B, M]`` mapping each row's logical
block j to a physical pool block (serving/engine.py owns allocation,
refcounts, and shared-prefix reuse). ``paged_decode_attention`` extends
the split-KV kernel through the SAME scalar-prefetch path: the block
table rides the prefetch channel next to the per-row lengths, so the
K/V index maps gather block-by-block — chunk j of row b DMAs pool block
``table[b, j]``, clamped to the row's last occupied block exactly like
the contiguous kernel clamps its chunk index. Nothing is ever gathered
into a contiguous logical view: the dense fallback streams bounded
``[B, bs, H, D]`` chunks (one ``jnp.take`` per table column) through the
same online-softmax ``lax.scan``, so no full-``seq_len`` array — and no
pool-sized copy — materializes per step (graft-lint's paged decode
program pins both).

Speculative verify tile (ISSUE 11): speculative decoding proposes k
draft tokens per row and the TARGET model scores all k+1 positions in
one batched forward — the whole point is that the pool read (the
bandwidth bill decode pays) is amortized over k+1 query positions
instead of one. ``paged_verify_attention`` extends the paged kernel
from q_len=1 to a small q TILE ``[B, T, H, D]`` with causal masking
inside the chunk loop: query position t of a row whose total occupancy
(tile included) is ``kv_len`` attends logical positions
``< kv_len - T + 1 + t`` — position 0 sees exactly what a single-token
decode step would, each later draft position additionally sees the
drafts before it. Same scalar-prefetch block-table gather, same
online-softmax merge, same streamed-bounded-chunk dense fallback
(``dense_paged_verify_attention``) — contract-identical off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.ops.flash_attention import (
    _pick_block,
    _warn_fallback,
)

_NEG_INF = -1.0e30

#: Test hook (the ``fused_bn.FORCE_INTERPRET`` pattern): set to True to
#: force the Pallas interpreter through model-level entry points that do
#: not expose an ``interpret`` argument.
FORCE_INTERPRET: bool | None = None


def dense_decode_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, kv_len: jax.Array
) -> jax.Array:
    """Reference decode attention: q ``[B, H, D]`` against the cache
    ``[B, S, H, D]``, keys at positions >= ``kv_len[b]`` masked out. fp32
    softmax, bf16-multiply/fp32-accumulate — the numerics contract the
    kernel is gated against (and the same contract as
    ``_masked_dense_attention`` in models/gpt.py)."""
    d = q.shape[-1]
    s = jnp.einsum("bhd,bshd->bhs", q, k, preferred_element_type=jnp.float32)
    s = s / jnp.sqrt(jnp.asarray(d, jnp.float32))
    kpos = jnp.arange(k.shape[1])
    mask = kpos[None, :] < kv_len[:, None]  # [B, S]
    s = jnp.where(mask[:, None, :], s, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum(
        "bhs,bshd->bhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return o.astype(q.dtype)


def dense_decode_attention_quant(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_len: jax.Array,
    k_scale: jax.Array,
    v_scale: jax.Array,
    *,
    block: int | None = None,
) -> jax.Array:
    """Reference decode attention over a QUANTIZED cache: k/v are 1-byte
    ``[B, S, H, D]`` payloads with ``[B, S, H]`` scales.

    Deliberately NOT "dequantize the cache, call the dense reference":
    that materializes a full-precision cache-sized tensor every decode
    step — exactly the allocation the quantized cache exists to avoid
    (and the graft-lint mutation gate for it). Instead the cache streams
    through an online-softmax ``lax.scan`` in chunks of ``block``
    positions: each iteration dequantizes one bounded ``[B, block, H, D]``
    chunk, folds the per-position scales into the score strip / the
    probability row, and merges with the standard log-sum-exp rescale —
    the same merge the Pallas kernel and the flash kernels use, in plain
    XLA. fp32 softmax throughout (the decode numerics contract).
    """
    b, s, h, d = k.shape
    if block is None:
        # Largest power-of-two divisor of S capped at min(64, S/2): the
        # cap at S/2 keeps the dequantized chunk STRICTLY smaller than
        # the bucket at every size, so the "no wide cache-geometry
        # intermediate" pin holds even for the smallest buckets.
        cap = min(64, max(1, s // 2))
        block = next(
            c for c in (64, 32, 16, 8, 4, 2, 1) if c <= cap and s % c == 0
        )
    n = s // block
    q32 = q.astype(jnp.float32)
    inv = 1.0 / np.sqrt(d)
    # [n, B, block, H, ...] chunk stacks (1-byte reshapes — no widening).
    kc = jnp.moveaxis(k.reshape(b, n, block, h, d), 1, 0)
    vc = jnp.moveaxis(v.reshape(b, n, block, h, d), 1, 0)
    ksc = jnp.moveaxis(
        k_scale.astype(jnp.float32).reshape(b, n, block, h), 1, 0
    )
    vsc = jnp.moveaxis(
        v_scale.astype(jnp.float32).reshape(b, n, block, h), 1, 0
    )

    def step(carry, xs):
        m, l, acc, j = carry
        k_q, k_s, v_q, v_s = xs
        k_f = k_q.astype(jnp.float32)  # [B, block, H, D] — bounded
        sc = jnp.einsum("bhd,bchd->bhc", q32, k_f)
        sc = sc * jnp.moveaxis(k_s, 1, 2) * inv  # scale per (b, h, pos)
        kpos = j * block + jnp.arange(block)
        mask = kpos[None, None, :] < kv_len[:, None, None]
        sc = jnp.where(mask, sc, _NEG_INF)
        m_new = jnp.maximum(m, sc.max(axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(sc - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        pv = p * jnp.moveaxis(v_s, 1, 2)  # fold v scales into the probs
        acc = acc * alpha + jnp.einsum(
            "bhc,bchd->bhd", pv, v_q.astype(jnp.float32)
        )
        return (m_new, l, acc, j + 1), None

    carry0 = (
        jnp.full((b, h, 1), _NEG_INF, jnp.float32),
        jnp.zeros((b, h, 1), jnp.float32),
        jnp.zeros((b, h, d), jnp.float32),
        jnp.int32(0),
    )
    (m, l, acc, _), _ = jax.lax.scan(step, carry0, (kc, ksc, vc, vsc))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def dense_paged_decode_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    kv_len: jax.Array,
    block_tables: jax.Array,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """Reference decode attention over a PAGED cache: q ``[B, H, D]``
    against pool blocks ``[N, bs, H, D]`` addressed through per-row block
    tables ``[B, M]`` (row b's logical positions ``[j*bs, (j+1)*bs)``
    live in pool block ``block_tables[b, j]``), keys at logical positions
    >= ``kv_len[b]`` masked out. With ``k_scale``/``v_scale``
    (``[N, bs, H]``) the pool is quantized and the scales fold into the
    score strip / probability row per chunk.

    Deliberately NOT "gather the logical cache, call the contiguous
    reference": that materializes an ``M*bs >= seq_len``-wide tensor
    every decode step — exactly the full-context array the block pool
    exists to avoid (and the graft-lint mutation gate for the paged
    program). Instead the table columns stream through an online-softmax
    ``lax.scan``: each iteration gathers ONE bounded ``[B, bs, H, D]``
    block per row (``jnp.take`` on the physical ids — gather at the
    boundary, the arXiv 2112.01075 discipline) and merges with the
    standard log-sum-exp rescale. fp32 softmax throughout (the decode
    numerics contract)."""
    _, bs, h, d = k_pool.shape
    quant = k_scale is not None
    q32 = q.astype(jnp.float32)
    inv = 1.0 / np.sqrt(d)
    cols = block_tables.astype(jnp.int32).T  # [M, B] physical ids per step

    def step(carry, phys):
        m, l, acc, j = carry
        k_c = jnp.take(k_pool, phys, axis=0)  # [B, bs, H, D] — bounded
        v_c = jnp.take(v_pool, phys, axis=0)
        sc = jnp.einsum(
            "bhd,bchd->bhc", q32, k_c.astype(jnp.float32)
        )
        if quant:
            k_s = jnp.take(k_scale, phys, axis=0).astype(jnp.float32)
            sc = sc * jnp.moveaxis(k_s, 1, 2)  # scale per (b, h, pos)
        sc = sc * inv
        kpos = j * bs + jnp.arange(bs)
        mask = kpos[None, None, :] < kv_len[:, None, None]
        sc = jnp.where(mask, sc, _NEG_INF)
        m_new = jnp.maximum(m, sc.max(axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(sc - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        if quant:
            v_s = jnp.take(v_scale, phys, axis=0).astype(jnp.float32)
            p = p * jnp.moveaxis(v_s, 1, 2)  # fold v scales into the probs
        acc = acc * alpha + jnp.einsum(
            "bhc,bchd->bhd", p, v_c.astype(jnp.float32)
        )
        return (m_new, l, acc, j + 1), None

    b = q.shape[0]
    carry0 = (
        jnp.full((b, h, 1), _NEG_INF, jnp.float32),
        jnp.zeros((b, h, 1), jnp.float32),
        jnp.zeros((b, h, d), jnp.float32),
        jnp.int32(0),
    )
    (m, l, acc, _), _ = jax.lax.scan(step, carry0, cols)
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def dense_paged_verify_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    kv_len: jax.Array,
    block_tables: jax.Array,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """Reference VERIFY-TILE attention over a paged cache (ISSUE 11):
    q ``[B, T, H, D]`` — the row's last accepted token plus T-1 draft
    tokens, whose K/V have already been written into the pool at logical
    positions ``kv_len - T .. kv_len - 1`` — against pool blocks
    addressed through the block tables. CAUSAL inside the tile: query t
    attends logical positions ``< kv_len - T + 1 + t``, so position 0
    scores exactly like a single-token decode step and each draft
    position additionally sees the drafts before it.

    Streams one bounded ``[B, bs, H, D]`` block per table column through
    the same online-softmax ``lax.scan`` as the q_len=1 reference — the
    no-logical-view contract is unchanged; the tile only widens the
    score strip to ``[B, H, T, bs]``. fp32 softmax throughout."""
    _, bs, h, d = k_pool.shape
    b, t, _, _ = q.shape
    quant = k_scale is not None
    q32 = q.astype(jnp.float32)
    inv = 1.0 / np.sqrt(d)
    cols = block_tables.astype(jnp.int32).T  # [M, B] physical ids per step
    # Per-(row, query) occupancy: query t of row b covers base[b] + t.
    base = kv_len.astype(jnp.int32) - (t - 1)  # length at query 0
    qlen = base[:, None] + jnp.arange(t)[None, :]  # [B, T]

    def step(carry, phys):
        m, l, acc, j = carry
        k_c = jnp.take(k_pool, phys, axis=0)  # [B, bs, H, D] — bounded
        v_c = jnp.take(v_pool, phys, axis=0)
        sc = jnp.einsum(
            "bthd,bchd->bhtc", q32, k_c.astype(jnp.float32)
        )  # [B, H, T, bs]
        if quant:
            k_s = jnp.take(k_scale, phys, axis=0).astype(jnp.float32)
            sc = sc * jnp.transpose(k_s, (0, 2, 1))[:, :, None, :]
        sc = sc * inv
        kpos = j * bs + jnp.arange(bs)
        mask = kpos[None, None, None, :] < qlen[:, None, :, None]
        sc = jnp.where(mask, sc, _NEG_INF)
        m_new = jnp.maximum(m, sc.max(axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(sc - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        if quant:
            v_s = jnp.take(v_scale, phys, axis=0).astype(jnp.float32)
            p = p * jnp.transpose(v_s, (0, 2, 1))[:, :, None, :]
        acc = acc * alpha + jnp.einsum(
            "bhtc,bchd->bhtd", p, v_c.astype(jnp.float32)
        )
        return (m_new, l, acc, j + 1), None

    carry0 = (
        jnp.full((b, h, t, 1), _NEG_INF, jnp.float32),
        jnp.zeros((b, h, t, 1), jnp.float32),
        jnp.zeros((b, h, t, d), jnp.float32),
        jnp.int32(0),
    )
    (m, l, acc, _), _ = jax.lax.scan(step, carry0, cols)
    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)  # [B, H, T, D]
    return jnp.swapaxes(out, 1, 2)  # [B, T, H, D]


# ------------------------------------------------------------------ kernel


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                   acc_ref, *, block_k, scale):
    """One (batch row, KV chunk) program: all H heads at once, so the
    sublane dimension of every tile is H (scores are [H, block_k])."""
    b_, j = pl.program_id(0), pl.program_id(1)
    n_k = pl.num_programs(1)
    length = len_ref[b_]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Chunks entirely past this row's occupancy contribute nothing (their
    # DMA is clamped to the last live chunk by the index map below).
    @pl.when(j * block_k < length)
    def _step():
        q = q_ref[0, :, 0, :]  # (H, D)
        k_blk = k_ref[0]  # (H, Bk, D)
        v_blk = v_ref[0]
        # Batched-over-heads matvec on the MXU: (H, D) x (H, Bk, D) -> (H, Bk).
        s = lax.dot_general(
            q, k_blk,
            dimension_numbers=(((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        kpos = j * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < length, s, _NEG_INF)
        m = m_ref[:]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + p.sum(axis=-1, keepdims=True)
        # (H, Bk) x (H, Bk, D) -> (H, D), batched over H.
        acc_ref[:] = acc_ref[:] * alpha + lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            dimension_numbers=(((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_k - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, :, 0, :] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _decode_kernel_quant(len_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref,
                         o_ref, m_ref, l_ref, acc_ref, *, block_k, scale):
    """Quantized-cache sibling of ``_decode_kernel``: k/v arrive as 1-byte
    payloads with per-(head, position) scales. The chunk dequantizes IN
    VMEM — the payload upcasts in-register for the dot and the scale
    folds into the score strip / probability row afterwards (it factors
    out of the head_dim contraction), so no full-precision cache chunk
    ever round-trips through HBM."""
    b_, j = pl.program_id(0), pl.program_id(1)
    n_k = pl.num_programs(1)
    length = len_ref[b_]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(j * block_k < length)
    def _step():
        q = q_ref[0, :, 0, :].astype(jnp.float32)  # (H, D)
        k_blk = k_ref[0].astype(jnp.float32)  # (H, Bk, D) — VMEM upcast
        v_blk = v_ref[0].astype(jnp.float32)
        k_s = ks_ref[0]  # (H, Bk) fp32 scales
        v_s = vs_ref[0]
        s = lax.dot_general(
            q, k_blk,
            dimension_numbers=(((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * k_s * scale
        kpos = j * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < length, s, _NEG_INF)
        m = m_ref[:]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + lax.dot_general(
            p * v_s, v_blk,
            dimension_numbers=(((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_k - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, :, 0, :] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _paged_decode_kernel(len_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, block_k, scale):
    """Paged sibling of ``_decode_kernel``: one (batch row, logical
    block) program. The block table is consumed by the INDEX MAPS (it
    rides the scalar-prefetch channel, so the physical block id is known
    before the body runs and the DMA fetches pool block
    ``tbl_ref[b, j]`` directly); the body itself only needs the length
    mask — pool blocks arrive in their storage layout ``(bs, H, D)``, so
    the dots batch over the MIDDLE heads dim instead of transposing the
    pool."""
    b_, j = pl.program_id(0), pl.program_id(1)
    n_k = pl.num_programs(1)
    length = len_ref[b_]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(j * block_k < length)
    def _step():
        q = q_ref[0]  # (H, D)
        k_blk = k_ref[0]  # (Bk, H, D) — pool-block storage layout
        v_blk = v_ref[0]
        # (H, D) x (Bk, H, D) -> (H, Bk): batch over H (rhs dim 1).
        s = lax.dot_general(
            q, k_blk,
            dimension_numbers=(((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * scale
        kpos = j * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < length, s, _NEG_INF)
        m = m_ref[:]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + p.sum(axis=-1, keepdims=True)
        # (H, Bk) x (Bk, H, D) -> (H, D): batch over H, contract Bk.
        acc_ref[:] = acc_ref[:] * alpha + lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            dimension_numbers=(((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_k - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _paged_decode_kernel_quant(len_ref, tbl_ref, q_ref, k_ref, ks_ref,
                               v_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref,
                               *, block_k, scale):
    """Quantized-pool sibling: 1-byte blocks upcast in VMEM, per-(pos,
    head) scales fold into the score strip / probability row after the
    dots — same per-chunk dequantize contract as ``_decode_kernel_quant``,
    addressed through the block table."""
    b_, j = pl.program_id(0), pl.program_id(1)
    n_k = pl.num_programs(1)
    length = len_ref[b_]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(j * block_k < length)
    def _step():
        q = q_ref[0].astype(jnp.float32)  # (H, D)
        k_blk = k_ref[0].astype(jnp.float32)  # (Bk, H, D) — VMEM upcast
        v_blk = v_ref[0].astype(jnp.float32)
        k_s = ks_ref[0]  # (Bk, H) fp32 scales
        v_s = vs_ref[0]
        s = lax.dot_general(
            q, k_blk,
            dimension_numbers=(((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * jnp.swapaxes(k_s, 0, 1) * scale
        kpos = j * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < length, s, _NEG_INF)
        m = m_ref[:]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + lax.dot_general(
            p * jnp.swapaxes(v_s, 0, 1), v_blk,
            dimension_numbers=(((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_k - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _paged_verify_kernel(len_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, block_k, q_len, scale):
    """Verify-tile sibling of ``_paged_decode_kernel`` (ISSUE 11): the
    query is a small [T, H, D] tile, scores widen to [H, T, Bk], and the
    causal mask is applied INSIDE the chunk loop — query t of a row at
    total occupancy ``len_ref[b]`` admits keys at logical positions
    ``< len - (T-1) + t``. Running max/denominator/accumulator carry the
    extra T dim in VMEM scratch; the block-table DMA gather is the same
    scalar-prefetch index map as the q_len=1 kernel."""
    b_, j = pl.program_id(0), pl.program_id(1)
    n_k = pl.num_programs(1)
    length = len_ref[b_]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(j * block_k < length)
    def _step():
        q = q_ref[0]  # (T, H, D)
        k_blk = k_ref[0]  # (Bk, H, D) — pool-block storage layout
        v_blk = v_ref[0]
        # (T, H, D) x (Bk, H, D) -> (H, T, Bk): batch over H, contract D.
        s = lax.dot_general(
            q, k_blk,
            dimension_numbers=(((2,), (2,)), ((1,), (1,))),
            preferred_element_type=jnp.float32,
        ) * scale
        kpos = j * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        tpos = lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < length - (q_len - 1) + tpos, s, _NEG_INF)
        m = m_ref[:]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + p.sum(axis=-1, keepdims=True)
        # (H, T, Bk) x (Bk, H, D) -> (H, T, D): batch H, contract Bk.
        acc_ref[:] = acc_ref[:] * alpha + lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            dimension_numbers=(((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_k - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = jnp.swapaxes(acc_ref[:] / l_safe, 0, 1).astype(
            o_ref.dtype
        )


def _paged_verify_kernel_quant(len_ref, tbl_ref, q_ref, k_ref, ks_ref,
                               v_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref,
                               *, block_k, q_len, scale):
    """Quantized-pool verify tile: 1-byte blocks upcast in VMEM, the
    per-(position, head) scales fold into the [H, T, Bk] score strip /
    probability rows after the dots — the ``_paged_decode_kernel_quant``
    contract with the tile's causal mask composed on top."""
    b_, j = pl.program_id(0), pl.program_id(1)
    n_k = pl.num_programs(1)
    length = len_ref[b_]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(j * block_k < length)
    def _step():
        q = q_ref[0].astype(jnp.float32)  # (T, H, D)
        k_blk = k_ref[0].astype(jnp.float32)  # (Bk, H, D) — VMEM upcast
        v_blk = v_ref[0].astype(jnp.float32)
        k_s = ks_ref[0]  # (Bk, H) fp32 scales
        v_s = vs_ref[0]
        s = lax.dot_general(
            q, k_blk,
            dimension_numbers=(((2,), (2,)), ((1,), (1,))),
            preferred_element_type=jnp.float32,
        ) * jnp.swapaxes(k_s, 0, 1)[:, None, :] * scale
        kpos = j * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        tpos = lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < length - (q_len - 1) + tpos, s, _NEG_INF)
        m = m_ref[:]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + lax.dot_general(
            p * jnp.swapaxes(v_s, 0, 1)[:, None, :], v_blk,
            dimension_numbers=(((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_k - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = jnp.swapaxes(acc_ref[:] / l_safe, 0, 1).astype(
            o_ref.dtype
        )


def _kv_index_map(block_k):
    """Clamp the chunk index to the row's last OCCUPIED chunk: programs
    past the occupancy re-reference the chunk already resident, so no DMA
    fires for dead cache rows (their compute is skipped by ``pl.when``).
    The scalar-prefetch channel is what makes the length visible here,
    before the kernel body runs."""

    def index_map(b_, j, len_ref):
        last = jnp.maximum((len_ref[b_] - 1) // block_k, 0)
        return (b_, 0, jnp.minimum(j, last), 0)

    return index_map


def _kv_scale_index_map(block_k):
    """The scale arrays' ([B, H, S]-layout) twin of ``_kv_index_map``."""

    def index_map(b_, j, len_ref):
        last = jnp.maximum((len_ref[b_] - 1) // block_k, 0)
        return (b_, 0, jnp.minimum(j, last))

    return index_map


def _flash_decode(q, k, v, kv_len, *, block_k, interpret):
    """q ``[B, H, 1, D]``, k/v ``[B, H, S, D]`` (kernel layout), kv_len
    ``[B]`` int32 -> ``[B, H, 1, D]``."""
    b, h, s, d = k.shape
    n_k = s // block_k
    q_spec = pl.BlockSpec((1, h, 1, d), lambda b_, j, len_ref: (b_, 0, 0, 0))
    kv_spec = pl.BlockSpec((1, h, block_k, d), _kv_index_map(block_k))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, n_k),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),  # running max
            pltpu.VMEM((h, 1), jnp.float32),  # running denom
            pltpu.VMEM((h, d), jnp.float32),  # output accumulator
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _decode_kernel, block_k=block_k, scale=1.0 / np.sqrt(d)
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(kv_len, q, k, v)


def _flash_decode_quant(q, k, k_scale, v, v_scale, kv_len, *, block_k,
                        interpret):
    """Quantized-cache split-KV decode: q ``[B, H, 1, D]`` float, k/v
    ``[B, H, S, D]`` 1-byte payloads, scales ``[B, H, S]`` fp32."""
    b, h, s, d = k.shape
    n_k = s // block_k
    q_spec = pl.BlockSpec((1, h, 1, d), lambda b_, j, len_ref: (b_, 0, 0, 0))
    kv_spec = pl.BlockSpec((1, h, block_k, d), _kv_index_map(block_k))
    sc_spec = pl.BlockSpec((1, h, block_k), _kv_scale_index_map(block_k))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, n_k),
        in_specs=[q_spec, kv_spec, sc_spec, kv_spec, sc_spec],
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),  # running max
            pltpu.VMEM((h, 1), jnp.float32),  # running denom
            pltpu.VMEM((h, d), jnp.float32),  # output accumulator
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _decode_kernel_quant, block_k=block_k, scale=1.0 / np.sqrt(d)
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(kv_len, q, k, k_scale, v, v_scale)


def _paged_kv_index_map(block_k):
    """The block-table gather: logical block j of row b DMAs POOL block
    ``tbl_ref[b, j]``. Blocks entirely past the row's occupancy re-
    reference the last occupied block (their compute is skipped by
    ``pl.when``) — the same clamp discipline as ``_kv_index_map``, with
    the table lookup composed on top. Both the lengths and the table
    ride the scalar-prefetch channel, so the physical id is available
    to the DMA before the kernel body runs."""

    def index_map(b_, j, len_ref, tbl_ref):
        last = jnp.maximum((len_ref[b_] - 1) // block_k, 0)
        jj = jnp.minimum(j, last)
        return (tbl_ref[b_, jj], 0, 0, 0)

    return index_map


def _paged_scale_index_map(block_k):
    """The ``[N, bs, H]`` scale pools' twin of ``_paged_kv_index_map``."""

    def index_map(b_, j, len_ref, tbl_ref):
        last = jnp.maximum((len_ref[b_] - 1) // block_k, 0)
        jj = jnp.minimum(j, last)
        return (tbl_ref[b_, jj], 0, 0)

    return index_map


def _flash_paged_decode(q, k_pool, v_pool, kv_len, tables, *, interpret,
                        k_scale=None, v_scale=None):
    """q ``[B, H, D]``, pools ``[N, bs, H, D]`` (+ optional ``[N, bs, H]``
    fp32 scales), tables ``[B, M]`` int32 -> ``[B, H, D]``. Grid is
    (rows, logical blocks); block_k == the pool's block size."""
    b, h, d = q.shape
    _, bs, _, _ = k_pool.shape
    n_k = tables.shape[1]
    q_spec = pl.BlockSpec((1, h, d), lambda b_, j, *_refs: (b_, 0, 0))
    kv_spec = pl.BlockSpec((1, bs, h, d), _paged_kv_index_map(bs))
    scratch = [
        pltpu.VMEM((h, 1), jnp.float32),  # running max
        pltpu.VMEM((h, 1), jnp.float32),  # running denom
        pltpu.VMEM((h, d), jnp.float32),  # output accumulator
    ]
    if k_scale is None:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, n_k),
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=q_spec,
            scratch_shapes=scratch,
        )
        return pl.pallas_call(
            functools.partial(
                _paged_decode_kernel, block_k=bs, scale=1.0 / np.sqrt(d)
            ),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=interpret,
        )(kv_len, tables, q, k_pool, v_pool)
    sc_spec = pl.BlockSpec((1, bs, h), _paged_scale_index_map(bs))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_k),
        in_specs=[q_spec, kv_spec, sc_spec, kv_spec, sc_spec],
        out_specs=q_spec,
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        functools.partial(
            _paged_decode_kernel_quant, block_k=bs, scale=1.0 / np.sqrt(d)
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(kv_len, tables, q, k_pool, k_scale, v_pool, v_scale)


# ------------------------------------------------------------------ router


#: Preferred KV chunk: decode is HBM-bandwidth-bound, so the chunk only has
#: to be big enough to amortize the revolving-buffer DMA; 512 matches the
#: short-T training block. The on-chip ladder is queued (BACKLOG R8-1).
_PREFERRED_BLOCK_K = 512


def _local_decode(q, k, v, kv_len, *, impl, interpret, k_scale=None,
                  v_scale=None):
    """Decode attention on LOCAL (already per-shard) arrays; with
    ``k_scale``/``v_scale`` present the cache is quantized and every
    branch takes its chunk-dequantizing twin."""
    quant = k_scale is not None

    def dense():
        if quant:
            return dense_decode_attention_quant(
                q, k, v, kv_len, k_scale, v_scale
            )
        return dense_decode_attention(q, k, v, kv_len)

    if impl == "dense":
        return dense()
    if impl != "flash":
        raise KeyError(
            f"unknown decode_attention impl {impl!r} (dense | flash)"
        )
    if interpret is None:
        interpret = FORCE_INTERPRET
    s, d = k.shape[1], q.shape[-1]
    block_k = _pick_block(s, min(_PREFERRED_BLOCK_K, s))
    if block_k is None or d % 32 != 0:
        if jax.default_backend() == "tpu":
            _warn_fallback(
                "flash-decode falling back to dense: cache shape "
                f"(S={s}, head_dim={d}) is not tileable (need a "
                "power-of-two divisor of S and head_dim % 32 == 0)"
            )
        return dense()
    if interpret is None:
        if jax.default_backend() != "tpu":
            # Identical numerics, no interpreter slowdown — the same
            # silent off-TPU contract as flash_attention.
            return dense()
        interpret = False
    qT = q[:, :, None, :]  # [B, H, 1, D]
    kT = k.transpose(0, 2, 1, 3)  # [B, H, S, D]
    vT = v.transpose(0, 2, 1, 3)
    lens = jnp.maximum(kv_len.astype(jnp.int32), 1)
    if quant:
        o = _flash_decode_quant(
            qT, kT, k_scale.astype(jnp.float32).transpose(0, 2, 1),
            vT, v_scale.astype(jnp.float32).transpose(0, 2, 1),
            lens, block_k=block_k, interpret=interpret,
        )
    else:
        o = _flash_decode(
            qT, kT, vT, lens, block_k=block_k, interpret=interpret
        )
    return o[:, :, 0, :]


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_len: jax.Array,
    *,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    impl: str = "flash",
    interpret: bool | None = None,
) -> jax.Array:
    """Single-token decode attention over a KV cache — the ONE entry point
    every decode consumer (generate, beam_search, serving/engine.py) routes
    through.

    q ``[B, H, D]``, k/v ``[B, S, H, D]`` (cache layout), ``kv_len [B]``
    int32 occupancy per row. With ``k_scale``/``v_scale`` (``[B, S, H]``,
    both or neither) the cache is QUANTIZED (1-byte k/v payloads,
    ``model.kv_cache_quant``) and every branch dequantizes per chunk —
    module docstring. Under a mesh whose ``model`` axis is live the
    call runs head-sharded via shard_map (GSPMD cannot partition an opaque
    pallas_call, and even the dense path benefits from a pinned layout):
    each shard attends its local heads against its local cache shard —
    zero collectives here; the one psum per block happens where Megatron
    puts it, in the row-sharded ``out`` projection that consumes this
    output. The batch dimension shards over the batch axes exactly when
    the cache constraint does (``_constrain_kv_cache``): the two MUST
    agree, or entering this region would all-gather the cache's batch
    shards — the monolithic reshard the handoff pin forbids. The scale
    arrays shard like the cache (heads over ``model``) for the same
    reason.
    """
    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        BATCH_AXES,
        current_mesh_env,
        shard_map_compat,
    )

    if (k_scale is None) != (v_scale is None):
        raise ValueError(
            "k_scale and v_scale must be passed together (a quantized "
            "cache quantizes both of its halves)"
        )
    env = current_mesh_env()
    m = env.axis_size("model") if env is not None else 1
    h = q.shape[1]
    if env is None or m <= 1 or h % m != 0:
        return _local_decode(
            q, k, v, kv_len, impl=impl, interpret=interpret,
            k_scale=k_scale, v_scale=v_scale,
        )
    batch = BATCH_AXES if q.shape[0] % env.batch_axis_size == 0 else None
    q_spec = P(batch, "model", None)
    kv_spec = P(batch, None, "model", None)
    if k_scale is None:
        fn = shard_map_compat(
            functools.partial(_local_decode, impl=impl, interpret=interpret),
            mesh=env.mesh,
            in_specs=(q_spec, kv_spec, kv_spec, P(batch)),
            out_specs=q_spec,
        )
        return fn(q, k, v, kv_len)
    sc_spec = P(batch, None, "model")
    fn = shard_map_compat(
        lambda q_, k_, v_, l_, ks_, vs_: _local_decode(
            q_, k_, v_, l_, impl=impl, interpret=interpret,
            k_scale=ks_, v_scale=vs_,
        ),
        mesh=env.mesh,
        in_specs=(q_spec, kv_spec, kv_spec, P(batch), sc_spec, sc_spec),
        out_specs=q_spec,
    )
    return fn(q, k, v, kv_len, k_scale, v_scale)


def _local_paged_decode(q, k_pool, v_pool, kv_len, tables, *, impl,
                        interpret, k_scale=None, v_scale=None):
    """Paged decode attention on LOCAL (already per-shard) arrays; the
    paged twin of ``_local_decode`` with the same impl routing and
    fallback contract."""
    quant = k_scale is not None

    def dense():
        return dense_paged_decode_attention(
            q, k_pool, v_pool, kv_len, tables, k_scale, v_scale
        )

    if impl == "dense":
        return dense()
    if impl != "flash":
        raise KeyError(
            f"unknown decode_attention impl {impl!r} (dense | flash)"
        )
    if interpret is None:
        interpret = FORCE_INTERPRET
    bs, d = k_pool.shape[1], q.shape[-1]
    # The pool block IS the kernel chunk: it must be a tileable size on
    # its own (the contiguous kernel gets to pick a divisor; a paged
    # kernel cannot re-chunk across physical blocks).
    tileable = bs >= 8 and (bs & (bs - 1)) == 0 and d % 32 == 0
    if not tileable:
        if jax.default_backend() == "tpu":
            _warn_fallback(
                "paged flash-decode falling back to dense: block geometry "
                f"(bs={bs}, head_dim={d}) is not tileable (need a "
                "power-of-two block size >= 8 and head_dim % 32 == 0)"
            )
        return dense()
    if interpret is None:
        if jax.default_backend() != "tpu":
            return dense()
        interpret = False
    lens = jnp.maximum(kv_len.astype(jnp.int32), 1)
    tbl = tables.astype(jnp.int32)
    if quant:
        return _flash_paged_decode(
            q, k_pool, v_pool, lens, tbl, interpret=interpret,
            k_scale=k_scale.astype(jnp.float32),
            v_scale=v_scale.astype(jnp.float32),
        )
    return _flash_paged_decode(
        q, k_pool, v_pool, lens, tbl, interpret=interpret
    )


def paged_decode_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    kv_len: jax.Array,
    block_tables: jax.Array,
    *,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    impl: str = "flash",
    interpret: bool | None = None,
) -> jax.Array:
    """Single-token decode attention over a PAGED (block-pool) KV cache —
    the paged sibling of ``decode_attention`` and the one entry point the
    block-table decode path (models/gpt.py paged branch, serving engine)
    routes through.

    q ``[B, H, D]``; pools ``[N, bs, H, D]`` (block-major storage — the
    layout serving/engine.py grafts prefilled blocks into); ``kv_len
    [B]`` int32 logical occupancy; ``block_tables [B, M]`` int32 mapping
    logical block j of row b to a physical pool block. With
    ``k_scale``/``v_scale`` (``[N, bs, H]``, both or neither) the pool
    is quantized and every branch dequantizes per block.

    Sharding: the pool carries NO batch axis — blocks are shared across
    rows (that is the whole point), so under a live ``model`` axis the
    pool shards over HEADS only (``P(None, None, 'model', None)``, the
    paged analog of the ``_constrain_kv_cache`` layout) and is
    replicated over the batch axes, while q / lengths / tables shard
    over batch when divisible. Each shard then attends its local heads
    of its local rows against its full local-head pool — zero
    collectives here, same as the contiguous path.
    """
    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        BATCH_AXES,
        current_mesh_env,
        shard_map_compat,
    )

    if (k_scale is None) != (v_scale is None):
        raise ValueError(
            "k_scale and v_scale must be passed together (a quantized "
            "pool quantizes both of its halves)"
        )
    env = current_mesh_env()
    m = env.axis_size("model") if env is not None else 1
    h = q.shape[1]
    if env is None or m <= 1 or h % m != 0:
        return _local_paged_decode(
            q, k_pool, v_pool, kv_len, block_tables, impl=impl,
            interpret=interpret, k_scale=k_scale, v_scale=v_scale,
        )
    batch = BATCH_AXES if q.shape[0] % env.batch_axis_size == 0 else None
    q_spec = P(batch, "model", None)
    pool_spec = P(None, None, "model", None)
    tbl_spec = P(batch, None)
    if k_scale is None:
        fn = shard_map_compat(
            functools.partial(
                _local_paged_decode, impl=impl, interpret=interpret
            ),
            mesh=env.mesh,
            in_specs=(q_spec, pool_spec, pool_spec, P(batch), tbl_spec),
            out_specs=q_spec,
        )
        return fn(q, k_pool, v_pool, kv_len, block_tables)
    sc_spec = P(None, None, "model")
    fn = shard_map_compat(
        lambda q_, k_, v_, l_, t_, ks_, vs_: _local_paged_decode(
            q_, k_, v_, l_, t_, impl=impl, interpret=interpret,
            k_scale=ks_, v_scale=vs_,
        ),
        mesh=env.mesh,
        in_specs=(q_spec, pool_spec, pool_spec, P(batch), tbl_spec,
                  sc_spec, sc_spec),
        out_specs=q_spec,
    )
    return fn(q, k_pool, v_pool, kv_len, block_tables, k_scale, v_scale)


# ------------------------------------------------------ speculative verify


def _flash_paged_verify(q, k_pool, v_pool, kv_len, tables, *, interpret,
                        k_scale=None, v_scale=None):
    """q ``[B, T, H, D]``, pools ``[N, bs, H, D]`` (+ optional scales),
    tables ``[B, M]`` int32 -> ``[B, T, H, D]``. Grid is (rows, logical
    blocks) exactly like the q_len=1 kernel; the scratch accumulators
    carry the extra T dim."""
    b, t, h, d = q.shape
    _, bs, _, _ = k_pool.shape
    n_k = tables.shape[1]
    q_spec = pl.BlockSpec((1, t, h, d), lambda b_, j, *_refs: (b_, 0, 0, 0))
    kv_spec = pl.BlockSpec((1, bs, h, d), _paged_kv_index_map(bs))
    scratch = [
        pltpu.VMEM((h, t, 1), jnp.float32),  # running max
        pltpu.VMEM((h, t, 1), jnp.float32),  # running denom
        pltpu.VMEM((h, t, d), jnp.float32),  # output accumulator
    ]
    if k_scale is None:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, n_k),
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=q_spec,
            scratch_shapes=scratch,
        )
        return pl.pallas_call(
            functools.partial(
                _paged_verify_kernel, block_k=bs, q_len=t,
                scale=1.0 / np.sqrt(d),
            ),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=interpret,
        )(kv_len, tables, q, k_pool, v_pool)
    sc_spec = pl.BlockSpec((1, bs, h), _paged_scale_index_map(bs))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_k),
        in_specs=[q_spec, kv_spec, sc_spec, kv_spec, sc_spec],
        out_specs=q_spec,
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        functools.partial(
            _paged_verify_kernel_quant, block_k=bs, q_len=t,
            scale=1.0 / np.sqrt(d),
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(kv_len, tables, q, k_pool, k_scale, v_pool, v_scale)


def _local_paged_verify(q, k_pool, v_pool, kv_len, tables, *, impl,
                        interpret, k_scale=None, v_scale=None):
    """Verify-tile attention on LOCAL (already per-shard) arrays; the
    tile twin of ``_local_paged_decode`` with the same impl routing and
    fallback contract."""
    quant = k_scale is not None

    def dense():
        return dense_paged_verify_attention(
            q, k_pool, v_pool, kv_len, tables, k_scale, v_scale
        )

    if impl == "dense":
        return dense()
    if impl != "flash":
        raise KeyError(
            f"unknown decode_attention impl {impl!r} (dense | flash)"
        )
    if interpret is None:
        interpret = FORCE_INTERPRET
    bs, d = k_pool.shape[1], q.shape[-1]
    tileable = bs >= 8 and (bs & (bs - 1)) == 0 and d % 32 == 0
    if not tileable:
        if jax.default_backend() == "tpu":
            _warn_fallback(
                "paged verify falling back to dense: block geometry "
                f"(bs={bs}, head_dim={d}) is not tileable (need a "
                "power-of-two block size >= 8 and head_dim % 32 == 0)"
            )
        return dense()
    if interpret is None:
        if jax.default_backend() != "tpu":
            return dense()
        interpret = False
    lens = jnp.maximum(kv_len.astype(jnp.int32), 1)
    tbl = tables.astype(jnp.int32)
    if quant:
        return _flash_paged_verify(
            q, k_pool, v_pool, lens, tbl, interpret=interpret,
            k_scale=k_scale.astype(jnp.float32),
            v_scale=v_scale.astype(jnp.float32),
        )
    return _flash_paged_verify(
        q, k_pool, v_pool, lens, tbl, interpret=interpret
    )


def paged_verify_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    kv_len: jax.Array,
    block_tables: jax.Array,
    *,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    impl: str = "flash",
    interpret: bool | None = None,
) -> jax.Array:
    """Speculative VERIFY-TILE attention over a paged KV cache (ISSUE
    11) — the small-q-tile sibling of ``paged_decode_attention`` and the
    one entry point the verify step (models/gpt.py paged branch with
    t > 1, serving engine ``_verify_fn``) routes through.

    q ``[B, T, H, D]`` — T = k+1 positions per row (last accepted token
    + k drafts), whose K/V have already been scattered into the pool at
    logical positions ``kv_len - T .. kv_len - 1``; ``kv_len [B]`` is
    each row's TOTAL occupancy including the tile. Causality is per
    query position inside the tile: query t attends logical positions
    ``< kv_len - T + 1 + t``, so query 0 computes exactly what a
    single-token decode step would and every draft position additionally
    sees the drafts before it — which is what makes greedy acceptance
    exact (token-identity with ``generate()``). Sharding is identical to
    the q_len=1 entry: the pool shards over heads only and is replicated
    over batch; q/lengths/tables ride the batch axes."""
    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        BATCH_AXES,
        current_mesh_env,
        shard_map_compat,
    )

    if (k_scale is None) != (v_scale is None):
        raise ValueError(
            "k_scale and v_scale must be passed together (a quantized "
            "pool quantizes both of its halves)"
        )
    env = current_mesh_env()
    m = env.axis_size("model") if env is not None else 1
    h = q.shape[2]
    if env is None or m <= 1 or h % m != 0:
        return _local_paged_verify(
            q, k_pool, v_pool, kv_len, block_tables, impl=impl,
            interpret=interpret, k_scale=k_scale, v_scale=v_scale,
        )
    batch = BATCH_AXES if q.shape[0] % env.batch_axis_size == 0 else None
    q_spec = P(batch, None, "model", None)
    pool_spec = P(None, None, "model", None)
    tbl_spec = P(batch, None)
    if k_scale is None:
        fn = shard_map_compat(
            functools.partial(
                _local_paged_verify, impl=impl, interpret=interpret
            ),
            mesh=env.mesh,
            in_specs=(q_spec, pool_spec, pool_spec, P(batch), tbl_spec),
            out_specs=q_spec,
        )
        return fn(q, k_pool, v_pool, kv_len, block_tables)
    sc_spec = P(None, None, "model")
    fn = shard_map_compat(
        lambda q_, k_, v_, l_, t_, ks_, vs_: _local_paged_verify(
            q_, k_, v_, l_, t_, impl=impl, interpret=interpret,
            k_scale=ks_, v_scale=vs_,
        ),
        mesh=env.mesh,
        in_specs=(q_spec, pool_spec, pool_spec, P(batch), tbl_spec,
                  sc_spec, sc_spec),
        out_specs=q_spec,
    )
    return fn(q, k_pool, v_pool, kv_len, block_tables, k_scale, v_scale)
