"""Fused AdamW update — the BACKLOG-5 bandwidth experiment (off by default).

The optax chain expresses one optimizer step as several tree_maps
(moment update → bias correction → decay → LR scale → apply), each a
param-sized elementwise pass XLA must fuse back together; the RN50 trace
shows ~7 ms/step in the optimizer+casts segment. This module fuses the
whole AdamW update for one leaf into ONE Pallas pass: 4 reads (g, m, v, p)
and 3 writes (m', v', p') at fp32 — the HBM floor for Adam-family state.

Honesty contract (the pool_grad=mask precedent): this is an EXPERIMENT.
``optimizer.name=fused_adamw`` is opt-in, numerically pinned to
``optax.adamw`` by tests, and ships as default only if the on-chip sweep
(tools/perf_sweep.py rn50_fused_opt) measures a win. Sharding note: a
pallas_call is opaque to GSPMD, so the kernel path is for
replicated-state configs (DDP / single chip — exactly the RN50 headline);
the trainer refuses ZeRO/FSDP configs (trainer/loop.py) because the
opaque call would silently all-gather the sharded state every step.

Non-TPU backends run the identical math as plain jnp (exact, fast) so CI
and sim meshes never touch Mosaic; the kernel itself is covered in
interpret mode.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

_LANES = 128
_BLOCK_ROWS = 512  # 512x128 fp32 = 256 KB per operand; 7 operands < 2 MB VMEM


class FusedAdamWState(NamedTuple):
    count: jax.Array  # int32 scalar
    mu: optax.Updates
    nu: optax.Updates


def _adamw_math(g, m, v, p, lr, bc1, bc2, *, b1, b2, eps, wd):
    """The update formula — single source shared by kernel and fallback.
    Matches optax.adamw exactly: scale_by_adam (bias-corrected) +
    add_decayed_weights + scale_by_learning_rate."""
    g = g.astype(jnp.float32)
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * (g * g)
    mhat = m / bc1
    vhat = v / bc2
    p_new = p - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * p)
    return p_new, m, v


def _kernel(lr_ref, bc1_ref, bc2_ref, g_ref, m_ref, v_ref, p_ref,
            pn_ref, mn_ref, vn_ref, *, b1, b2, eps, wd):
    p_new, m_new, v_new = _adamw_math(
        g_ref[...], m_ref[...], v_ref[...], p_ref[...],
        lr_ref[0, 0], bc1_ref[0, 0], bc2_ref[0, 0],
        b1=b1, b2=b2, eps=eps, wd=wd,
    )
    pn_ref[...] = p_new
    mn_ref[...] = m_new
    vn_ref[...] = v_new


def _update_leaf(g, m, v, p, lr, bc1, bc2, *, b1, b2, eps, wd, interpret):
    """One leaf through the fused kernel: ravel → pad to a 2D lane grid →
    pallas_call → unpad. Padding lanes carry zeros (sqrt(0) is fine) and
    are sliced away."""
    from jax.experimental import pallas as pl

    shape, dtype = p.shape, p.dtype
    n = p.size
    per_block = _BLOCK_ROWS * _LANES
    padded = max(per_block, ((n + per_block - 1) // per_block) * per_block)
    rows = padded // _LANES

    def prep(x):
        flat = jnp.ravel(x).astype(jnp.float32)
        return jnp.pad(flat, (0, padded - n)).reshape(rows, _LANES)

    scalar_spec = pl.BlockSpec((1, 1), lambda i: (0, 0))
    block_spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    out2d = jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)
    to2 = lambda s: jnp.asarray(s, jnp.float32).reshape(1, 1)
    pn, mn, vn = pl.pallas_call(
        functools.partial(_kernel, b1=b1, b2=b2, eps=eps, wd=wd),
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[scalar_spec] * 3 + [block_spec] * 4,
        out_specs=[block_spec] * 3,
        out_shape=[out2d] * 3,
        interpret=interpret,
    )(to2(lr), to2(bc1), to2(bc2), prep(g), prep(m), prep(v), prep(p))

    unpad = lambda x: x.reshape(-1)[:n].reshape(shape).astype(dtype)
    return unpad(pn), unpad(mn), unpad(vn)


def fused_adamw(
    learning_rate: optax.ScalarOrSchedule,
    *,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    interpret: bool | None = None,
) -> optax.GradientTransformation:
    """AdamW as one fused pass per leaf; optax-compatible.

    The returned transformation also carries ``fused_apply(grads, state,
    params) -> (new_params, new_state)`` — the train step uses it to skip
    the separate ``apply_updates`` pass; the standard ``update`` contract
    (returning deltas) stays available for generic callers at the cost of
    one extra subtraction pass.
    """

    def _lr(count):
        return (
            learning_rate(count)
            if callable(learning_rate)
            else jnp.asarray(learning_rate)
        )

    def init_fn(params):
        zeros = lambda: jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        return FusedAdamWState(
            count=jnp.zeros((), jnp.int32), mu=zeros(), nu=zeros()
        )

    def _apply(grads, state, params):
        if params is None:
            raise ValueError("fused_adamw requires params")
        t = optax.safe_int32_increment(state.count)
        # optax.adamw's scale_by_learning_rate evaluates the schedule at
        # the PRE-increment count while scale_by_adam bias-corrects with
        # the incremented one — match both exactly.
        lr = _lr(state.count)
        tf = t.astype(jnp.float32)
        bc1 = 1.0 - b1**tf
        bc2 = 1.0 - b2**tf

        backend = jax.default_backend()
        use_interpret = (
            interpret if interpret is not None else backend != "tpu"
        )
        use_fallback = use_interpret and backend != "tpu" and interpret is None

        def math_leaf(g, m, v, p):
            # Identical update without Mosaic; restores the param dtype
            # exactly like the kernel path's unpad (fp32 promotion would
            # otherwise flip a bf16 params tree to fp32 after one step —
            # retrace, donation mismatch, unrestorable checkpoints).
            pn, mn, vn = _adamw_math(
                g, m, v, p.astype(jnp.float32), lr, bc1, bc2,
                b1=b1, b2=b2, eps=eps, wd=weight_decay,
            )
            return pn.astype(p.dtype), mn, vn

        def leaf(g, m, v, p):
            # Sub-block leaves (BatchNorm scales, biases) skip the kernel:
            # padding them to the 512x128 tile would amplify their HBM
            # traffic ~1000x and pay a launch each — the plain math fuses
            # fine at that size.
            if use_fallback or p.size < _BLOCK_ROWS * _LANES:
                return math_leaf(g, m, v, p)
            return _update_leaf(
                g, m, v, p, lr, bc1, bc2,
                b1=b1, b2=b2, eps=eps, wd=weight_decay,
                interpret=use_interpret,
            )

        triples = jax.tree.map(leaf, grads, state.mu, state.nu, params)
        is_triple = lambda x: isinstance(x, tuple) and len(x) == 3
        pick = lambda i: jax.tree.map(
            lambda tr: tr[i], triples, is_leaf=is_triple
        )
        new_params = pick(0)
        new_state = FusedAdamWState(count=t, mu=pick(1), nu=pick(2))
        return new_params, new_state

    def update_fn(updates, state, params=None):
        new_params, new_state = _apply(updates, state, params)
        deltas = jax.tree.map(
            lambda np_, p: (np_ - p.astype(jnp.float32)).astype(p.dtype),
            new_params, params,
        )
        return deltas, new_state

    tx = optax.GradientTransformation(init_fn, update_fn)
    # Attach the direct path (GradientTransformation is a NamedTuple —
    # subclass-free attachment via __dict__ is unavailable, so wrap).
    return _FusedTransform(tx.init, tx.update, _apply)


class _FusedTransform(optax.GradientTransformation):
    """GradientTransformation + ``fused_apply`` (params/state in one step)."""

    def __new__(cls, init, update, fused_apply):
        self = super().__new__(cls, init, update)
        return self

    def __init__(self, init, update, fused_apply):
        self.fused_apply = fused_apply
