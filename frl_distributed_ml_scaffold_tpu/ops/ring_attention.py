"""Ring attention: sequence-parallel causal attention over the ``seq`` axis.

SURVEY C8 / §5. The sequence dimension is sharded across the ``seq`` mesh
axis; each shard keeps its queries resident while the K/V shards rotate
around the ring via ``ppermute`` (one neighbor hop per step — this is what
rides the ICI torus links). Softmax is computed online (flash-attention
style running max/denominator rescaling), so no shard ever materializes the
full [T, T] score matrix — memory stays O(T_local²·heads) and context
length scales linearly with the ring size.

Numerics: logits/accumulators in fp32, output cast back to the input dtype;
fully-masked blocks contribute nothing (mask applied to probabilities, not
only logits, so the -1e30 sentinel can't leak through the running max).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.dist.mesh import BATCH_AXES, current_mesh_env

_NEG_INF = -1.0e30


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    causal: bool = True,
) -> jax.Array:
    """(B, T, H, D) attention with T sharded over ``axis_name``.

    Called from model code tracing under the GSPMD jit; wraps its own
    shard_map region over the current mesh. Falls back to single-device
    blockwise math when the seq axis is trivial.
    """
    env = current_mesh_env()
    if env is None or env.axis_size(axis_name) == 1:
        return dense_attention(q, k, v, causal=causal)

    spec = P(BATCH_AXES, axis_name, "model", None)
    inner = partial(_ring_shard_fn, axis_name=axis_name, causal=causal)
    return jax.shard_map(
        inner,
        mesh=env.mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def _ring_shard_fn(q, k, v, *, axis_name: str, causal: bool):
    idx = lax.axis_index(axis_name)
    n = lax.axis_size(axis_name)
    b, t_local, h, d = q.shape
    scale = 1.0 / np.sqrt(d)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(s, carry):
        k_blk, v_blk, m, l, acc = carry
        # After s rotations this shard holds the block originally at idx - s.
        src = (idx - s) % n
        # bf16 operands, fp32 accumulation: the MXU's native mode (same
        # contract as dense_attention).
        logits = (
            jnp.einsum(
                "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
            )
            * scale
        )
        if causal:
            qpos = idx * t_local + jnp.arange(t_local)[:, None]
            kpos = src * t_local + jnp.arange(t_local)[None, :]
            mask = (qpos >= kpos)[None, None]
        else:
            mask = jnp.ones((1, 1, t_local, t_local), bool)
        logits = jnp.where(mask, logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[..., None]) * mask  # mask kills sentinels
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd",
            p.astype(q.dtype),
            v_blk,
            preferred_element_type=jnp.float32,
        )
        k_nxt, v_nxt = lax.ppermute((k_blk, v_blk), axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, acc_new)

    m0 = jnp.full((b, h, t_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    acc0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    _, _, _, l, acc = lax.fori_loop(0, n, body, (k, v, m0, l0, acc0))
    denom = jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]
    return (acc / denom).astype(q.dtype)


def dense_attention(q, k, v, *, causal: bool = True):
    """(B, T, H, D) dense attention — the numerics contract all sharded
    paths reduce to when their axis is trivial.

    MXU-friendly mixed precision: einsum operands stay in the input dtype
    (bf16 under the mixed policy) with fp32 accumulation
    (``preferred_element_type``) — the MXU's native bf16-multiply /
    fp32-accumulate mode — and the softmax itself is fp32.
    """
    t, d = q.shape[1], q.shape[3]
    scale = 1.0 / np.sqrt(d)
    logits = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))[None, None]
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs, v, preferred_element_type=jnp.float32
    )
    return out.astype(q.dtype)


# Backwards-compat private alias (pre-public-export importers).
_single_shard_attention = dense_attention
