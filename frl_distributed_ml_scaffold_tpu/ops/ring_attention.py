"""Ring attention: sequence-parallel causal attention over the ``seq`` axis.

SURVEY C8 / §5. The sequence dimension is sharded across the ``seq`` mesh
axis; each shard keeps its queries resident while the K/V shards rotate
around the ring via ``ppermute`` (one neighbor hop per step — this is what
rides the ICI torus links). Each hop's compute is the fused Pallas flash
kernel (ops/flash_attention.py) on TPU — per-hop VMEM stays O(block·D) and
no shard ever materializes even its local [T_local, T_local] score matrix —
so context length is bounded by HBM across the ring, not by any quadratic
buffer. Off-TPU the hops use the identical-numerics dense-with-lse path.

Hop results merge exactly by per-row logsumexp: each hop returns its block
output normalized by its own (o, lse); ``logaddexp`` combines them into the
running global (o, lse). Hops strictly above the causal diagonal skip their
compute entirely (``lax.cond`` — only the ppermute runs).

Backward is a custom VJP (the memory win would otherwise be lost to saved
per-hop K/V residuals): only the LOCAL (q, k, v, o, lse) are saved; the
backward re-rotates K/V around the ring together with traveling dK/dV
accumulators, each hop calling the flash backward kernels with the global
lse (``p = exp(s - lse)`` is exact per block). Accumulators travel in fp32.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.dist.mesh import BATCH_AXES, current_mesh_env

_NEG_INF = -1.0e30


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    causal: bool = True,
    interpret: bool | None = None,
) -> jax.Array:
    """(B, T, H, D) attention with T sharded over ``axis_name``.

    Called from model code tracing under the GSPMD jit; wraps its own
    shard_map region over the current mesh. Falls back to single-device
    blockwise math when the seq axis is trivial. ``interpret`` forces the
    per-hop Pallas kernels into interpreter mode (tests on CPU); ``None``
    picks pallas-on-TPU / dense-elsewhere automatically.
    """
    env = current_mesh_env()
    if env is None or env.axis_size(axis_name) == 1:
        return dense_attention(q, k, v, causal=causal)

    spec = P(BATCH_AXES, axis_name, "model", None)
    inner = partial(
        _ring_shard_fn, axis_name=axis_name, causal=causal, interpret=interpret
    )
    from frl_distributed_ml_scaffold_tpu.dist.mesh import shard_map_compat

    return shard_map_compat(
        inner,
        mesh=env.mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)


def _ring_shard_fn(q, k, v, *, axis_name: str, causal: bool, interpret):
    # Flash kernels run in (B, H, T, D); these transposes sit against the
    # projection reshapes outside and fuse in XLA.
    qT, kT, vT = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o = _ring(qT, kT, vT, axis_name, causal, interpret)
    return o.transpose(0, 2, 1, 3)


def _merge(o_run, lse_run, o_blk, lse_blk):
    """Exact combine of two self-normalized partial attentions (fp32)."""
    lse_new = jnp.logaddexp(lse_run, lse_blk)
    w_run = jnp.exp(lse_run - lse_new)
    w_blk = jnp.exp(lse_blk - lse_new)
    o_new = o_run * w_run + o_blk.astype(jnp.float32) * w_blk
    return o_new, lse_new


def _ring_fwd_loop(q, k, v, axis_name, causal, interpret):
    """``lax.fori_loop`` over hops 1..n-1 (hop 0, the diagonal, is special)
    so traced program size stays O(1) in the ring size."""
    from frl_distributed_ml_scaffold_tpu.ops.flash_attention import (
        block_attention_fwd,
    )

    from frl_distributed_ml_scaffold_tpu.dist.collectives import axis_size

    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Hop 0: the diagonal block (q and k share a position origin).
    o0, lse0 = block_attention_fwd(q, k, v, causal=causal, interpret=interpret)

    def body(s, carry):
        k_blk, v_blk, o, lse = carry
        k_blk, v_blk = lax.ppermute((k_blk, v_blk), axis_name, perm)
        if causal:
            # After s rotations this shard holds the block from idx - s.
            src = (idx - s) % n
            o_s, lse_s = lax.cond(
                src < idx,  # blocks from the future contribute nothing
                lambda a, b, c: block_attention_fwd(
                    a, b, c, causal=False, interpret=interpret
                ),
                lambda a, b, c: (
                    jnp.zeros_like(o0),
                    jnp.full_like(lse0, _NEG_INF),
                ),
                q,
                k_blk,
                v_blk,
            )
        else:
            o_s, lse_s = block_attention_fwd(
                q, k_blk, v_blk, causal=False, interpret=interpret
            )
        o, lse = _merge(o, lse, o_s, lse_s)
        return (k_blk, v_blk, o, lse)

    _, _, o, lse = lax.fori_loop(
        1, n, body, (k, v, o0.astype(jnp.float32), lse0)
    )
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring(q, k, v, axis_name, causal, interpret):
    o, _ = _ring_fwd_loop(q, k, v, axis_name, causal, interpret)
    return o


def _ring_fwd_rule(q, k, v, axis_name, causal, interpret):
    o, lse = _ring_fwd_loop(q, k, v, axis_name, causal, interpret)
    return o, (q, k, v, o, lse)


def _ring_bwd_rule(axis_name, causal, interpret, res, do):
    from frl_distributed_ml_scaffold_tpu.ops.flash_attention import (
        block_attention_bwd,
    )

    q, k, v, o, lse = res
    from frl_distributed_ml_scaffold_tpu.dist.collectives import axis_size

    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Hop 0: diagonal. dK/dV accumulators then TRAVEL with their block
    # around the ring (fp32), so each visiting device adds its contribution
    # in place; after the final rotation they arrive back home complete.
    dq0, dk0, dv0 = block_attention_bwd(
        q, k, v, o, lse, do, causal=causal, interpret=interpret
    )

    def _live(args):
        q_, k_, v_, o_, lse_, do_ = args
        return block_attention_bwd(
            q_, k_, v_, o_, lse_, do_, causal=False, interpret=interpret
        )

    def _dead(args):
        q_, k_, v_, _o, _l, _d = args
        return jnp.zeros_like(q_), jnp.zeros_like(k_), jnp.zeros_like(v_)

    def body(s, carry):
        k_blk, v_blk, dq, dk_acc, dv_acc = carry
        k_blk, v_blk, dk_acc, dv_acc = lax.ppermute(
            (k_blk, v_blk, dk_acc, dv_acc), axis_name, perm
        )
        if causal:
            src = (idx - s) % n
            dq_s, dk_s, dv_s = lax.cond(
                src < idx, _live, _dead, (q, k_blk, v_blk, o, lse, do)
            )
        else:
            dq_s, dk_s, dv_s = _live((q, k_blk, v_blk, o, lse, do))
        return (
            k_blk,
            v_blk,
            dq + dq_s.astype(jnp.float32),
            dk_acc + dk_s.astype(jnp.float32),
            dv_acc + dv_s.astype(jnp.float32),
        )

    _, _, dq, dk_acc, dv_acc = lax.fori_loop(
        1,
        n,
        body,
        (k, v, dq0.astype(jnp.float32), dk0.astype(jnp.float32),
         dv0.astype(jnp.float32)),
    )
    # n-1 rotations have happened; one more brings each block's dK/dV home.
    dk_acc, dv_acc = lax.ppermute((dk_acc, dv_acc), axis_name, perm)
    return dq.astype(q.dtype), dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)


_ring.defvjp(_ring_fwd_rule, _ring_bwd_rule)


def dense_attention(q, k, v, *, causal: bool = True):
    """(B, T, H, D) dense attention — the numerics contract all sharded
    paths reduce to when their axis is trivial.

    MXU-friendly mixed precision: einsum operands stay in the input dtype
    (bf16 under the mixed policy) with fp32 accumulation
    (``preferred_element_type``) — the MXU's native bf16-multiply /
    fp32-accumulate mode — and the softmax itself is fp32.
    """
    t, d = q.shape[1], q.shape[3]
    scale = 1.0 / np.sqrt(d)
    logits = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))[None, None]
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs, v, preferred_element_type=jnp.float32
    )
    return out.astype(q.dtype)


# Backwards-compat private alias (pre-public-export importers).
_single_shard_attention = dense_attention
