"""Mixed-precision policy (SURVEY C10) — the AMP equivalent, TPU-native.

The reference uses autocast(bf16) + GradScaler. On TPU, bf16 has fp32's
exponent range, so no loss scaling is needed; the whole AMP story reduces to
a dtype policy: params are stored in ``param_dtype``, cast to
``compute_dtype`` for the forward/backward, and gradients/optimizer math run
in ``param_dtype``. Collective reductions ride ``reduce_dtype`` (fp32 keeps
large-mesh gradient sums stable).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from frl_distributed_ml_scaffold_tpu.config.schema import PrecisionConfig


@dataclass(frozen=True)
class Policy:
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32  # loss/logits dtype
    reduce_dtype: Any = jnp.float32

    def cast_to_compute(self, tree: Any) -> Any:
        return _cast_floats(tree, self.compute_dtype)

    def cast_to_param(self, tree: Any) -> Any:
        return _cast_floats(tree, self.param_dtype)

    def cast_to_output(self, tree: Any) -> Any:
        return _cast_floats(tree, self.output_dtype)


def _cast_floats(tree: Any, dtype: Any) -> Any:
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)


_POLICIES = {
    # Full fp32: debugging / CPU-sim numerics reference.
    "fp32": Policy(),
    # Pure bf16: maximum speed, params also bf16 (used for inference).
    "bf16": Policy(
        param_dtype=jnp.bfloat16,
        compute_dtype=jnp.bfloat16,
        output_dtype=jnp.bfloat16,
        reduce_dtype=jnp.float32,
    ),
    # The "bf16 AMP" equivalent: fp32 master params, bf16 compute.
    "bf16_mixed": Policy(
        param_dtype=jnp.float32,
        compute_dtype=jnp.bfloat16,
        output_dtype=jnp.float32,
        reduce_dtype=jnp.float32,
    ),
}


def get_policy(cfg: PrecisionConfig | str) -> Policy:
    name = cfg if isinstance(cfg, str) else cfg.policy
    if name not in _POLICIES:
        raise KeyError(f"unknown precision policy {name!r}; have {sorted(_POLICIES)}")
    return _POLICIES[name]
