"""Config schema: every knob the framework exposes, as typed dataclasses.

One ``ExperimentConfig`` fully describes a run — model, data, mesh,
parallelism strategy, precision, optimizer, checkpointing. The five
BASELINE.json reference recipes are instances of this schema
(config/recipes.py).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional


# --------------------------------------------------------------------------
# Mesh / parallelism
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class MeshConfig:
    """Logical device-mesh shape (SURVEY C2).

    Axis sizes multiply to the device count; ``data = -1`` means "absorb all
    remaining devices". Axes of size 1 are still present in the mesh so
    PartitionSpecs can always name them — XLA drops trivial dimensions at
    compile time.

    The axis vocabulary is the whole parallelism story (SURVEY C4–C9):

    - ``data``:   DP — batch sharded, params replicated (or FSDP-sharded).
    - ``fsdp``:   parameter/optimizer sharding axis (FSDP/ZeRO). Kept
                  separate from ``data`` so DP×FSDP hybrids express naturally.
    - ``model``:  tensor parallelism (Megatron column/row splits).
    - ``seq``:    sequence/context parallelism (ring attention, Ulysses).
    - ``expert``: MoE expert parallelism.
    - ``pipe``:   pipeline stages.
    """

    data: int = -1
    fsdp: int = 1
    model: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1
    # Number of DCN (cross-slice) segments along the data axis; 1 = single
    # slice. When >1, the mesh is built hybrid: data axis spans DCN, all other
    # axes stay inside the ICI slice.
    dcn_data: int = 1

    def axis_sizes(self) -> dict[str, int]:
        return {
            "data": self.data,
            "fsdp": self.fsdp,
            "model": self.model,
            "seq": self.seq,
            "expert": self.expert,
            "pipe": self.pipe,
        }


@dataclass(frozen=True)
class ParallelConfig:
    """How state is laid out over the mesh (SURVEY C4–C9).

    - ``param_sharding``: "replicated" (DDP) or "fsdp" (full shard over the
      fsdp axis — SimpleFSDP-style sharding annotations, no wrapper module).
    - ``opt_sharding``: "like_params" | "zero1" (shard optimizer state over
      the fsdp axis even when params are replicated — ZeRO-1).
    - ``sequence``: "none" | "ring" | "ulysses" — long-context attention mode.
    - ``fsdp_min_size``: leaves smaller than this stay replicated (sharding
      tiny params costs more collective latency than it saves memory).
    - ``fsdp_overlap``: opt-in overlap-scheduled FSDP (SimpleFSDP-style,
      arxiv 2411.00284): instead of leaving parameter gathering to GSPMD
      (which tends to materialize full params up front and serialize the
      collectives against compute), each transformer block / ResNet block
      explicitly ``all_gather``s its shard immediately before its compute
      and the backward ``reduce_scatter``s gradients straight back into
      shards (parallel/fsdp_overlap.py). Requires ``param_sharding="fsdp"``
      and a model family with blockwise apply hooks (gpt, resnet).
    - ``fsdp_prefetch``: how many blocks ahead a gather may be issued
      (default 1 — the SimpleFSDP "one block ahead" schedule). On the
      per-block Python loop (ResNet) the window is enforced structurally
      with optimization barriers; on the scanned transformer stack the
      rolled loop exposes exactly one block of lookahead to XLA's
      collective pipeliner, so values > 1 behave as 1 there.
    - ``tp_overlap``: opt-in latency-hiding tensor parallelism
      (parallel/tp_overlap.py, the collective-matmul schedule of the JAX
      pjit/TPUv4 scaling paper): the four per-block TP matmuls (QKV,
      attn-out, fc_in, fc_out — and the ViT/video equivalents) become
      bidirectional ``ppermute`` rings that hide the model-axis
      communication under their own block compute, with the residual
      stream sharded over the model axis between them, instead of GSPMD's
      monolithic per-layer allreduces. Requires ``mesh.model > 1`` and a
      model family with hooks (gpt, vit, video); composes with data/fsdp
      meshes and ``fsdp_overlap``, not with pipeline/sequence parallelism
      or MoE.
    - ``low_precision``: the low-precision fast path for the collective-
      matmul rings ("none" | "int8" | "fp8_e4m3" | "fp8_e5m2",
      ops/quantization.py): the four hooked TP matmuls run as scaled
      low-precision matmuls (per-tensor activation scales, per-channel
      weight scales, bf16/fp32 master weights, straight-through grads)
      and the rings ``ppermute`` the QUANTIZED chunks + scales — comm
      bytes on the model axis shrink with the element width (4x at fp32,
      2x at bf16), pinned by graft-lint's per-dtype collective census.
      Requires ``tp_overlap=true`` (the knob quantizes the rings; there
      is no GSPMD low-precision path to fall back to). Tolerances and
      when-to-use guidance: docs/perf_playbook.md "Low-precision fast
      path".
    - ``schedule``: the unified overlap-schedule declaration
      (parallel/schedule.py, ROADMAP item 2). "auto" (default) derives
      the per-axis gather/scatter schedule from the knobs above —
      ``fsdp_overlap``/``fsdp_prefetch`` become
      ``gather(fsdp,block,prefetch=N)+scatter(fsdp)``,
      ``tp_overlap``/``low_precision`` become
      ``gather(model,ring_chunk[,lowp=FMT])+scatter(model[,lowp=FMT])``
      — preserving their exact semantics. An explicit declaration string
      in that grammar replaces the derivation (and must agree with any
      legacy knob also set); contradictions raise a typed
      ``ScheduleError`` naming the schedule attribute at Trainer
      construction, never a shape error inside the scan body. Guidance:
      docs/perf_playbook.md "Declaring an overlap schedule".
    """

    param_sharding: str = "replicated"  # replicated | fsdp
    opt_sharding: str = "like_params"  # like_params | zero1
    sequence: str = "none"  # none | ring | ulysses
    fsdp_min_size: int = 1024
    fsdp_overlap: bool = False
    fsdp_prefetch: int = 1
    tp_overlap: bool = False
    low_precision: str = "none"  # none | int8 | fp8_e4m3 | fp8_e5m2
    # "auto" = derive from the knobs above; else an explicit declaration,
    # e.g. "gather(fsdp,block,prefetch=1)+scatter(fsdp)".
    schedule: str = "auto"


@dataclass(frozen=True)
class PrecisionConfig:
    """Mixed-precision policy (SURVEY C10).

    bf16 on TPU needs no loss scaling (8-bit exponent), so the reference's
    GradScaler has no equivalent here — ``bf16_mixed`` keeps fp32 master
    params with bf16 compute, matching "bf16 AMP" semantics.
    """

    policy: str = "bf16_mixed"  # fp32 | bf16 | bf16_mixed


# --------------------------------------------------------------------------
# Trainer / optimizer / checkpoint / data
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class OptimizerConfig:
    name: str = "adamw"  # adamw | sgd | adam | adafactor | lion | fused_adamw
    learning_rate: float = 1e-3
    warmup_steps: int = 0
    schedule: str = "constant"  # constant | cosine | linear | wsd
    weight_decay: float = 0.0
    b1: float = 0.9
    # None = the optimizer's own canonical default (0.999 for the adam
    # family, 0.99 for lion); an explicit value is always honored.
    b2: Optional[float] = None
    eps: float = 1e-8  # adam family only (adafactor keeps optax's 1e-30)
    momentum: float = 0.9  # sgd only
    grad_clip_norm: Optional[float] = None
    # "wsd" only: fraction of post-warmup steps spent in the final linear
    # decay (the rest holds the peak LR).
    wsd_decay_fraction: float = 0.2


@dataclass(frozen=True)
class TrainerConfig:
    total_steps: int = 1000
    grad_accum: int = 1
    remat: str = "none"  # none | full | dots
    log_every: int = 50
    eval_every: int = 0  # 0 = no eval during training
    eval_steps: int = 10
    seed: int = 0
    # Profiling (SURVEY C19): capture a jax.profiler trace for
    # [profile_start_step, profile_start_step + profile_steps). 0 = off.
    profile_steps: int = 0
    profile_start_step: int = 10
    # Exponential moving average of params, updated inside the compiled
    # step (ema = d*ema + (1-d)*params). 0 = off. When on, eval runs with
    # the EMA weights (the reason to keep them) and they ride the same
    # sharding specs + checkpoint as the live params.
    ema_decay: float = 0.0
    # Initialize model params from a flax-msgpack file (e.g. an imported
    # HF checkpoint from tools/import_hf_gpt2.py) instead of random init.
    # The tree structure/shapes must match the model exactly; params are
    # cast to the precision policy's param dtype and placed into the
    # run's shardings. Optimizer state still initializes fresh.
    init_params_path: str = ""
    # Write metric scalars to TensorBoard (<workdir>/<name>/tb) next to
    # the profiler traces. JSONL remains the record of truth; the sink is
    # lazy-TF and degrades to a warning if TF is unusable.
    tensorboard: bool = False
    # Stall watchdog deadline (ISSUE 7): a host thread fires when no step
    # completes dispatch within this many seconds — faulthandler
    # tracebacks + metric snapshot to <run_dir>/stall_dump.txt and a
    # stalls_total counter increment. 0 = off. Size it to several times
    # the slowest expected STEP; the first-beat grace below absorbs the
    # initial compile.
    stall_timeout_s: float = 0.0
    # First-beat deadline multiplier: beats only start flowing once
    # dispatch does, so the initial silence includes XLA compile time —
    # until the first beat lands the watchdog waits
    # stall_timeout_s * this. ~5x makes a steady-state-sized deadline
    # survive the step-0 compile (the false-fire docs/operations.md used
    # to warn about); 1.0 restores the old strict behavior.
    stall_timeout_first_beat_scale: float = 5.0
    # Host-side span tracing (ISSUE 8, telemetry/tracing.py): per-step
    # spans (step/load_batch/dispatch/checkpoint/eval) recorded around
    # the jitted calls, teed into telemetry.jsonl as timeline events and
    # exported as Chrome-trace-event JSON (<run_dir>/trace_events.json —
    # load in Perfetto next to the device traces profile_steps captures;
    # the span context managers wrap jax.profiler Trace/StepTrace
    # annotations so the two align). Ring-bounded host dicts: overhead
    # is microseconds/step, so it ships on.
    tracing: bool = True
    # Keep the optimizer state in host memory (``pinned_host``): XLA
    # streams it through HBM around the update. A CAPACITY knob, not a
    # speed knob — it pays PCIe traffic every optimizer step to free
    # state-sized HBM (e.g. GPT-2-medium's ~4.3G AdamW fp32 state).
    # TPU-only: the CPU sim backend cannot partition host-memory arrays
    # (the Trainer refuses with a clear error).
    offload_opt_state: bool = False
    # Graceful preemption (SIGTERM → finish the in-flight step → save a
    # synchronized checkpoint → exit rc 0): whether the preemption path
    # SAVES before exiting. Off only for runs whose checkpoints are
    # managed externally (the clean exit itself always happens — a
    # preempted child must never die mid-collective).
    preempt_save: bool = True


@dataclass(frozen=True)
class CheckpointConfig:
    enabled: bool = False
    save_every: int = 1000
    max_to_keep: int = 3
    async_save: bool = True
    resume: bool = True  # restore latest checkpoint if present
    # Restore through the redistribution service (ISSUE 15): each leaf
    # is read at a memory-efficient EVEN layout (every device reads
    # ~1/N — never a replicated staging copy, even for leaves whose
    # target is replication) and then redistributed on-device to the
    # trainer's target shardings by redistribute/'s plan executor. The
    # elastic supervisor's reform path forces this on (a reformed mesh
    # is exactly the saved-on-any-mesh/restored-on-any-other case);
    # default off so unchanged-topology resumes keep the direct Orbax
    # path bit-for-bit.
    restore_redistribute: bool = False
    # Scratch budget for the redistribution's bounded chunking, MiB.
    # 0 = auto (one destination shard + one chunk per leaf — the plan
    # compiler's own ceiling).
    redistribute_scratch_mb: int = 0


@dataclass(frozen=True)
class ServingConfig:
    """Serving-tier failure semantics (ISSUE 9, docs/operations.md
    "Failure semantics"). These are the graceful-degradation knobs the
    continuous-batching engine (serving/engine.py) takes at construction;
    tools/serve_bench.py --chaos exercises them end-to-end."""

    # Bounded admission: submits beyond this many queued (not yet
    # admitted) requests are LOAD-SHED — the caller gets a typed
    # completion (finish_reason="shed") immediately instead of unbounded
    # queue growth eating host memory and blowing every SLO at once.
    # 0 = unbounded (the pre-ISSUE-9 behavior).
    max_queue_depth: int = 0
    # Per-request deadline, seconds from submit: a request still queued
    # past its deadline sheds at admission; one mid-decode is CANCELLED —
    # retired with finish_reason="deadline" and the tokens generated so
    # far, freeing the slot for refill. submit(deadline_s=...) overrides
    # per request. 0 = no deadline.
    default_deadline_s: float = 0.0
    # Paged KV cache (ISSUE 10): > 0 stores K/V in a shared pool of
    # fixed-size blocks (power of two) with per-slot block tables —
    # slots stop reserving power-of-two cache buckets, growth appends a
    # block instead of cloning the cache, and HBM is priced per BLOCK.
    # 0 = the bucketed contiguous cache (pre-ISSUE-10 behavior).
    kv_block_size: int = 0
    # Pool size in blocks (block 0 is the reserved trash block retired
    # slots write into). 0 = auto: num_slots x ceil(seq_len/block) + 1,
    # the never-blocks-admission worst case — size it DOWN deliberately
    # to multiply concurrency (admission then waits on pool headroom,
    # composing with max_queue_depth's shed bound; docs/operations.md).
    kv_pool_blocks: int = 0
    # Refcounted shared-prefix caching over full pool blocks: a prompt
    # whose leading blocks match an earlier prompt's reuses them
    # (prefill runs only on the suffix); the first divergent or partial
    # block is copy-on-write private, so shared blocks are immutable.
    prefix_cache: bool = True
    # Speculative decoding on the paged engine (ISSUE 11). "ngram" =
    # tier-A self-speculation: drafts come from prompt-lookup over the
    # slot's own token history (no second model — wins on repetitive /
    # structured text); "draft" = tier-B small draft GPT sharing the
    # tokenizer (pass draft_model/draft_params to the engine). Greedy
    # decode only (acceptance is exact argmax matching, so speculative
    # output is TOKEN-IDENTICAL to generate() — a pure-perf knob);
    # requires the paged cache (kv_block_size > 0): accept/rollback is
    # block-table pointer bookkeeping there, never cache surgery.
    # "off" = plain single-token decode.
    speculate: str = "off"
    # Draft tokens proposed per verify step: the target model scores
    # k+1 positions in ONE batched forward, amortizing the pool read.
    # The verify program compiles ONCE at this k (no per-k ladder);
    # slots with fewer (or zero) drafts ride the same program.
    speculate_k: int = 4
    # Disaggregated prefill/decode serving (ISSUE 12,
    # serving/scheduler.py): True routes serving through the
    # prefill-worker / decode-worker split coordinated by the
    # multi-tenant SLO scheduler (serving.build_engine dispatches on
    # this). Requires the paged cache (kv_block_size > 0): the
    # prefill→decode handoff is a block-table splice there, never a
    # cache copy.
    disaggregate: bool = False
    # Prefill admissions the scheduler starts per decode tick: the
    # decoupled-admission bound that keeps a prefill burst from starving
    # running decodes — queued prefills DEFER (the burst queues up)
    # instead of running inline ahead of the next decode step the way
    # colocated admission does. 1 is the tail-isolation setting;
    # raising it trades decode TPOT tails for admission throughput.
    prefill_max_per_tick: int = 1
    # Prefill-worker / handoff failures re-queue the request and retry
    # up to this many times before the request resolves as a typed
    # "error" (never hangs — the ISSUE-9 contract across the worker
    # boundary).
    handoff_retries: int = 2


@dataclass(frozen=True)
class ElasticConfig:
    """Checkpoint-restart elasticity (SURVEY C14): the supervisor restarts a
    dead child up to ``max_restarts`` times with exponential backoff."""

    max_restarts: int = 3
    backoff_s: float = 1.0
    # Backoff cap for the restart loop (the supervisor's retry budget is
    # the faults/retry.py RetryPolicy: backoff_s * 2^(n-1), capped here,
    # budgeted by max_restarts) — exponential backoff must not park a
    # crash-looping host for hours.
    max_backoff_s: float = 300.0
    # Membership heartbeat writes that fail (shared-FS outage) are
    # counted (heartbeat_write_failures_total) and retried each
    # interval; after this many CONSECUTIVE failures the supervisor
    # retires its membership record (unlinks it) so peers evict this
    # host deterministically instead of racing the mtime staleness
    # window. 0 = retry forever (the pre-ISSUE-9 behavior).
    heartbeat_retire_after: int = 10
    # A child that survives this long before dying counts as real progress:
    # the restart budget and backoff reset (torchrun-elastic-agent semantics),
    # so a week-long run isn't killed by its 4th once-a-day preemption.
    reset_after_s: float = 600.0
    # Smaller-slice continuation (SURVEY C14 "re-initialize (possibly
    # smaller slice)"): after this many consecutive failed restarts, the
    # supervisor consults the shared-workdir membership heartbeats; peers
    # stale for more than ``peer_timeout_s`` are declared dead, and the
    # child is re-launched over the surviving hosts only (ranks remapped,
    # coordinator re-elected to the lowest surviving host, Orbax restores
    # with resharding). 0 = never shrink — a missing host blocks until the
    # restart budget runs out, the round-2/3 behavior.
    shrink_after: int = 0
    peer_timeout_s: float = 60.0
    # Grow-back after a shrink: when a previously-dead host resumes
    # heartbeating (repaired, or a false-positive eviction), the supervisor
    # preempts the child (SIGTERM -> checkpoint -> clean exit) and
    # re-forms at the larger world — ranks remapped by uid, Orbax
    # resharding restore, no steps lost. false = shrink-only (a wrongly
    # evicted host then needs operator action, the round-4 behavior).
    grow: bool = True


@dataclass(frozen=True)
class DataConfig:
    """Input pipeline selection (SURVEY C16). ``global_batch_size`` is the
    whole-run batch; the pipeline shards it per host and the mesh shards it
    per chip."""

    name: str = "synthetic_mnist"
    global_batch_size: int = 128
    image_size: int = 28
    num_classes: int = 10
    channels: int = 1
    seq_len: int = 1024
    vocab_size: int = 50257
    num_frames: int = 8
    shuffle_seed: int = 0
    # For real datasets: directory to look in; synthetic fallback if absent.
    data_dir: Optional[str] = None
    # Batches built ahead on a background thread (0 = synchronous).
    prefetch: int = 2
    # Online ingestion (data/streaming.py): treat data_dir as APPEND-ONLY
    # GROWABLE — re-scan every `streaming_refresh_every` steps for newly
    # sealed shard pairs and widen the sampling window (hosts agree on
    # the window via the host-tier collective). Determinism contract in
    # the module docstring. false = the corpus freezes at construction.
    streaming: bool = False
    streaming_refresh_every: int = 256
    # Host-side batch-build failures (decode error, transient shared-FS
    # read) are retried under the unified faults/retry.py policy — the
    # batch is a pure function of step, so a rebuild is safe. After the
    # budget the original exception propagates (a permanently bad shard
    # must kill the run loudly, not spin).
    loader_max_retries: int = 2
    loader_retry_backoff_s: float = 0.05


# --------------------------------------------------------------------------
# Model families (SURVEY C15)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class MLPConfig:
    family: str = "mlp"
    hidden_sizes: tuple[int, ...] = (512, 256)
    num_classes: int = 10
    dropout: float = 0.0


@dataclass(frozen=True)
class ResNetConfig:
    family: str = "resnet"
    depth: int = 50  # 18 | 34 | 50 | 101 | 152
    num_classes: int = 1000
    width_multiplier: int = 1
    # "conv7" = torchvision 7x7/s2 stem; "s2d" = the mathematically exact
    # space-to-depth rewrite (MXU-friendly; see models/resnet.py).
    stem: str = "conv7"
    # Stem max-pool backward: "scatter" = XLA select_and_scatter (the
    # autodiff default; first-max-wins on ties, and the faster path on
    # v5e — "mask" measured ~8% slower end-to-end, see BASELINE.md
    # "measured and rejected"); "mask" = custom-VJP compare-and-sum pass
    # whose tie semantics split the gradient equally across tied maxima
    # (models/resnet.py::_max_pool_mask_grad).
    pool_grad: str = "scatter"
    # Fused BatchNorm-backward Pallas kernel (ops/fused_bn.py): identical
    # forward, train-mode backward replaced by the two-pass reduction+dx
    # kernel chain attacking the measured ~150 ms/step of HBM-bound
    # BN-backward traffic (docs/perf_playbook.md roofline). Ships off by
    # default until tools/perf_sweep.py rn50_fused_bn measures the win
    # on-chip (the fused_adamw honesty contract).
    fused_bn: bool = False


@dataclass(frozen=True)
class ViTConfig:
    family: str = "vit"
    image_size: int = 224
    patch_size: int = 16
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    num_classes: int = 1000
    dropout: float = 0.0
    pool: str = "cls"  # cls | mean


@dataclass(frozen=True)
class MoEConfig:
    """Mixture-of-experts block config (SURVEY C9). ``num_experts = 0``
    disables MoE."""

    num_experts: int = 0
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_loss: float = 0.01
    # ST-MoE router z-loss coefficient (mean log²-sum-exp of router
    # logits); 0 disables.
    router_z_loss: float = 1e-3
    # Routing groups (GShard GSEC layout): dispatch/combine memory scales
    # with 1/G and capacity is enforced per group. 0 = auto (the mesh's
    # batch-shard count, so each data shard routes its own tokens).
    num_groups: int = 0
    # Token->expert exchange formulation, identical routing/drop semantics
    # (seating comes from the same slot-major cumsum either way):
    #   einsum — one-hot [G,S,E,C] dispatch/combine einsums (GShard); the
    #            exchange is MACs against mostly-zero one-hots, costing
    #            O(S*E*C*D) — comparable to the expert FFN itself at
    #            audited shapes (docs/perf_playbook.md).
    #   sort   — scatter/gather (ragged) exchange: seat indices are
    #            scattered into the [E*C] slot table and tokens gathered
    #            by index; ~zero exchange MACs.
    dispatch: str = "einsum"  # einsum | sort


@dataclass(frozen=True)
class GPTConfig:
    family: str = "gpt"
    vocab_size: int = 50257
    num_layers: int = 24
    num_heads: int = 16
    hidden_dim: int = 1024
    seq_len: int = 1024
    mlp_ratio: int = 4
    dropout: float = 0.0
    # GPT-2's LayerNorm epsilon (flax's default is 1e-6; HF checkpoints
    # are trained with 1e-5 — keeping it makes HF imports numerically
    # exact, see tools/import_hf_gpt2.py).
    layer_norm_epsilon: float = 1e-5
    # Attention implementation: "dense" | "ring" | "ulysses" | "flash"
    attention: str = "dense"
    # KV-cache decode attention: "flash" routes single-token steps through
    # the fused split-KV Pallas kernel (ops/decode_attention.py; on
    # non-TPU backends it silently takes the identical-numerics dense
    # path, same contract as attention="flash"), "dense" forces the
    # masked-dense reference. Orthogonal to ``attention`` — the training
    # kernels are pointless at one-token query shapes.
    decode_attention: str = "flash"
    # Quantized KV cache ("none" | "int8" | "fp8_e4m3"): decode stores
    # K/V in the 1-byte format with per-(row, position, head) bf16 scales
    # carried alongside (each written token quantizes once, over its own
    # head vector, and is never re-quantized) — cache HBM per slot drops
    # ~2x vs bf16 at matched decode tolerance, which is what caps
    # servable concurrent slots (serving/engine.py accounting,
    # tools/serve_bench.py int8 arms). The flash-decode kernel
    # dequantizes per split-KV chunk in VMEM; the dense fallback
    # dequantizes in bounded chunks — no full-precision full-context
    # tensor materializes in a decode step (graft-lint pinned).
    kv_cache_quant: str = "none"
    # Chunked-vocab LM loss: compute the weight-tied head + cross-entropy
    # in sequence chunks of this many tokens (rematerialized in backward),
    # so the [B, T, vocab] logits tensor never materializes — for
    # GPT-2-medium at T=1024 that is ~400 MB of bf16 logits (plus their
    # cotangents) traded for a scan. 0 = off (dense head). If the sequence
    # length is not divisible by the chunk, the loss warns and falls back
    # to the dense head (the knob is a memory optimization, not a
    # correctness switch).
    lm_loss_chunk: int = 0
    moe: MoEConfig = field(default_factory=MoEConfig)
    # Pipeline parallelism (SURVEY C7): >1 stages the block stack over the
    # ``pipe`` mesh axis. ``pipeline_microbatches`` = 0 means "same as
    # stages" (the minimum that keeps every stage busy outside the bubble).
    pipeline_stages: int = 1
    pipeline_microbatches: int = 0
    # Pipeline backend (ISSUE 14):
    #   "spmd" — the stage-vmap GPipe schedule (parallel/pipeline.py): the
    #            whole timeline is ONE compiled GSPMD program; all M
    #            microbatch activations stay live across the tick scan.
    #   "mpmd" — per-stage programs (parallel/mpmd_pipeline.py, the MPMD
    #            pipeline-parallelism formulation of arXiv 2412.14374):
    #            each stage is its own jitted program on its pipe-slice
    #            submesh with stage-local params/optimizer shards (no
    #            leading [S, ...] vmap dim), driven by a host-side 1F1B
    #            scheduler with EXPLICIT inter-stage activation/gradient
    #            transfers — steady-state holds only min(S, M) in-flight
    #            microbatch activations instead of M, there is no
    #            vmap(spmd_axis_name) lowering (so sequence-parallel
    #            ring/ulysses attention composes — BACKLOG R8-2), and the
    #            per-stage-program shape is the multi-slice scale-out
    #            substrate. ``pipeline_stages``/``pipeline_microbatches``
    #            keep their meaning (``effective_microbatches`` is still
    #            the one resolution rule); grad accumulation folds into
    #            the same 1F1B run as additional microbatches.
    pipeline_impl: str = "spmd"  # spmd | mpmd
    # Circular (interleaved) schedule: each physical stage holds this many
    # non-adjacent layer groups ("virtual stages"), cutting the GPipe bubble
    # from (S-1)/(M+S-1) to (S-1)/(repeat*M + S-1) at the price of rotating
    # activations through the stages ``repeat`` times. 1 = plain GPipe.
    pipeline_circular_repeat: int = 1
    # Stage-granular rematerialization — 1F1B's activation residency in the
    # one-program GSPMD schedule: the backward saves only per-tick stage
    # BOUNDARY activations and recomputes stage internals (one extra stage
    # forward each, the usual remat trade). Finer-grained than
    # trainer.remat=full (which recomputes the whole pipeline timeline
    # inside the backward); composes with either schedule above.
    pipeline_stage_remat: bool = False
    # Per-block (per-layer) rematerialization on the nn.scan stack — the
    # selective policy tier between trainer.remat=dots (saves every matmul
    # output: O(L·B·T·D·(9+mlp_ratio)) residuals) and trainer.remat=full
    # (whole-loss checkpoint: low forward residency but the backward's
    # recompute materializes the full scan residual set at once). Each
    # scanned Block is checkpointed individually, so the backward holds the
    # L carry boundaries [B,T,D] plus ONE block's internals at a time:
    #   "full"      — save only the scan carry per layer (max memory cut,
    #                 one extra block-forward per layer of recompute);
    #   "save_attn" — additionally save each block's attention-sublayer
    #                 output ([B,T,D]/layer, checkpoint_name-tagged), so
    #                 the recompute pass skips re-running attention — the
    #                 quadratic part of the block — for ~2x the (tiny)
    #                 boundary residuals;
    #   "none"      — off.
    # Residual accounting across these modes: tools/pp_memory_audit.py
    # --flagship. Ignored by the pipeline path (pipeline_stage_remat is
    # that path's equivalent) and by decode.
    block_remat: str = "none"


@dataclass(frozen=True)
class VideoConfig:
    """Video-clip classifier (BASELINE config 5): ViT over tubelet embeddings
    of a frame stack — the TPU-native stand-in for the Ego4D recipe."""

    family: str = "video"
    image_size: int = 224
    num_frames: int = 8
    tubelet_size: tuple[int, int, int] = (2, 16, 16)  # (t, h, w)
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    num_classes: int = 400
    dropout: float = 0.0


# --------------------------------------------------------------------------
# Top-level experiment
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class ExperimentConfig:
    name: str = "experiment"
    model: Any = field(default_factory=MLPConfig)
    data: DataConfig = field(default_factory=DataConfig)
    trainer: TrainerConfig = field(default_factory=TrainerConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    precision: PrecisionConfig = field(default_factory=PrecisionConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    elastic: ElasticConfig = field(default_factory=ElasticConfig)
    workdir: str = "/tmp/frl_tpu_runs"

    def replace(self, **kw) -> "ExperimentConfig":
        return dataclasses.replace(self, **kw)
