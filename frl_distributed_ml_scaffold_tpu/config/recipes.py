"""The five reference recipes (BASELINE.json ``configs``), TPU-native.

Each maps a reference workload onto mesh axes + sharding annotations instead
of DDP/FSDP wrappers:

1. ``mnist_mlp``          — single-process trainer-loop smoke test.
2. ``imagenet_rn50_ddp``  — DP over the ``data`` axis (GSPMD inserts the
                            gradient allreduce that NCCL-DDP did), bf16.
3. ``imagenet_vitb_fsdp`` — params+grads+opt state full-sharded over the
                            ``fsdp`` axis + activation checkpointing.
4. ``gpt2_medium_zero1``  — grad accumulation + ZeRO-1 optimizer-state
                            sharding on a replicated-param transformer.
5. ``ego4d_video_elastic``— video-clip classifier with sharded checkpoints,
                            run under the elastic supervisor.

Plus additional recipes exercising TP/PP/SP/EP, which the task brief makes
first-class even though the reference configs don't name them.
"""

from __future__ import annotations

import dataclasses

from frl_distributed_ml_scaffold_tpu.config.registry import register_config
from frl_distributed_ml_scaffold_tpu.config.schema import (
    CheckpointConfig,
    DataConfig,
    ExperimentConfig,
    GPTConfig,
    MLPConfig,
    MeshConfig,
    MoEConfig,
    OptimizerConfig,
    ParallelConfig,
    PrecisionConfig,
    ResNetConfig,
    TrainerConfig,
    VideoConfig,
    ViTConfig,
)


@register_config("mnist_mlp")
def mnist_mlp() -> ExperimentConfig:
    """BASELINE config 1: MLP on MNIST, single-process smoke test."""
    return ExperimentConfig(
        name="mnist_mlp",
        model=MLPConfig(hidden_sizes=(512, 256), num_classes=10),
        data=DataConfig(name="mnist", global_batch_size=256, image_size=28, channels=1),
        trainer=TrainerConfig(total_steps=1500, log_every=100, eval_every=500, eval_steps=20),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-3, schedule="cosine", warmup_steps=50),
        mesh=MeshConfig(data=-1),
        precision=PrecisionConfig(policy="fp32"),
    )


@register_config("imagenet_rn50_ddp")
def imagenet_rn50_ddp() -> ExperimentConfig:
    """BASELINE config 2: ResNet-50 ImageNet, DP (the DDP equivalent), bf16."""
    return ExperimentConfig(
        name="imagenet_rn50_ddp",
        model=ResNetConfig(depth=50, num_classes=1000),
        data=DataConfig(
            name="imagenet", global_batch_size=1024, image_size=224, channels=3, num_classes=1000
        ),
        trainer=TrainerConfig(total_steps=112590, log_every=100, eval_every=5000),
        optimizer=OptimizerConfig(
            name="sgd", learning_rate=0.4, momentum=0.9, weight_decay=1e-4,
            schedule="cosine", warmup_steps=1565,
        ),
        mesh=MeshConfig(data=-1),
        parallel=ParallelConfig(param_sharding="replicated"),
        precision=PrecisionConfig(policy="bf16_mixed"),
    )


@register_config("imagenet_vitb_fsdp")
def imagenet_vitb_fsdp() -> ExperimentConfig:
    """BASELINE config 3: ViT-B/16 ImageNet, FSDP full-shard + remat."""
    return ExperimentConfig(
        name="imagenet_vitb_fsdp",
        model=ViTConfig(image_size=224, patch_size=16, hidden_dim=768, num_layers=12,
                        num_heads=12, num_classes=1000),
        data=DataConfig(
            name="imagenet", global_batch_size=1024, image_size=224, channels=3, num_classes=1000
        ),
        trainer=TrainerConfig(total_steps=93500, remat="full", log_every=100, eval_every=5000),
        optimizer=OptimizerConfig(
            name="adamw", learning_rate=3e-3, weight_decay=0.3,
            schedule="cosine", warmup_steps=10000, grad_clip_norm=1.0,
        ),
        mesh=MeshConfig(data=1, fsdp=-1),
        parallel=ParallelConfig(param_sharding="fsdp"),
        precision=PrecisionConfig(policy="bf16_mixed"),
    )


@register_config("gpt2_medium_zero1")
def gpt2_medium_zero1() -> ExperimentConfig:
    """BASELINE config 4: GPT-2-medium LM, grad-accum + ZeRO-1 opt sharding."""
    return ExperimentConfig(
        name="gpt2_medium_zero1",
        model=GPTConfig(
            vocab_size=50257, num_layers=24, num_heads=16, hidden_dim=1024, seq_len=1024
        ),
        data=DataConfig(
            name="lm_synthetic", global_batch_size=64, seq_len=1024, vocab_size=50257
        ),
        trainer=TrainerConfig(total_steps=100000, grad_accum=8, remat="dots", log_every=50),
        optimizer=OptimizerConfig(
            name="adamw", learning_rate=3e-4, weight_decay=0.1, b2=0.95,
            schedule="cosine", warmup_steps=2000, grad_clip_norm=1.0,
        ),
        mesh=MeshConfig(data=1, fsdp=-1),
        parallel=ParallelConfig(param_sharding="replicated", opt_sharding="zero1"),
        precision=PrecisionConfig(policy="bf16_mixed"),
    )


@register_config("ego4d_video_elastic")
def ego4d_video_elastic() -> ExperimentConfig:
    """BASELINE config 5: video-clip classifier, elastic + sharded ckpt resume."""
    return ExperimentConfig(
        name="ego4d_video_elastic",
        model=VideoConfig(num_frames=8, num_classes=400),
        data=DataConfig(
            name="video_synthetic", global_batch_size=64, image_size=224, channels=3,
            num_frames=8, num_classes=400,
        ),
        trainer=TrainerConfig(total_steps=30000, remat="full", log_every=50),
        optimizer=OptimizerConfig(
            name="adamw", learning_rate=1e-3, weight_decay=0.05,
            schedule="cosine", warmup_steps=2500, grad_clip_norm=1.0,
        ),
        mesh=MeshConfig(data=1, fsdp=-1),
        parallel=ParallelConfig(param_sharding="fsdp"),
        precision=PrecisionConfig(policy="bf16_mixed"),
        checkpoint=CheckpointConfig(enabled=True, save_every=500, max_to_keep=3),
    )


@register_config("gpt2_medium_adafactor")
def gpt2_medium_adafactor() -> ExperimentConfig:
    """Flagship LM on Adafactor: the measured-throughput variant of
    ``gpt2_medium_zero1``.

    Round-4 on-chip sweep (evidence_r4/perf_sweep2.log, TPU v5e, mb4
    remat=none): adafactor 31.7 vs adamw 30.3 samples/sec/chip (+4.6%),
    lion 31.6; and the factored second moment drops optimizer state from
    8 to ~4 bytes/param — on a 345M-param model that frees ~1.4 GB of
    HBM for activations/microbatch. Convergence sanity (tools/
    opt_convergence.py, evidence_r5/opt_convergence.log, pinned by
    tests/test_optimizers.py): adafactor's update is RELATIVE, so the
    adamw LR must NOT be inherited — at 3e-4 it barely moves (6.26→6.20
    in 300 steps); at its conventional 1e-2 it beats adamw's final loss
    outright (0.83 vs 4.07 on the proxy task; 3e-2 measured better still
    on the proxy, 1e-2 kept for scale-stability convention, T5/PaLM
    practice). De-risked at scale round 6 (ISSUE r6: the 0.48M proxy was
    judged too small to pin a recipe LR): the SAME grid at a 10.34M-param
    proxy for 1000 steps — evidence_r6/opt_convergence_10m.log, pinned by
    test_adafactor_recipe_lr_at_10m_proxy — confirms 1e-2 from both
    sides of the bracket: adafactor@1e-2 0.7274 final loss vs adamw@3e-4
    0.8519 (wins outright at scale too), while 3e-3 under-trains (2.68)
    and 3e-2 ties (0.7342) — at 10M params 1e-2 is already the optimum,
    not just the stability-conservative pick.
    The BASELINE-faithful recipe keeps adamw (reference
    config 4 parity); this variant is the recorded recipe-level decision
    for throughput-first runs. ZeRO-1 is redundant under adafactor's
    factored state, so opt_sharding stays for parity of comparison only.
    """
    base = gpt2_medium_zero1()
    return base.replace(
        name="gpt2_medium_adafactor",
        optimizer=dataclasses.replace(
            base.optimizer, name="adafactor", learning_rate=1e-2,
            weight_decay=0.0,
        ),
    )


@register_config("gpt2_medium_fsdp_overlap")
def gpt2_medium_fsdp_overlap() -> ExperimentConfig:
    """Flagship LM under overlap-scheduled FSDP (parallel/fsdp_overlap.py):
    params full-sharded over ``fsdp`` with EXPLICIT per-block all-gather /
    reduce-scatter and one-block-ahead prefetch, instead of GSPMD's
    gather-up-front schedule. The sweep config for the on-chip A/B
    (tools/perf_sweep.py gpt2_fsdp_overlap, queued in BACKLOG): same
    operating point as the gpt2_medium_zero1 protocol row so the step-time
    delta reads as the scheduling win alone. Correctness is sim-gated in
    tests/test_fsdp_overlap.py (numerics vs the GSPMD FSDP path, blockwise
    gather jaxpr assertion, mesh compositions)."""
    base = gpt2_medium_zero1()
    return base.replace(
        name="gpt2_medium_fsdp_overlap",
        mesh=MeshConfig(data=1, fsdp=-1),
        parallel=ParallelConfig(
            param_sharding="fsdp",
            opt_sharding="like_params",  # opt state inherits the fsdp shards
            fsdp_overlap=True,
            fsdp_prefetch=1,
        ),
    )


@register_config("gpt2_medium_tp_overlap")
def gpt2_medium_tp_overlap() -> ExperimentConfig:
    """Flagship LM under latency-hiding tensor parallelism
    (parallel/tp_overlap.py): the four per-block TP matmuls run as
    bidirectional collective-matmul rings (ppermute-chained blocks, comm
    hidden under compute) with the residual stream sequence-sharded over
    the model axis, instead of GSPMD's monolithic per-layer allreduces.
    The sweep config for the on-chip A/B (tools/perf_sweep.py
    gpt2_tp_overlap, queued in BACKLOG R7): same operating point as the
    gpt2_tp showcase so the step-time delta reads as the scheduling win
    alone. Correctness is sim-gated in tests/test_tp_overlap.py (numerics
    vs the GSPMD TP path, blockwise-ppermute jaxpr pins, mesh
    compositions)."""
    base = gpt2_medium_zero1()
    return base.replace(
        name="gpt2_medium_tp_overlap",
        mesh=MeshConfig(data=1, model=-1),
        parallel=ParallelConfig(
            param_sharding="replicated",
            opt_sharding="zero1",
            tp_overlap=True,
        ),
    )


@register_config("gpt2_medium_tp_overlap_int8")
def gpt2_medium_tp_overlap_int8() -> ExperimentConfig:
    """The low-precision fast path on the tp_overlap flagship: the four
    per-block collective-matmul rings ppermute int8 chunks + scales and
    run their matmuls on the MXU's 8-bit path (per-tensor activation /
    per-channel weight scales, bf16 master weights, straight-through
    grads — ops/quantization.py, parallel.low_precision). Comm bytes on
    the model-axis collective-permute class shrink with the element width
    (graft-lint pins it per dtype: a ring that ppermutes wide floats
    under this recipe is a lint error). Numerics vs the bf16/fp32 rings
    are tolerance-gated in tests/test_low_precision.py; the on-chip A/B
    rides the tp_overlap sweep slot (BACKLOG R7)."""
    base = gpt2_medium_tp_overlap()
    return base.replace(
        name="gpt2_medium_tp_overlap_int8",
        parallel=dataclasses.replace(base.parallel, low_precision="int8"),
    )


@register_config("gpt2_medium_fsdp_tp_overlap")
def gpt2_medium_fsdp_tp_overlap() -> ExperimentConfig:
    """The composed overlap schedule (parallel/schedule.py, ROADMAP item
    2's payoff case): BOTH explicit schedules in one scan body — params
    full-sharded over ``fsdp`` with blockwise in-scan all-gather /
    reduce-scatter (one-block-ahead prefetch), AND the four per-block TP
    matmuls running as bidirectional collective-matmul ppermute rings
    over ``model`` — with ZERO monolithic all_gathers in the step
    (jaxpr-pinned via ``analysis.pins.assert_schedule``; the declared
    schedule is ``gather(fsdp,block,prefetch=1)+scatter(fsdp)+
    gather(model,ring_chunk)+scatter(model)``). Correctness is sim-gated
    in tests/test_schedule.py (numerics vs the all-GSPMD fsdp x model
    path, program identity vs the explicit declaration string); the
    on-chip A/B rides ``tools/perf_sweep.py gpt2_fsdp_tp_overlap``
    (BACKLOG relay window, next to R6-1/R7-1)."""
    base = gpt2_medium_zero1()
    return base.replace(
        name="gpt2_medium_fsdp_tp_overlap",
        mesh=MeshConfig(data=1, fsdp=-1, model=2),
        parallel=ParallelConfig(
            param_sharding="fsdp",
            opt_sharding="like_params",
            fsdp_overlap=True,
            fsdp_prefetch=1,
            tp_overlap=True,
        ),
    )


@register_config("gpt2_medium_fsdp_tp_overlap_int8")
def gpt2_medium_fsdp_tp_overlap_int8() -> ExperimentConfig:
    """The composed schedule with low precision as a transfer attribute:
    same blockwise fsdp gathers, but the model-axis rings ppermute int8
    chunks + scales (``lowp=int8`` on the ring pair). Census-pinned via
    ``assert_schedule`` to >= 3.5x lower model-axis ppermute bytes than
    the fp32 composed path (4x element width minus scale traffic);
    numerics tolerance-gated through the shared low-precision bands
    (docs/perf_playbook.md "Low-precision fast path")."""
    base = gpt2_medium_fsdp_tp_overlap()
    return base.replace(
        name="gpt2_medium_fsdp_tp_overlap_int8",
        parallel=dataclasses.replace(base.parallel, low_precision="int8"),
    )


# ----- task-required parallelism showcases beyond the reference configs -----


@register_config("gpt2_tp")
def gpt2_tp() -> ExperimentConfig:
    """Tensor-parallel transformer (SURVEY C6): Megatron column/row sharding."""
    base = gpt2_medium_zero1()
    return base.replace(
        name="gpt2_tp",
        mesh=MeshConfig(data=-1, model=2),
        parallel=ParallelConfig(param_sharding="replicated"),
        trainer=base.trainer,
    )


@register_config("gpt2_ring")
def gpt2_ring() -> ExperimentConfig:
    """Sequence-parallel long-context LM (SURVEY C8): ring attention."""
    base = gpt2_medium_zero1()
    return base.replace(
        name="gpt2_ring",
        model=GPTConfig(
            vocab_size=50257, num_layers=24, num_heads=16, hidden_dim=1024,
            seq_len=8192, attention="ring",
        ),
        data=DataConfig(name="lm_synthetic", global_batch_size=8, seq_len=8192),
        mesh=MeshConfig(data=-1, seq=4),
        parallel=ParallelConfig(param_sharding="replicated", sequence="ring"),
        # Long context already divides the batch finely; no microbatching.
        trainer=dataclasses.replace(base.trainer, grad_accum=1),
    )


@register_config("gpt2_long")
def gpt2_long() -> ExperimentConfig:
    """Single-chip long context (SURVEY C8 complement to ``gpt2_ring``):
    8k tokens through the Pallas flash kernel (O(block) memory, measured
    to 32k on one v5e — BASELINE.md) with the chunked-vocab loss and full
    remat keeping activations off HBM. No sequence axis needed until the
    context outgrows the chip."""
    base = gpt2_medium_zero1()
    return base.replace(
        name="gpt2_long",
        model=GPTConfig(
            vocab_size=50257, num_layers=24, num_heads=16, hidden_dim=1024,
            seq_len=8192, attention="flash", lm_loss_chunk=256,
        ),
        data=DataConfig(name="lm_synthetic", global_batch_size=8, seq_len=8192),
        mesh=MeshConfig(data=-1),
        parallel=ParallelConfig(param_sharding="replicated"),
        trainer=dataclasses.replace(base.trainer, grad_accum=8, remat="full"),
    )


@register_config("gpt2_moe")
def gpt2_moe() -> ExperimentConfig:
    """Expert-parallel MoE LM (SURVEY C9)."""
    base = gpt2_medium_zero1()
    return base.replace(
        name="gpt2_moe",
        model=GPTConfig(
            vocab_size=50257, num_layers=12, num_heads=16, hidden_dim=1024,
            seq_len=1024, moe=MoEConfig(num_experts=8, top_k=2),
        ),
        mesh=MeshConfig(data=-1, expert=4),
        parallel=ParallelConfig(param_sharding="replicated"),
    )


@register_config("gpt2_pp")
def gpt2_pp() -> ExperimentConfig:
    """Pipeline-parallel LM (SURVEY C7): 4 stages over the ``pipe`` axis,
    GPipe schedule with 8 microbatches (bubble = 3/11 of a step)."""
    base = gpt2_medium_zero1()
    return base.replace(
        name="gpt2_pp",
        model=GPTConfig(
            vocab_size=50257, num_layers=24, num_heads=16, hidden_dim=1024,
            seq_len=1024, pipeline_stages=4, pipeline_microbatches=8,
        ),
        mesh=MeshConfig(data=-1, pipe=4),
        parallel=ParallelConfig(param_sharding="replicated"),
        trainer=dataclasses.replace(base.trainer, grad_accum=1),
    )


@register_config("gpt2_pipeline_mpmd")
def gpt2_pipeline_mpmd() -> ExperimentConfig:
    """MPMD pipeline parallelism (ISSUE 14): the ``gpt2_pp`` operating
    point on the per-stage-program backend (parallel/mpmd_pipeline.py) —
    each of the 4 stages is its own jitted program on its pipe-slice
    submesh, a host-side 1F1B driver moves activations/gradients as
    explicit ``device_put`` transfers, and steady state holds min(S, M)=4
    in-flight microbatch activations instead of GPipe's 8. Loss/token
    parity with the SPMD backend is sim-gated in
    tests/test_mpmd_pipeline.py; the step-time A/B rides
    ``tools/perf_sweep.py gpt2_pipeline_mpmd`` (BACKLOG R17-1)."""
    base = gpt2_pp()
    return base.replace(
        name="gpt2_pipeline_mpmd",
        model=dataclasses.replace(base.model, pipeline_impl="mpmd"),
    )


@register_config("gpt2_pp_circular")
def gpt2_pp_circular() -> ExperimentConfig:
    """Circular (interleaved) pipeline: same 4 physical stages as
    ``gpt2_pp`` but each holds 2 virtual layer groups, cutting the bubble
    from 3/11 to 3/19 of a step at the cost of rotating activations
    through the ring twice."""
    base = gpt2_pp()
    return base.replace(
        name="gpt2_pp_circular",
        model=dataclasses.replace(base.model, pipeline_circular_repeat=2),
    )


@register_config("imagenet_rn101_ddp")
def imagenet_rn101_ddp() -> ExperimentConfig:
    """Deeper-variant showcase: ResNet-101 on the RN50 recipe (the torch
    zoo's standard scale-up; same schedule, depth=101 bottleneck stacks)."""
    base = imagenet_rn50_ddp()
    return base.replace(
        name="imagenet_rn101_ddp",
        model=dataclasses.replace(base.model, depth=101),
    )


@register_config("imagenet_vitl_fsdp")
def imagenet_vitl_fsdp() -> ExperimentConfig:
    """Scale-up showcase: ViT-L/16 (307M params) on the ViT-B FSDP recipe —
    the config where FSDP sharding and remat stop being optional on small
    slices."""
    base = imagenet_vitb_fsdp()
    return base.replace(
        name="imagenet_vitl_fsdp",
        model=dataclasses.replace(
            base.model, hidden_dim=1024, num_layers=24, num_heads=16
        ),
    )


@register_config("gpt2_medium_serve")
def gpt2_medium_serve() -> ExperimentConfig:
    """Flash-decode serving operating point (the BACKLOG R8-1 on-chip
    A/B): the gpt2_medium flagship weights served through
    ``serving/engine.py`` with the fused split-KV decode kernel
    (``model.decode_attention=flash``, the default) and the KV cache
    model-sharded over a 2-way ``model`` axis. ``tools/serve_bench.py``
    measures the four (decode impl x cache sharding) arms; this recipe
    records the mesh/model shape those arms load."""
    base = gpt2_medium_zero1()
    return base.replace(
        name="gpt2_medium_serve",
        model=dataclasses.replace(base.model, decode_attention="flash"),
        mesh=MeshConfig(data=-1, fsdp=1, model=2),
        parallel=ParallelConfig(param_sharding="replicated"),
    )
