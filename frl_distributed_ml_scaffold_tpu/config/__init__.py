"""Config system (SURVEY C17): typed dataclass tree + dotted-path overrides.

The reference scaffold selects a per-recipe config and lets the CLI override
fields; we reproduce that with plain dataclasses (ml_collections is not in
this image) — every field is typed, every override is validated against the
schema, and configs serialize to JSON for run records.
"""

from frl_distributed_ml_scaffold_tpu.config.core import (
    apply_overrides,
    config_to_dict,
    config_from_dict,
    pretty_config,
)
from frl_distributed_ml_scaffold_tpu.config.schema import (
    CheckpointConfig,
    DataConfig,
    ExperimentConfig,
    GPTConfig,
    MLPConfig,
    MeshConfig,
    MoEConfig,
    OptimizerConfig,
    PrecisionConfig,
    ResNetConfig,
    TrainerConfig,
    VideoConfig,
    ViTConfig,
)
from frl_distributed_ml_scaffold_tpu.config.registry import (
    get_config,
    list_configs,
    register_config,
)
