"""Dataclass config machinery: dotted-path overrides, (de)serialization.

``apply_overrides(cfg, ["trainer.lr=3e-4", "mesh.data=8"])`` returns a new
config with those fields replaced, type-coerced against the dataclass schema.
Unknown paths and un-coercible values raise — silent config typos are how
training runs die at step 80k.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Optional, Union, get_args, get_origin


def _coerce(raw: str, typ: Any) -> Any:
    """Parse a CLI string into the target annotation type."""
    origin = get_origin(typ)
    if origin is Union:  # Optional[X] and unions
        args = [a for a in get_args(typ) if a is not type(None)]
        if raw.lower() in ("none", "null"):
            return None
        last_err: Exception | None = None
        for a in args:
            try:
                return _coerce(raw, a)
            except (ValueError, TypeError) as e:
                last_err = e
        raise ValueError(f"cannot parse {raw!r} as {typ}: {last_err}")
    if origin in (tuple, list):
        inner = get_args(typ)
        items = [s for s in raw.strip("()[]").split(",") if s.strip()]
        if origin is tuple and inner and inner[-1] is not Ellipsis:
            coerced = [_coerce(s.strip(), t) for s, t in zip(items, inner)]
            return tuple(coerced)
        elem_t = inner[0] if inner else str
        coerced = [_coerce(s.strip(), elem_t) for s in items]
        return tuple(coerced) if origin is tuple else coerced
    if origin is dict:
        return json.loads(raw)
    if typ is bool:
        if raw.lower() in ("true", "1", "yes"):
            return True
        if raw.lower() in ("false", "0", "no"):
            return False
        raise ValueError(f"cannot parse {raw!r} as bool")
    if typ is int:
        return int(raw)
    if typ is float:
        return float(raw)
    if typ is str:
        return raw
    if typ is Any:
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            return raw
    if dataclasses.is_dataclass(typ):
        return config_from_dict(typ, json.loads(raw))
    raise TypeError(f"unsupported config field type {typ} for value {raw!r}")


def _field_type(cfg: Any, name: str) -> Any:
    for f in dataclasses.fields(cfg):
        if f.name == name:
            return f.type if not isinstance(f.type, str) else _resolve_str_type(cfg, f.type)
    raise KeyError(
        f"{type(cfg).__name__} has no field {name!r} "
        f"(fields: {[f.name for f in dataclasses.fields(cfg)]})"
    )


def _resolve_str_type(cfg: Any, ann: str) -> Any:
    """Resolve string annotations (from __future__ annotations)."""
    import sys
    import typing

    mod = sys.modules.get(type(cfg).__module__)
    ns = dict(vars(typing))
    if mod is not None:
        ns.update(vars(mod))
    return eval(ann, ns)  # noqa: S307 — schema-controlled input


def apply_overrides(cfg: Any, overrides: list[str]) -> Any:
    """Apply ``"a.b.c=value"`` overrides, returning a new config."""
    for ov in overrides:
        if "=" not in ov:
            raise ValueError(f"override {ov!r} must look like path.to.field=value")
        path, raw = ov.split("=", 1)
        cfg = _set_path(cfg, path.strip().lstrip("-").split("."), raw.strip())
    return cfg


def _set_path(cfg: Any, parts: list[str], raw: str) -> Any:
    if not dataclasses.is_dataclass(cfg):
        raise TypeError(f"cannot descend into non-dataclass {type(cfg)} at {parts}")
    head, rest = parts[0], parts[1:]
    if rest:
        child = getattr(cfg, head)
        if child is None:
            raise ValueError(f"cannot override field of None sub-config {head!r}")
        new_child = _set_path(child, rest, raw)
        return dataclasses.replace(cfg, **{head: new_child})
    typ = _field_type(cfg, head)
    return dataclasses.replace(cfg, **{head: _coerce(raw, typ)})


def config_to_dict(cfg: Any) -> Any:
    """Recursive dataclass → plain-dict conversion (JSON-safe)."""
    if dataclasses.is_dataclass(cfg):
        return {f.name: config_to_dict(getattr(cfg, f.name)) for f in dataclasses.fields(cfg)}
    if isinstance(cfg, (list, tuple)):
        return [config_to_dict(x) for x in cfg]
    if isinstance(cfg, dict):
        return {k: config_to_dict(v) for k, v in cfg.items()}
    return cfg


def config_from_dict(typ: Any, data: dict) -> Any:
    """Inverse of config_to_dict for a known dataclass type."""
    if not dataclasses.is_dataclass(typ):
        return data
    import typing

    hints = typing.get_type_hints(typ)
    kwargs = {}
    for f in dataclasses.fields(typ):
        if f.name not in data:
            continue
        v = data[f.name]
        ft = hints.get(f.name)
        if ft is not None and dataclasses.is_dataclass(ft) and isinstance(v, dict):
            kwargs[f.name] = config_from_dict(ft, v)
        elif (
            ft in (Any, None)
            and isinstance(v, dict)
            and "family" in v
        ):
            # Polymorphic model field: dispatch on the `family` tag.
            kwargs[f.name] = _model_config_from_dict(v)
        elif isinstance(v, list):
            kwargs[f.name] = tuple(v) if _is_tuple_field(typ, f) else v
        else:
            kwargs[f.name] = v
    return typ(**kwargs)


def _model_config_from_dict(v: dict) -> Any:
    from frl_distributed_ml_scaffold_tpu.config import schema

    families = {
        "mlp": schema.MLPConfig,
        "resnet": schema.ResNetConfig,
        "vit": schema.ViTConfig,
        "gpt": schema.GPTConfig,
        "video": schema.VideoConfig,
    }
    return config_from_dict(families[v["family"]], v)


def _is_tuple_field(typ: Any, f: dataclasses.Field) -> bool:
    ann = f.type
    if isinstance(ann, str):
        return ann.startswith(("tuple", "Tuple"))
    return get_origin(ann) is tuple


def pretty_config(cfg: Any) -> str:
    return json.dumps(config_to_dict(cfg), indent=2, default=str)
