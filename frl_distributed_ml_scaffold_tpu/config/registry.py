"""Named-config registry: ``get_config("imagenet_rn50_ddp")`` → ExperimentConfig.

Mirrors the reference scaffold's per-recipe config selection. Recipes register
themselves at import; config/recipes.py holds the five BASELINE.json
acceptance configs.
"""

from __future__ import annotations

from typing import Callable

from frl_distributed_ml_scaffold_tpu.config.schema import ExperimentConfig

_REGISTRY: dict[str, Callable[[], ExperimentConfig]] = {}


def register_config(name: str):
    """Decorator: register a zero-arg builder returning an ExperimentConfig."""

    def deco(fn: Callable[[], ExperimentConfig]):
        if name in _REGISTRY:
            raise ValueError(f"config {name!r} already registered")
        _REGISTRY[name] = fn
        return fn

    return deco


def get_config(name: str) -> ExperimentConfig:
    _ensure_recipes_loaded()
    if name not in _REGISTRY:
        raise KeyError(f"unknown config {name!r}; available: {sorted(_REGISTRY)}")
    return _REGISTRY[name]()


def list_configs() -> list[str]:
    _ensure_recipes_loaded()
    return sorted(_REGISTRY)


def _ensure_recipes_loaded() -> None:
    # Import side effect registers the built-in recipes exactly once.
    from frl_distributed_ml_scaffold_tpu.config import recipes  # noqa: F401
