"""Stall watchdog: a host thread that notices when progress stops.

The failure mode it exists for: a deadlocked collective, a hung host
callback, or a wedged data loader leaves the process ALIVE but the
step/decode loop silent — the logs just stop, and on a fleet that reads
as "no news". The watchdog turns silence into a report: if no ``beat()``
lands within ``timeout_s`` it

1. increments the registry's ``stalls_total`` counter (the alarmable
   signal — a scrape sees it even if the dump is unreachable),
2. appends a dump to ``dump_path``: ``faulthandler`` tracebacks of every
   thread (where is the loop actually stuck?), the live metric snapshot,
   and the timeline tail (what last completed), and
3. logs an ERROR through the framework logger.

It then stays quiet until the NEXT beat re-arms it — one report per
silence, not one per poll. ``beat()`` is a single monotonic-clock store,
cheap enough for per-step (or per-decode-step) calls; the watchdog never
touches device state, so it cannot itself deadlock on the thing it is
diagnosing.
"""

from __future__ import annotations

import faulthandler
import json
import threading
import time
from typing import Any

from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger


class StallWatchdog:
    """Fire when no ``beat()`` arrives within ``timeout_s`` (see module
    docstring). ``timeout_s <= 0`` constructs a disabled no-op watchdog
    (no thread), so callers can wire it unconditionally.

    ``first_beat_scale`` stretches the deadline until the FIRST beat
    lands: beats only start flowing once dispatch does, so the initial
    silence includes XLA compile time — sizing ``timeout_s`` to steady-
    state steps used to false-fire on step 0 (the compile-time warning
    docs/operations.md carried). With the default ~5x grace, a deadline
    sized to the slowest expected *step* tolerates the compile; once any
    beat arrives the normal deadline applies.
    """

    def __init__(
        self,
        timeout_s: float,
        *,
        name: str = "train",
        registry: Any | None = None,
        timeline: Any | None = None,
        dump_path: str | None = None,
        poll_s: float | None = None,
        first_beat_scale: float = 5.0,
    ):
        self.timeout_s = float(timeout_s)
        self.first_beat_scale = max(float(first_beat_scale), 1.0)
        self._beaten = False  # first beat seen -> normal deadline
        self.name = name
        self._registry = registry
        self._timeline = timeline
        self._dump_path = dump_path
        self._counter = (
            registry.counter(
                "stalls_total",
                help="watchdog firings: no progress within the deadline",
            )
            if registry is not None
            else None
        )
        self._last = time.monotonic()
        self._armed = True  # one report per silence window
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if self.timeout_s > 0:
            poll = poll_s if poll_s is not None else max(self.timeout_s / 4, 0.25)
            self._thread = threading.Thread(
                target=self._loop,
                args=(max(poll, 0.005),),
                name=f"stall-watchdog-{name}",
                daemon=True,
            )
            self._thread.start()

    @property
    def enabled(self) -> bool:
        return self._thread is not None

    def beat(self) -> None:
        """Progress landed; re-arm. Host-side store only — never call
        from traced code (graft-lint hygiene enforces the same for the
        metric mutations this class makes)."""
        self._last = time.monotonic()
        self._beaten = True
        self._armed = True

    @property
    def fired(self) -> int:
        return int(self._counter.value) if self._counter is not None else 0

    def _loop(self, poll: float) -> None:
        while not self._stop.wait(poll):
            # Read _armed BEFORE _beaten BEFORE _last — the mirror of
            # beat()'s _last-then-_beaten-then-_armed write order.
            # Reading them the other way around can pair a stale _last
            # with a freshly-set _armed and fire a spurious "stall" right
            # after progress resumed. (A stale _beaten=False only widens
            # the deadline — delays a fire, never invents one.)
            armed = self._armed
            deadline = self.timeout_s * (
                1.0 if self._beaten else self.first_beat_scale
            )
            silent = time.monotonic() - self._last
            if armed and silent > deadline:
                self._armed = False  # quiet until the next beat
                try:
                    self._fire(silent, deadline)
                except Exception as e:  # the reporter must never kill a run
                    get_logger().warning(
                        "watchdog[%s]: stall report failed (%s)", self.name, e
                    )

    def _fire(self, silent_s: float, deadline_s: float | None = None) -> None:
        if self._counter is not None:
            self._counter.inc()
        get_logger().error(
            "watchdog[%s]: no progress for %.1fs (deadline %.1fs)%s",
            self.name,
            silent_s,
            deadline_s if deadline_s is not None else self.timeout_s,
            f" — dumping to {self._dump_path}" if self._dump_path else "",
        )
        if self._dump_path is None:
            return
        with open(self._dump_path, "a") as fh:
            fh.write(
                f"=== watchdog[{self.name}] stall at {time.time():.3f}: "
                f"no progress for {silent_s:.1f}s ===\n"
            )
            faulthandler.dump_traceback(file=fh, all_threads=True)
            if self._registry is not None:
                fh.write("\n--- metric snapshot ---\n")
                fh.write(json.dumps(self._registry.snapshot(), indent=1))
                fh.write("\n")
            if self._timeline is not None:
                fh.write("--- timeline tail ---\n")
                for rec in self._timeline.tail():
                    fh.write(json.dumps(rec) + "\n")
            fh.flush()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "StallWatchdog":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()
