"""One metrics layer for the train, serve, and elastic tiers (ISSUE 7).

The repo's north-star metric is host-measured (samples/sec/chip, e2e step
time — BASELINE.md protocol), and with the off-chip bench relay down,
host-side telemetry is the only live measurement channel. This module is
the common vocabulary the three tiers publish through:

- **Counter** — monotone event counts (``decode_steps_total``,
  ``stalls_total``). ``inc()`` only.
- **Gauge** — last-written level (``slot_occupancy``, ``queue_depth``,
  ``hbm_in_use_gib``). ``set()`` only.
- **Histogram** — latency distributions over FIXED log2 buckets
  (``LOG2_LATENCY_BUCKETS_S``): every histogram in every tier buckets
  identically, so snapshots from different runs/processes merge by
  summing counts and percentile tables are comparable across PRs.
  ``quantile()`` interpolates linearly inside the containing bucket —
  at log2 granularity the estimate is within 2x of truth by
  construction, which is the resolution the step-time/TTFT/TPOT tables
  need (exact per-request latencies still ride ``Completion``).

Everything is HOST-SIDE state around jitted pure functions (the veScale
single-controller argument, arXiv 2509.07003): metric mutations must
never appear inside traced code — enforced statically by the graft-lint
hygiene pass (``metrics-in-traced`` error), not hoped. A registry can be
constructed ``enabled=False``: the same metric objects exist, mutators
no-op — the telemetry-off arm of the overhead pin
(tests/test_telemetry.py) is shape-identical to the on arm.

Export goes two ways, both pull-based snapshots of the same state:
``snapshot()`` (a JSON-able dict, written through the existing
``JsonlWriter`` — the record of truth) and ``prometheus_text()`` (the
Prometheus text exposition format, golden-tested byte-for-byte) for
scrape endpoints / sidecar files.
"""

from __future__ import annotations

import threading
from typing import Any, Iterable, Mapping

#: Fixed log2 latency buckets, in seconds: 2^-17 (~7.6 us) .. 2^6 (64 s).
#: One shared ladder for every latency histogram in the repo — merges and
#: cross-run diffs stay well-defined (see module docstring).
LOG2_LATENCY_BUCKETS_S: tuple[float, ...] = tuple(
    float(2.0**e) for e in range(-17, 7)
)


def _fmt(x: float) -> str:
    """Deterministic float rendering for the text format (golden-tested):
    integers print bare, everything else via repr-shortest %.10g."""
    if x == int(x) and abs(x) < 1e15:
        return str(int(x))
    return format(x, ".10g")


class Counter:
    """Monotone event counter."""

    def __init__(self, registry: "MetricsRegistry", name: str, help: str):
        self._reg = registry
        self.name = name
        self.help = help
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if not self._reg.enabled:
            return
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) < 0")
        with self._reg._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def _reset(self) -> None:
        self._value = 0.0


class Gauge:
    """Last-written level."""

    def __init__(self, registry: "MetricsRegistry", name: str, help: str):
        self._reg = registry
        self.name = name
        self.help = help
        self._value = 0.0

    def set(self, v: float) -> None:
        if not self._reg.enabled:
            return
        with self._reg._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        return self._value

    def _reset(self) -> None:
        self._value = 0.0


class Histogram:
    """Fixed-bucket histogram (log2 latency ladder by default).

    Observations land in the first bucket whose upper bound is >= the
    value; values past the last bound land in the implicit +Inf bucket.
    """

    def __init__(
        self,
        registry: "MetricsRegistry",
        name: str,
        help: str,
        buckets: Iterable[float] = LOG2_LATENCY_BUCKETS_S,
    ):
        self._reg = registry
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {self.name}: no buckets")
        self._counts = [0] * (len(self.buckets) + 1)  # +1: +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        if not self._reg.enabled:
            return
        v = float(v)
        with self._reg._lock:
            i = 0
            for i, b in enumerate(self.buckets):  # noqa: B007
                if v <= b:
                    break
            else:
                i = len(self.buckets)
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate in [bucket lo, bucket hi].

        The +Inf bucket clamps to the last finite bound (a deliberate
        floor-of-truth: the table can understate, never invent, a tail).
        Empty histogram -> 0.0.
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile({q}) outside [0, 1]")
        if self._count == 0:
            return 0.0
        target = q * self._count
        cum = 0.0
        for i, n in enumerate(self._counts):
            if n == 0:
                continue
            if cum + n >= target:
                if i >= len(self.buckets):  # +Inf bucket
                    return self.buckets[-1]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                frac = (target - cum) / n
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += n
        return self.buckets[-1]

    def _reset(self) -> None:
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0


class MetricsRegistry:
    """Get-or-create home of a tier's metrics; the snapshot/export unit.

    One registry per publishing component (a ``ServingEngine``, a
    ``Trainer.fit`` run, an elastic supervisor) — no process-global
    state, so tests and multi-engine hosts never share counters.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}

    def _get_or_create(self, cls, name: str, **kw: Any):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(self, name, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help=help)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Iterable[float] = LOG2_LATENCY_BUCKETS_S,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help=help, buckets=buckets)

    def reset(self) -> None:
        """Zero every metric, keeping registrations (the serve_bench
        warm-up discipline: compile-polluted observations are dropped
        before the measured pass — ``ServingEngine.reset_cache``)."""
        with self._lock:
            for m in self._metrics.values():
                m._reset()

    def snapshot(self) -> dict[str, Any]:
        """JSON-able state of every metric, sorted by name.

        Counters/gauges flatten to their value; histograms carry count,
        sum, the p50/p95/p99 estimates AND the raw cumulative bucket
        counts — so offline tools (tools/telemetry_report.py) can
        recompute any quantile and merge runs without re-observing.
        """
        with self._lock:
            out: dict[str, Any] = {}
            for name in sorted(self._metrics):
                m = self._metrics[name]
                if isinstance(m, Histogram):
                    cum, buckets = 0, {}
                    for b, n in zip(m.buckets, m._counts):
                        cum += n
                        buckets[_fmt(b)] = cum
                    buckets["+Inf"] = m._count
                    out[name] = {
                        "type": "histogram",
                        "count": m._count,
                        "sum": m._sum,
                        "p50": m.quantile(0.50),
                        "p95": m.quantile(0.95),
                        "p99": m.quantile(0.99),
                        "buckets": buckets,
                    }
                else:
                    out[name] = m.value
            return out


def prometheus_text(registry: MetricsRegistry) -> str:
    """Prometheus text exposition format snapshot of ``registry``.

    Deterministic (metrics sorted by name, floats via ``_fmt``) so the
    output is golden-testable byte-for-byte; histograms emit cumulative
    ``_bucket{le=...}`` series plus ``_sum``/``_count`` per convention.
    """
    lines: list[str] = []
    # Render ENTIRELY under the registry lock (like ``snapshot``): the
    # watchdog/heartbeat threads mutate ``_counts``/``_count``/``_sum``
    # under it, and rendering after only copying the dict (the previous
    # shape) could scrape a histogram whose ``_bucket`` rows disagree
    # with its ``_count`` — a torn read the concurrency lint's guarded-
    # attribute rule exists to keep out of reports.
    with registry._lock:
        for name in sorted(registry._metrics):
            m = registry._metrics[name]
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_fmt(m.value)}")
            else:
                lines.append(f"# TYPE {name} histogram")
                cum = 0
                for b, n in zip(m.buckets, m._counts):
                    cum += n
                    lines.append(f'{name}_bucket{{le="{_fmt(b)}"}} {cum}')
                lines.append(f'{name}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{name}_sum {_fmt(m.sum)}")
                lines.append(f"{name}_count {m.count}")
    return "\n".join(lines) + "\n"


def write_prometheus_file(registry: MetricsRegistry, path: str) -> None:
    """Atomically publish the snapshot as a scrape-able sidecar file
    (node-exporter textfile-collector style — the deployment shape that
    needs no listener port on a TPU host). Primary-process gating is the
    caller's job; this just never publishes a torn file."""
    import os

    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(prometheus_text(registry))
    os.replace(tmp, path)


def jsonl_record(
    registry: MetricsRegistry,
    *,
    step: int | None = None,
    extra: Mapping[str, Any] | None = None,
) -> dict[str, Any]:
    """The telemetry JSONL record shape (``{"event": "telemetry", ...}``)
    shared by the trainer exporter and tools/telemetry_report.py."""
    import time

    rec: dict[str, Any] = {"event": "telemetry", "ts": round(time.time(), 3)}
    if step is not None:
        rec["step"] = int(step)
    if extra:
        rec.update(extra)
    rec["metrics"] = registry.snapshot()
    return rec
