"""End-to-end host-side tracing: spans across serve, train, and elastic.

Where the metrics registry aggregates and the ``Timeline`` remembers
order, a TRACE remembers **causality**: one connected tree of named,
timed spans per unit of work — a serving request from enqueue to retire,
a training step from data-wait to dispatch, a supervision incident from
child death to re-formed world. When a TTFT p99 spikes or a step time
drifts, the trace answers *which phase* spent the time, not just that
time was spent (the veScale structured-tracing shape, arXiv 2509.07003).

Vocabulary:

- **trace**: an integer lane id, allocated by ``new_trace()`` — one per
  causally-connected unit (a request, a fit run, a supervisor session).
  Every span carries its trace id; the Chrome export renders each trace
  as its own named thread lane.
- **span**: a named ``[t0, t0+dur]`` interval with a ``span_id`` and an
  optional ``parent`` span id. Root spans (parent ``None``) anchor the
  tree; children attach explicitly (cross-call lifetimes: the serving
  engine holds a request's root span open from ``submit`` to retire) or
  implicitly (``span()`` context managers nest via a context variable).

Three ways to record, all host-side-only (the graft-lint hygiene pass
rejects any of them inside traced code, same contract as metrics):

- ``with tracer.span(name, ...):`` — scoped span; enters a
  ``jax.profiler.TraceAnnotation`` (or ``StepTraceAnnotation`` when
  ``step_num`` is passed) when ``annotate=True``, so host spans line up
  with the device timeline the profiler window
  (``trainer.profile_steps``) captures.
- ``span = tracer.begin(name, ...); ...; span.end()`` — cross-call
  lifetime (no profiler annotation: annotations require strict nesting,
  which overlapping request roots cannot promise).
- ``tracer.emit(name, t0=..., dur_s=..., ...)`` — a span recorded after
  the fact with explicit clock values (queue-wait is only known at
  admission; the per-slot decode tick shares the engine step's timing).

Finished spans land in a ring buffer (``capacity`` newest survive — a
stalled exporter can never grow the host heap, the ``Timeline``
discipline) and, when a ``timeline`` is attached, are ALSO teed into it
as plain timeline events — so the existing ``telemetry.jsonl`` drain
path keeps carrying the phase records while the ring holds the span
tree for ``write_chrome_trace()``. The export is Chrome-trace-event
JSON (``{"traceEvents": [...]}``), loadable by ``chrome://tracing`` and
ui.perfetto.dev.

``enabled=False`` constructs a no-op tracer: every call returns the
shared null span, no clock reads, no profiler annotations — the
tracing-off arm of the serve overhead pin (tests/test_tracing.py) runs
the identical host loop.
"""

from __future__ import annotations

import collections
import contextvars
import threading
import time
from typing import Any

#: Implicit parent for nested ``span()`` context managers (per-thread /
#: per-task via contextvars; ``begin()`` spans never become implicit
#: parents — their lifetime is not lexically scoped).
_CURRENT: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "frl_current_span", default=None
)


class Span:
    """An open span; ``end()`` (or context-manager exit) records it."""

    __slots__ = (
        "_tracer", "name", "cat", "trace", "span_id", "parent_id",
        "t0", "attrs", "_annotation", "_token", "_ended", "_step_num",
    )

    def __init__(
        self, tracer, name, cat, trace, span_id, parent_id, t0, attrs,
        step_num=None,
    ):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.trace = trace
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.attrs = attrs
        self._annotation = None
        self._token = None
        self._ended = False
        self._step_num = step_num

    def end(self, **attrs: Any) -> None:
        """Close the span at "now"; extra attrs merge into the record.
        Host-side store only — never call from traced code (graft-lint's
        ``metrics-in-traced`` hygiene error covers span mutations too)."""
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.attrs = {**self.attrs, **attrs}
        self._tracer._finish(self, time.perf_counter())

    def __enter__(self) -> "Span":
        self._token = _CURRENT.set(self)
        if self._tracer.annotate:
            import jax

            if self._step_num is not None:
                self._annotation = jax.profiler.StepTraceAnnotation(
                    self.name, step_num=self._step_num
                )
            else:
                self._annotation = jax.profiler.TraceAnnotation(self.name)
            self._annotation.__enter__()
        return self

    def __exit__(self, *exc: Any) -> None:
        if self._annotation is not None:
            self._annotation.__exit__(*exc)
            self._annotation = None
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        self.end()


class _NullSpan:
    """The disabled tracer's span: accepted everywhere, records nothing."""

    __slots__ = ()
    name = ""
    cat = None
    trace = 0
    span_id = 0
    parent_id = None

    def end(self, **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()


def _parent_id_of(parent: Any) -> "int | None":
    if parent is None:
        return None
    if isinstance(parent, int):
        return parent
    if isinstance(parent, _NullSpan):
        return None
    return parent.span_id


class Tracer:
    """Span recorder + ring buffer + Chrome-trace exporter (module
    docstring). One tracer per publishing component, like the metrics
    registry — engines, fit() runs, and supervisors never share lanes."""

    def __init__(
        self,
        capacity: int = 8192,
        enabled: bool = True,
        *,
        annotate: bool = False,
        timeline: Any = None,
        origin: float | None = None,
    ):
        self.enabled = enabled
        self.annotate = annotate and enabled
        self._timeline = timeline
        self._origin = time.perf_counter() if origin is None else origin
        self._spans: collections.deque[dict[str, Any]] = collections.deque(
            maxlen=capacity
        )
        self.dropped = 0
        self._lock = threading.Lock()
        self._next_trace = 0
        self._next_span = 0
        # Lane labels, bounded like the span ring (a long-lived engine
        # allocates one trace per request forever — the oldest label is
        # evicted with roughly the spans that referenced it).
        self._name_capacity = max(int(capacity), 1)
        self._trace_names: dict[int, str] = {}

    # ------------------------------------------------------------ recording

    def new_trace(self, name: str | None = None) -> int:
        """Allocate a trace (lane) id; ``name`` labels the Perfetto lane.
        Returns 0 when disabled — no state is touched, same contract as
        the null span."""
        if not self.enabled:
            return 0
        with self._lock:
            self._next_trace += 1
            tid = self._next_trace
            if name is not None:
                self._trace_names[tid] = name
                while len(self._trace_names) > self._name_capacity:
                    self._trace_names.pop(next(iter(self._trace_names)))
            return tid

    def _alloc_span(self) -> int:
        with self._lock:
            self._next_span += 1
            return self._next_span

    def _resolve(self, trace, parent):
        """(trace_id, parent_id) with contextvar fallback for both."""
        if parent is None:
            parent = _CURRENT.get()
        pid = _parent_id_of(parent)
        if trace is None:
            trace = getattr(parent, "trace", 0) if parent is not None else 0
        return trace, pid

    def span(
        self,
        name: str,
        *,
        trace: int | None = None,
        parent: Any = None,
        cat: str | None = None,
        step_num: int | None = None,
        **attrs: Any,
    ) -> "Span | _NullSpan":
        """A context-manager span; nests implicitly (children created in
        its body inherit it as parent) and carries the profiler
        annotation when the tracer was built ``annotate=True``."""
        if not self.enabled:
            return _NULL_SPAN
        trace, pid = self._resolve(trace, parent)
        return Span(
            self, name, cat, trace, self._alloc_span(), pid,
            time.perf_counter(), attrs, step_num=step_num,
        )

    def begin(
        self,
        name: str,
        *,
        trace: int | None = None,
        parent: Any = None,
        cat: str | None = None,
        **attrs: Any,
    ) -> "Span | _NullSpan":
        """An open span with cross-call lifetime; close with ``end()``."""
        if not self.enabled:
            return _NULL_SPAN
        trace, pid = self._resolve(trace, parent)
        return Span(
            self, name, cat, trace, self._alloc_span(), pid,
            time.perf_counter(), attrs,
        )

    def emit(
        self,
        name: str,
        *,
        t0: float,
        dur_s: float,
        trace: int | None = None,
        parent: Any = None,
        cat: str | None = None,
        **attrs: Any,
    ) -> int:
        """Record a completed span with explicit clock values (``t0`` in
        the ``time.perf_counter`` domain). Returns its span id (0 when
        disabled) so retrospective children can chain."""
        if not self.enabled:
            return 0
        trace, pid = self._resolve(trace, parent)
        span_id = self._alloc_span()
        self._record(name, cat, trace, span_id, pid, t0, dur_s, attrs)
        return span_id

    def _finish(self, span: Span, t1: float) -> None:
        self._record(
            span.name, span.cat, span.trace, span.span_id, span.parent_id,
            span.t0, t1 - span.t0, span.attrs,
        )

    def _record(self, name, cat, trace, span_id, parent_id, t0, dur, attrs):
        rec: dict[str, Any] = {
            "name": name,
            "trace": int(trace),
            "span": int(span_id),
            "t0_s": round(t0 - self._origin, 9),
            "dur_s": round(max(float(dur), 0.0), 9),
        }
        if cat is not None:
            rec["cat"] = cat
        if parent_id is not None:
            rec["parent"] = int(parent_id)
        if attrs:
            rec.update(attrs)
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(rec)
        if self._timeline is not None:
            self._timeline.event(
                name, dur_s=rec["dur_s"],
                **{k: v for k, v in rec.items()
                   if k not in ("name", "t0_s", "dur_s", "cat")},
            )

    # -------------------------------------------------------------- reading

    @property
    def timeline(self) -> Any:
        """The ``Timeline`` finished spans tee into (None when detached) —
        lets an owner check whether its own timeline already receives the
        phase records or needs a bare-event fallback."""
        return self._timeline

    def spans(self) -> list[dict[str, Any]]:
        """Finished spans, oldest first, WITHOUT consuming them."""
        with self._lock:
            return list(self._spans)

    def drain(self) -> list[dict[str, Any]]:
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
            return out

    def __len__(self) -> int:
        return len(self._spans)

    # ------------------------------------------------------------ exporting

    def chrome_trace(self, *, pid: int = 0) -> dict[str, Any]:
        return chrome_trace_events(
            self.spans(), trace_names=dict(self._trace_names), pid=pid
        )

    def write_chrome_trace(self, path: str, *, pid: int = 0) -> None:
        """Atomically write the Chrome-trace-event JSON next to the run's
        other artifacts (load in chrome://tracing or ui.perfetto.dev)."""
        import json
        import os

        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.chrome_trace(pid=pid), fh, indent=1)
        os.replace(tmp, path)


def chrome_trace_events(
    spans: list[dict[str, Any]],
    *,
    trace_names: dict[int, str] | None = None,
    pid: int = 0,
    process_name: str = "frl_tpu host",
) -> dict[str, Any]:
    """Convert span records to the Chrome trace-event JSON object format.

    Each span becomes a complete ("ph": "X") event on thread lane
    ``tid = trace id`` (one Perfetto lane per request/run/session);
    trace/span/parent ids and user attrs ride in ``args``, which is how
    the span TREE survives a format whose events are flat. Metadata
    events name the process and each lane that actually carries spans
    (labels for lanes whose spans were all evicted or drained would
    render as empty rows). Deterministic for fixed inputs
    (golden-tested)."""
    trace_names = trace_names or {}
    events: list[dict[str, Any]] = [
        {
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": process_name},
        }
    ]
    tids = sorted({rec["trace"] for rec in spans})
    for tid in tids:
        events.append(
            {
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": trace_names.get(tid, f"trace {tid}")},
            }
        )
    for rec in spans:
        args = {
            k: v for k, v in rec.items()
            if k not in ("name", "cat", "t0_s", "dur_s")
        }
        events.append(
            {
                "name": rec["name"],
                "cat": rec.get("cat", "host"),
                "ph": "X",
                "ts": round(rec["t0_s"] * 1e6, 3),
                "dur": round(rec["dur_s"] * 1e6, 3),
                "pid": pid,
                "tid": rec["trace"],
                "args": args,
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}
