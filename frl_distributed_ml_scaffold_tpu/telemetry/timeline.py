"""Per-step timeline event log (the host-side phase record).

Where the metrics registry aggregates, the timeline remembers ORDER: one
record per host-loop phase (``load_batch``, ``dispatch``, ``prefill``,
``decode``...), ring-buffered so a stalled exporter can never grow the
host heap, drained into the telemetry JSONL at log boundaries. It is the
offline answer to "what was the loop doing around step N" when a profiler
trace window wasn't armed — and the stall watchdog dumps the tail of it,
so a stall report carries the last phases that DID complete.
"""

from __future__ import annotations

import collections
import time
from typing import Any


class Timeline:
    """Bounded in-memory event log; ``drain()`` empties it for export."""

    def __init__(self, capacity: int = 4096, enabled: bool = True):
        self.enabled = enabled
        self._events: collections.deque[dict[str, Any]] = collections.deque(
            maxlen=capacity
        )
        self.dropped = 0  # overwritten by the ring before being drained

    def event(
        self,
        name: str,
        *,
        dur_s: float | None = None,
        step: int | None = None,
        **fields: Any,
    ) -> None:
        if not self.enabled:
            return
        if len(self._events) == self._events.maxlen:
            self.dropped += 1
        rec: dict[str, Any] = {
            "event": "timeline",
            "name": name,
            "ts": round(time.time(), 6),
        }
        if step is not None:
            rec["step"] = int(step)
        if dur_s is not None:
            rec["dur_s"] = round(float(dur_s), 6)
        if fields:
            rec.update(fields)
        self._events.append(rec)

    def tail(self, n: int = 32) -> list[dict[str, Any]]:
        """Last ``n`` events WITHOUT consuming them (the watchdog's view)."""
        return list(self._events)[-n:]

    def drain(self) -> list[dict[str, Any]]:
        out = list(self._events)
        self._events.clear()
        return out

    def __len__(self) -> int:
        return len(self._events)
