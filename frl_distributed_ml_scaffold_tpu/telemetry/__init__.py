"""Runtime telemetry: one metrics/tracing layer across the train, serve,
and elastic tiers (ISSUE 7). See metrics.py for the design contract; the
graft-lint hygiene pass enforces the host-side-only rule (no metric
mutation inside traced code)."""

from frl_distributed_ml_scaffold_tpu.telemetry.metrics import (
    LOG2_LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    jsonl_record,
    prometheus_text,
    write_prometheus_file,
)
from frl_distributed_ml_scaffold_tpu.telemetry.timeline import Timeline
from frl_distributed_ml_scaffold_tpu.telemetry.tracing import (
    Span,
    Tracer,
    chrome_trace_events,
)
from frl_distributed_ml_scaffold_tpu.telemetry.watchdog import StallWatchdog

__all__ = [
    "LOG2_LATENCY_BUCKETS_S",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Span",
    "StallWatchdog",
    "Timeline",
    "Tracer",
    "chrome_trace_events",
    "jsonl_record",
    "prometheus_text",
    "write_prometheus_file",
]
