"""Declarative schedule verification: derive the expected collective
classes/counts/bytes from a declared ``OverlapSchedule`` and check a
program against them (ISSUE 13).

Before this module, the overlap invariants were hand-written per
mechanism: PR 3's "zero all_gather on pure TP" and PR 2's
"blockwise gathers + reduce-scatter" lived as bespoke code in
``analysis.runner.lint_train_step`` and ad-hoc pins in the test files.
Now the DECLARATION is the source of truth — the same
``parallel/schedule.py`` object the Trainer lowers into hooks also
derives what its program must look like:

- a ``ring_chunk`` gather on axis ``a`` (size ``n``) ⇒ ``ppermute``
  chains on ``a`` exist, every layer scan's ``a``-axis ppermute count is
  a whole number of ``(n-1)``-hop chains (a partial chain is a broken
  ring), and — when no blockwise rule is declared — NO explicit
  ``all_gather`` anywhere (activations must ride the rings);
- ``lowp`` on the ring pair ⇒ every ``a``-axis ppermute payload is the
  declared 1-byte format; the only wide-dtype ppermute traffic allowed
  is the scalar scales riding next to the chunks (``scale_bytes_per_call``
  budget), and quantized payload traffic must actually exist;
- a ``block`` gather on axis ``b`` ⇒ explicit ``all_gather``s exist,
  every one of them moves a per-block param slice
  (``parallel.partition.block_param_slice_shapes``), they sit inside the
  layer scans (not hoisted), and the declared scatter's explicit
  ``reduce_scatter`` exists.

Consumed two ways: ``analysis.pins.assert_schedule`` raises on any
violation (the pytest face), and ``analysis.runner.lint_train_step``
reports the same findings per recipe (the CLI face) — one derivation,
mutation-gated in tests/test_schedule.py (a GSPMD fallback and a wide
fp32 ring under a ``lowp`` schedule must both trip).
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np

from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
    CollectiveRecord,
    collective_census,
)
from frl_distributed_ml_scaffold_tpu.analysis.findings import Finding
from frl_distributed_ml_scaffold_tpu.analysis.jaxpr_utils import (
    primitive_shapes,
    top_level_scans,
)
from frl_distributed_ml_scaffold_tpu.analysis.reshard import (
    monolithic_gather_findings,
)
from frl_distributed_ml_scaffold_tpu.ops.quantization import lowp_dtype

#: Wide-dtype ppermute payloads at or under this many bytes/call are
#: quantization SCALES (a per-chunk scalar, f32 <= 4 bytes; kept generous
#: for per-row scale vectors), not chunk traffic — the carve-out the
#: wide-ppermute error and the pinned bytes budgets share (moved here
#: from analysis.runner, which now reads it back).
SCALE_BYTES_PER_CALL = 256


def ring_ppermute_bytes(
    census: Iterable[CollectiveRecord], axis: str
) -> int:
    """Total per-step ppermute wire bytes on one ring axis — the
    measurement half of the declared-lowp wire-ratio pin."""
    return sum(
        r.total_bytes
        for r in census
        if r.primitive == "ppermute" and axis in r.axes
    )


def _scan_axis_ppermute_counts(jaxpr: Any, axis: str) -> list[int]:
    """Per top-level scan: how many ppermute eqns naming ``axis`` its
    body carries (sub-jaxprs included)."""
    counts = []
    for s in top_level_scans(jaxpr):
        body = s.params["jaxpr"]
        counts.append(sum(
            1
            for r in collective_census(body)
            if r.primitive == "ppermute" and axis in r.axes
        ))
    return counts


def schedule_findings(
    jaxpr: Any,
    sched: Any,
    *,
    axis_sizes: dict[str, int],
    param_slices: Iterable[tuple[int, ...]] | None = None,
    census: list[CollectiveRecord] | None = None,
    label: str = "",
    scale_bytes_per_call: int = SCALE_BYTES_PER_CALL,
) -> list[Finding]:
    """Check ``jaxpr`` against the expectations derived from ``sched``
    (module docstring); returns error findings (empty = the program is
    what the schedule declares).

    ``axis_sizes`` are the resolved mesh axis sizes (rules on size-1 axes
    lower to identity, so their checks are skipped). ``param_slices`` is
    required when a block rule is declared on a populated axis
    (``parallel.partition.block_param_slice_shapes``). ``census`` may be
    passed to reuse an already-computed collective census.
    """
    if census is None:
        census = collective_census(jaxpr)
    out: list[Finding] = []
    ring = sched.ring_gather()
    block = sched.block_gather()

    if ring is not None and axis_sizes.get(ring.axis, 1) > 1:
        n = axis_sizes[ring.axis]
        ring_recs = [
            r for r in census
            if r.primitive == "ppermute" and ring.axis in r.axes
        ]
        if not ring_recs:
            out.append(Finding(
                "schedule", "error", "missing-rings",
                f"{label}schedule declares gather({ring.axis},ring_chunk) "
                f"but the step carries no {ring.axis}-axis ppermute rings",
                {"axis": ring.axis},
            ))
        hops = n - 1
        for i, c in enumerate(_scan_axis_ppermute_counts(jaxpr, ring.axis)):
            if c % hops != 0:
                out.append(Finding(
                    "schedule", "error", "broken-ring",
                    f"{label}scan {i} carries {c} {ring.axis}-axis "
                    f"ppermute eqn(s), not a whole number of "
                    f"{hops}-hop chains over the {n}-way ring",
                    {"axis": ring.axis, "scan": i, "count": c,
                     "hops_per_chain": hops},
                ))
        if block is None:
            # No blockwise rule ⇒ nothing may all_gather explicitly:
            # activations (and everything else) ride the rings.
            for shapes in primitive_shapes(jaxpr, "all_gather"):
                out.append(Finding(
                    "schedule", "error", "exposed-all-gather",
                    f"{label}step carries an explicit all_gather of "
                    f"{[list(s) for s in shapes]} — the schedule declares "
                    "no blockwise gather; activations must ride the "
                    "ppermute rings",
                    {"shapes": [list(s) for s in shapes]},
                ))
        if ring.lowp is not None:
            want = str(np.dtype(lowp_dtype(ring.lowp)))
            wide = [
                r for r in ring_recs
                if r.dtype != want
                and r.bytes_per_call > scale_bytes_per_call
            ]
            for r in wide:
                out.append(Finding(
                    "schedule", "error", "wide-ppermute",
                    f"{label}lowp={ring.lowp} ring ppermutes a {r.dtype} "
                    f"payload of {r.bytes_per_call} bytes/call (shapes "
                    f"{[list(s) for s in r.shapes]}) — quantization "
                    "silently fell back to wide floats",
                    r.to_dict(),
                ))
            if not any(r.dtype == want for r in ring_recs):
                out.append(Finding(
                    "schedule", "error", "missing-lowp-rings",
                    f"{label}schedule declares lowp={ring.lowp} but no "
                    f"{want} ppermute payload exists on the "
                    f"{ring.axis} axis",
                    {"axis": ring.axis, "want_dtype": want},
                ))

    if block is not None and axis_sizes.get(block.axis, 1) > 1:
        if param_slices is None:
            raise ValueError(
                "schedule_findings: a block gather rule on a populated "
                "axis needs param_slices "
                "(parallel.partition.block_param_slice_shapes)"
            )
        gathers = primitive_shapes(jaxpr, "all_gather")
        if not gathers:
            out.append(Finding(
                "schedule", "error", "missing-block-gathers",
                f"{label}schedule declares gather({block.axis},block) but "
                "the step carries no explicit all_gather — param "
                "gathering fell back to the GSPMD schedule",
                {"axis": block.axis},
            ))
        out.extend(monolithic_gather_findings(
            jaxpr, param_slices, label=label
        ))
        if sched.scatter_on(block.axis) is not None and not \
                primitive_shapes(jaxpr, "reduce_scatter"):
            out.append(Finding(
                "schedule", "error", "missing-reduce-scatter",
                f"{label}schedule declares scatter({block.axis}) but the "
                "step has no explicit reduce_scatter — gradients leave "
                "blocks gathered",
                {"axis": block.axis},
            ))
        scans = top_level_scans(jaxpr)
        if scans and not any(
            len(primitive_shapes(s.params["jaxpr"], "all_gather")) > 0
            for s in scans
        ):
            out.append(Finding(
                "schedule", "error", "hoisted-gathers",
                f"{label}no scan body carries the explicit gathers — "
                "they were hoisted out of the layer loop",
                {"axis": block.axis},
            ))
    return out
