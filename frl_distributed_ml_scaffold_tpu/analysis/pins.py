"""The pytest assertion API over the analyzer passes.

``analysis.pins`` is how a perf PR's headline property becomes a pinned
invariant: one assertion per property, raising ``AssertionError`` with
the offending shapes/lines, built on the same walkers the ``graft_lint``
CLI runs.  The pre-existing ad-hoc pins map as:

- PR 2 "blockwise gathers, reduce-scatter backward"
    → ``assert_all_gather_outputs_within`` + ``scan_collective_counts``
      + ``assert_collective_present``.
- PR 3 "4 rings/block, zero all_gather on pure TP"
    → ``assert_no_collective`` + ``scan_collective_counts``.
- PR 4 "no full-seq_len arrays in a bucketed decode step"
    → ``assert_no_dim_materialized`` / ``assert_max_materialized_bytes``.
- PR 4 "prefill→decode handoff reshard-free in compiled HLO"
    → ``assert_reshard_free``.
- PR 5 "state/cache donated and actually aliased"
    → ``assert_donated`` / ``assert_aliased``.
- PR 6 "int8 rings actually shrink the wire; no decode step dequantizes
  the whole cache"
    → ``assert_collective_bytes_within`` (per-dtype collective bytes)
      / ``assert_no_wide_dims_materialized``.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Sequence

from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
    CollectiveRecord,
    collective_census,
    hlo_collective_census,
)
from frl_distributed_ml_scaffold_tpu.analysis.donation import (
    compiled_aliases,
    lowered_donations,
)
from frl_distributed_ml_scaffold_tpu.analysis.jaxpr_utils import (
    eqn_output_shapes,
    primitive_shapes,
    top_level_scans,
)
from frl_distributed_ml_scaffold_tpu.analysis.materialization import (
    intermediates_with_dim,
    max_materialized_bytes,
    oversized_intermediates,
    wide_intermediates_with_dims,
)
from frl_distributed_ml_scaffold_tpu.analysis.reshard import (
    monolithic_gathers,
    reshard_findings,
)

__all__ = [
    "assert_lock_order_acyclic",
    "assert_no_blocking_under_lock",
    "assert_schedule",
    "collective_census",
    "collective_bytes",
    "eqn_output_shapes",
    "primitive_shapes",
    "scan_collective_counts",
    "assert_no_collective",
    "assert_collective_present",
    "assert_collective_bytes_within",
    "assert_all_gather_outputs_within",
    "assert_max_materialized_bytes",
    "assert_no_dim_materialized",
    "assert_no_wide_dims_materialized",
    "assert_donated",
    "assert_aliased",
    "assert_reshard_free",
]


# ------------------------------------------------------- schedule pins


def assert_schedule(
    jaxpr: Any,
    schedule: Any,
    *,
    axis_sizes: dict[str, int],
    param_slices: Iterable[tuple[int, ...]] | None = None,
    baseline_census: Any = None,
    min_wire_ratio: float = 3.5,
    msg: str | None = None,
) -> None:
    """The program satisfies the invariants DERIVED from its declared
    ``parallel.schedule.OverlapSchedule`` (ISSUE 13): ring-chunk gathers
    really are whole ppermute chains (and, with no blockwise rule, the
    step is all_gather-free); blockwise gathers move only per-block param
    slices inside the layer scans with the explicit reduce_scatter
    present; a ``lowp`` ring's ppermute payloads are the declared 1-byte
    format with only scale-sized wide traffic (analysis/schedule.py is
    the one derivation, shared with graft-lint's per-recipe runner).

    ``param_slices`` (``parallel.partition.block_param_slice_shapes``) is
    required when a block rule is declared on a populated axis.

    ``baseline_census`` arms the declared-lowp WIRE-RATIO pin: pass the
    collective census of the SAME schedule without ``lowp`` (the wide
    ring) and the declared ring axis's ppermute bytes must shrink by at
    least ``min_wire_ratio`` (default 3.5x — the 4x fp32→int8 element
    width minus scale traffic).
    """
    from frl_distributed_ml_scaffold_tpu.analysis.schedule import (
        ring_ppermute_bytes,
        schedule_findings,
    )

    bad = schedule_findings(
        jaxpr, schedule, axis_sizes=axis_sizes, param_slices=param_slices
    )
    assert not bad, _fail(
        msg,
        f"program violates its declared schedule "
        f"{schedule.render()!r}: "
        + "; ".join(f.message for f in bad[:4])
        + (f" (+{len(bad) - 4} more)" if len(bad) > 4 else ""),
    )
    ring = schedule.ring_gather()
    if baseline_census is not None and ring is not None and ring.lowp:
        base = ring_ppermute_bytes(_census_of(baseline_census), ring.axis)
        cur = ring_ppermute_bytes(collective_census(jaxpr), ring.axis)
        assert cur > 0, _fail(
            msg, f"lowp schedule moves no {ring.axis}-axis ppermute bytes"
        )
        ratio = base / cur
        assert ratio >= min_wire_ratio, _fail(
            msg,
            f"declared lowp={ring.lowp} ring moves {cur} ppermute "
            f"bytes/step on axis {ring.axis!r} vs {base} for the wide "
            f"baseline — only {ratio:.2f}x lower, pinned >= "
            f"{min_wire_ratio}x",
        )


# ------------------------------------------------------------ jaxpr pins


def scan_collective_counts(jaxpr: Any, prim_name: str) -> list[int]:
    """Per top-level scan eqn: how many ``prim_name`` eqns its body
    carries (sub-jaxprs included) — the blockwise-schedule pin: the layer
    scans, not the top level, must own the collectives."""
    return [
        len(primitive_shapes(s.params["jaxpr"], prim_name))
        for s in top_level_scans(jaxpr)
    ]


def _fail(msg: str | None, detail: str) -> str:
    """Compose the AssertionError text: a custom ``msg`` prefixes the
    computed offender detail rather than replacing it — the whole point
    of a pin firing is seeing WHAT tripped it."""
    return f"{msg}: {detail}" if msg else detail


def assert_no_collective(
    jaxpr: Any, prim_name: str, msg: str | None = None
) -> None:
    """No eqn whose primitive name contains ``prim_name`` anywhere."""
    found = primitive_shapes(jaxpr, prim_name)
    assert not found, _fail(
        msg,
        f"program contains {len(found)} {prim_name!r} eqn(s) "
        f"(output shapes {found[:4]}...) but is pinned {prim_name}-free",
    )


def assert_collective_present(
    jaxpr: Any, prim_name: str, msg: str | None = None
) -> list[tuple]:
    """At least one ``prim_name`` eqn; returns the matches for further
    shape-level assertions."""
    found = primitive_shapes(jaxpr, prim_name)
    assert found, _fail(
        msg,
        f"program carries no {prim_name!r} eqn but is pinned to contain "
        "at least one",
    )
    return found


def _census_of(jaxpr_or_records: Any) -> list[CollectiveRecord]:
    """Accept a (Closed)Jaxpr or an already-computed census."""
    if isinstance(jaxpr_or_records, (list, tuple)) and (
        not jaxpr_or_records
        or isinstance(jaxpr_or_records[0], CollectiveRecord)
    ):
        return list(jaxpr_or_records)
    return collective_census(jaxpr_or_records)


def collective_bytes(
    jaxpr_or_records: Any,
    prim_name: str,
    *,
    dtypes: Iterable[str] | None = None,
    axes: Iterable[str] | None = None,
) -> int:
    """Total per-step wire bytes (``bytes_per_call x trip_count``) of the
    collectives whose primitive name contains ``prim_name``, optionally
    restricted to element ``dtypes`` and/or to eqns naming one of
    ``axes`` — the measurement half of the low-precision comm pin."""
    dt = set(dtypes) if dtypes is not None else None
    ax = set(axes) if axes is not None else None
    total = 0
    for r in _census_of(jaxpr_or_records):
        if prim_name not in r.primitive:
            continue
        if dt is not None and r.dtype not in dt:
            continue
        if ax is not None and not (ax & set(r.axes)):
            continue
        total += r.total_bytes
    return total


def assert_collective_bytes_within(
    jaxpr_or_records: Any,
    prim_name: str,
    budget_bytes: int,
    *,
    dtypes: Iterable[str] | None = None,
    axes: Iterable[str] | None = None,
    msg: str | None = None,
) -> int:
    """The matching per-step wire bytes stay <= ``budget_bytes``.

    The low-precision fast path's comm reduction as a pinned invariant
    (ISSUE 6): e.g. "wide-float ppermute bytes on the model axis fit in
    the scale-traffic budget" — if a ring silently falls back to bf16
    payloads, the bytes land outside the filter's budget and this fires
    with the measured total. Returns the measured bytes for reporting.
    """
    total = collective_bytes(
        jaxpr_or_records, prim_name, dtypes=dtypes, axes=axes
    )
    assert total <= budget_bytes, _fail(
        msg,
        f"{prim_name!r} collectives move {total} bytes/step"
        + (f" in dtypes {sorted(dtypes)}" if dtypes is not None else "")
        + (f" on axes {sorted(axes)}" if axes is not None else "")
        + f", over the pinned budget of {budget_bytes} bytes",
    )
    return total


def assert_all_gather_outputs_within(
    jaxpr: Any,
    allowed_shapes: Iterable[tuple[int, ...]],
    msg: str | None = None,
) -> None:
    """Every all_gather output shape is one of ``allowed_shapes`` (the
    per-block param slices an overlap schedule may legally move)."""
    bad = monolithic_gathers(jaxpr, allowed_shapes)
    assert not bad, _fail(
        msg,
        f"all_gather outputs {bad} are not per-block param slices — an "
        "activation (or full stacked tensor) passed through a monolithic "
        "gather",
    )


# -------------------------------------------------------- materialization


def assert_max_materialized_bytes(
    jaxpr: Any, budget_bytes: int, msg: str | None = None
) -> None:
    over = oversized_intermediates(jaxpr, budget_bytes)
    assert not over, _fail(
        msg,
        "intermediates exceed the materialization budget "
        f"({budget_bytes} bytes): "
        + ", ".join(
            f"{i.dtype}{list(i.shape)}={i.bytes}B" for i in over[:5]
        )
        + (f" (+{len(over) - 5} more)" if len(over) > 5 else "")
        + f"; max={max_materialized_bytes(jaxpr)}B",
    )


def assert_no_dim_materialized(
    jaxpr: Any, dim: int, msg: str | None = None
) -> None:
    """No eqn output carries ``dim`` in its shape — inputs (params) are
    exempt, exactly the decode pin's wpe carve-out."""
    hits = intermediates_with_dim(jaxpr, dim)
    assert not hits, _fail(
        msg,
        f"program materializes arrays carrying forbidden dim {dim}: "
        + str(sorted({i.shape for i in hits})),
    )


def assert_no_wide_dims_materialized(
    jaxpr: Any,
    dims: tuple[int, ...],
    *,
    min_itemsize: int = 2,
    msg: str | None = None,
) -> None:
    """No float intermediate of element width >= ``min_itemsize`` carries
    every dim of ``dims`` (with multiplicity, in any order — a layout
    transpose must not dodge the pin) — the quantized-KV pin: pass the
    cache geometry ``(bucket, H, hd)`` and a decode step that
    dequantizes the WHOLE cache (instead of per chunk in VMEM) fires,
    in the storage layout or the kernel's transposed one, while the
    1-byte cache updates, bounded dequantized chunks, and scale tensors
    all lack the full ``bucket`` dim and pass."""
    hits = wide_intermediates_with_dims(
        jaxpr, dims, min_itemsize=min_itemsize
    )
    assert not hits, _fail(
        msg,
        f"program materializes wide (>= {min_itemsize}-byte) float arrays "
        f"carrying the forbidden geometry {tuple(dims)}: "
        + str(sorted({(i.dtype, i.shape) for i in hits})),
    )


# --------------------------------------------------------------- donation


def assert_donated(
    lowered_or_text: Any,
    *,
    min_donated: int = 1,
    arg_paths: Sequence[str] | None = None,
    expect_donated: Callable[[str], bool] | None = None,
    msg: str | None = None,
) -> None:
    """The lowered program donates its buffers.

    With ``arg_paths`` + ``expect_donated``, every expected leaf must
    carry a donation marker; otherwise at least ``min_donated`` args must.
    """
    dons = lowered_donations(lowered_or_text)
    if arg_paths is not None and expect_donated is not None:
        assert len(arg_paths) == len(dons), (
            f"cannot map {len(dons)} lowered args onto {len(arg_paths)} "
            "tree leaves — pass the exact example args the jit sees"
        )
        missing = [
            p
            for d, p in zip(dons, arg_paths)
            if expect_donated(p) and not d.donated
        ]
        assert not missing, (
            msg
            or f"args expected donated carry no donation marker: "
            f"{missing[:6]}" + ("..." if len(missing) > 6 else "")
        )
        return
    n = sum(1 for d in dons if d.donated)
    assert n >= min_donated, (
        msg
        or f"only {n}/{len(dons)} lowered args are donated "
        f"(pinned >= {min_donated}) — a donate_argnums went missing"
    )


def assert_aliased(
    compiled_or_text: Any, *, min_aliases: int = 1, msg: str | None = None
) -> list[dict]:
    """The compiled executable actually aliases >= ``min_aliases``
    input/output pairs (donation that the compiler accepted); returns the
    alias table for finer-grained checks."""
    aliases = compiled_aliases(compiled_or_text)
    assert len(aliases) >= min_aliases, (
        msg
        or f"compiled executable aliases only {len(aliases)} buffers "
        f"(pinned >= {min_aliases}) — donation did not take"
    )
    return aliases


# ------------------------------------------------------------------- HLO


def assert_reshard_free(
    compiled_or_text: Any,
    shape_signatures: Iterable[tuple[int, ...]],
    *,
    ops: Sequence[str] = ("all-gather", "all-to-all", "collective-permute"),
    msg: str | None = None,
) -> None:
    """No collective in compiled HLO materializes an array with one of
    the given shape signatures (the pinned-layout arrays a GSPMD reshard
    would have to gather)."""
    text = (
        compiled_or_text
        if isinstance(compiled_or_text, str)
        else compiled_or_text.as_text()
    )
    bad = reshard_findings(text, shape_signatures, ops=ops)
    assert not bad, _fail(
        msg,
        "compiled HLO reshards pinned-layout arrays: "
        + "; ".join(f.message for f in bad[:3]),
    )


def assert_no_collective_hlo(
    compiled_or_text: Any,
    op: str,
    msg: str | None = None,
) -> None:
    """No HLO collective of class ``op`` (e.g. "all-gather") at all."""
    text = (
        compiled_or_text
        if isinstance(compiled_or_text, str)
        else compiled_or_text.as_text()
    )
    hits = [r for r in hlo_collective_census(text) if r.op == op]
    assert not hits, _fail(
        msg,
        f"compiled HLO carries {len(hits)} {op} op(s): "
        + "; ".join(r.line[:100] for r in hits[:3]),
    )


# ------------------------------------------------------ concurrency pins


def assert_lock_order_acyclic(
    recorder: Any, msg: str | None = None
) -> None:
    """The runtime lock-order graph a ``faults.instrumented_locks()``
    recorder observed is acyclic — the live twin of graft-lint's static
    ``lock-order-inversion`` check (ISSUE 20).  Call it mid-drill or at
    the end; ``instrumented_locks`` also asserts it at scope exit."""
    cycle = recorder.find_cycle()
    assert cycle is None, _fail(
        msg,
        f"runtime lock-order cycle {' -> '.join(cycle)} observed "
        f"(edges: {recorder.order_edges()}) — threads interleaving "
        "these acquisitions in opposite orders deadlock",
    )


def assert_no_blocking_under_lock(
    recorder: Any,
    max_hold_s: float = 2.0,
    msg: str | None = None,
) -> None:
    """No instrumented lock was held longer than ``max_hold_s`` — the
    runtime signature of ``blocking-under-lock``: a device sync, a
    subprocess wait, or a sleep under a lock shows up as a pathological
    hold time long before it shows up as a deadlock.  The default bound
    is deliberately generous (CI boxes stall); tighten it in perf-tier
    drills."""
    offenders = {
        site: (hold, who)
        for site, (hold, who) in recorder.max_holds().items()
        if hold > max_hold_s
    }
    assert not offenders, _fail(
        msg,
        "locks held past the blocking bound "
        f"({max_hold_s:g}s): "
        + "; ".join(
            f"{site} held {hold:.3f}s by {who or '?'}"
            for site, (hold, who) in sorted(offenders.items())
        ),
    )


# ---------------------------------------------------------------- helpers


def arg_paths_of(*example_args: Any) -> list[str]:
    """Flattened key paths of a call's positional args, in the order jit
    lowers them — the mapping ``assert_donated`` consumes."""
    import jax

    return [
        jax.tree_util.keystr(path)
        for path, _ in jax.tree_util.tree_leaves_with_path(example_args)
    ]
