"""Pass 2 — exposed-collective / reshard detector.

Two complementary detectors for the invariants PRs 2-4 pinned:

- **jaxpr level** (``monolithic_gather_findings``): on an overlap-
  scheduled path every hand-placed ``all_gather`` must move a per-block
  PARAM slice; an all_gather whose output is not in the allowed-shapes
  set is a monolithic activation (or stacked-model) gather — the exact
  regression the fsdp_overlap/tp_overlap pins guard against.

- **HLO level** (``exposed_collective_findings`` / ``reshard_findings``):
  GSPMD inserts collectives at partitioning time, so they only exist in
  lowered/compiled text.  ``exposed_collective_findings`` reports every
  collective of the named classes (the mutation test re-enables plain
  GSPMD TP and asserts this fires); ``reshard_findings`` flags
  collectives whose result carries one of a set of shape signatures —
  the "prefill→decode handoff is reshard-free" pin: a GSPMD repartition
  of the KV cache has to materialize a cache-shaped gather.
"""

from __future__ import annotations

from typing import Any, Iterable, Sequence

from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
    HloCollective,
    hlo_collective_census,
)
from frl_distributed_ml_scaffold_tpu.analysis.findings import Finding
from frl_distributed_ml_scaffold_tpu.analysis.jaxpr_utils import (
    primitive_shapes,
)


def monolithic_gathers(
    jaxpr: Any, allowed_shapes: Iterable[tuple[int, ...]]
) -> list[tuple[int, ...]]:
    """all_gather output shapes NOT in ``allowed_shapes`` (each a
    per-block param-slice shape the overlap schedule is allowed to move)."""
    allowed = set(tuple(s) for s in allowed_shapes)
    bad = []
    for out_shapes in primitive_shapes(jaxpr, "all_gather"):
        for shape in out_shapes:
            if tuple(shape) not in allowed:
                bad.append(tuple(shape))
    return bad


def monolithic_gather_findings(
    jaxpr: Any,
    allowed_shapes: Iterable[tuple[int, ...]],
    *,
    label: str = "",
) -> list[Finding]:
    return [
        Finding(
            "reshard", "error", "monolithic-gather",
            f"{label}all_gather output {list(s)} is not a per-block param "
            "slice — an activation (or full stacked tensor) passed through "
            "a monolithic gather",
            {"shape": list(s)},
        )
        for s in monolithic_gathers(jaxpr, allowed_shapes)
    ]


def exposed_collectives(
    hlo_text: str, ops: Sequence[str] = ("all-gather", "all-reduce")
) -> list[HloCollective]:
    """Collectives of the named HLO classes present in compiled text."""
    return [r for r in hlo_collective_census(hlo_text) if r.op in ops]


def exposed_collective_findings(
    hlo_text: str,
    *,
    ops: Sequence[str] = ("all-gather", "all-reduce"),
    severity: str = "error",
    label: str = "",
) -> list[Finding]:
    """One finding per exposed collective of the named classes — used on
    paths pinned collective-free (pure-TP overlap: zero all-gather)."""
    return [
        Finding(
            "reshard", severity, "exposed-collective",
            f"{label}{r.op} of {[list(s) for s in r.shapes]} "
            f"({r.bytes_total} bytes) in compiled HLO",
            {"collective": r.to_dict()},
        )
        for r in exposed_collectives(hlo_text, ops)
    ]


def reshard_findings(
    hlo_text: str,
    shape_signatures: Iterable[tuple[int, ...]],
    *,
    ops: Sequence[str] = ("all-gather", "all-to-all", "collective-permute"),
    label: str = "",
) -> list[Finding]:
    """Collectives whose RESULT carries one of the given shape signatures
    — a GSPMD-inserted reshard of that array (the serving handoff pin)."""
    sigs = set(tuple(s) for s in shape_signatures)
    out = []
    for r in hlo_collective_census(hlo_text):
        if r.op not in ops:
            continue
        hit = [s for s in r.shapes if tuple(s) in sigs]
        if hit:
            out.append(
                Finding(
                    "reshard", "error", "reshard",
                    f"{label}{r.op} materializes pinned-layout array "
                    f"{[list(s) for s in hit]} — a monolithic reshard",
                    {"collective": r.to_dict(),
                     "matched": [list(s) for s in hit]},
                )
            )
    return out
