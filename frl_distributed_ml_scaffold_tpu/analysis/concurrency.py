"""Concurrency lint: lock discipline for the host orchestration tier (ISSUE 20).

The host side of this repo is genuinely threaded — the stall watchdog,
the elastic heartbeat + grow-watch threads, the prefetch executor, the
native dataloader's worker pool behind ctypes — and the never-hangs
contract was, until this pass, pinned only by example-based tests.  This
module turns the lock-discipline review checklist into a whole-package
AST pass (program ``concurrency:package``, alongside
``robustness:package``) with three finding kinds:

- ``unguarded-shared-write`` (error) — a class (or module) that owns a
  lock or touches ``threading.Thread`` writes an attribute under
  ``with self._lock:`` in at least one method, establishing the lock as
  that attribute's guard; a *read-modify-write* of the same attribute
  outside any of its guarding locks is then a lost-update race.  Plain
  overwrites are deliberately NOT flagged: single-writer handoffs like
  the stall watchdog's documented lock-free ``_last``/``_beaten``
  ordering are a legitimate idiom, and they never read-modify-write.
- ``lock-order-inversion`` (error) — the interprocedural
  lock-acquisition-order graph (nested ``with`` regions plus call edges
  resolved through a name-keyed call graph) contains a cycle.  Two
  threads walking a cycle's edges in opposite orders deadlock.
- ``blocking-under-lock`` (error/warning) — a call that can block
  indefinitely or for device-scale time executes while a lock is held:
  ``block_until_ready``/``device_put`` (device sync under the metrics
  lock deadlocks the watchdog that samples it), ``subprocess``
  waits, ``.result()``, thread ``.join()``, ``time.sleep`` (errors);
  generic ``.wait()`` (warning — condition/event waits are sometimes a
  deliberate handoff, but holding an unrelated lock across one is
  almost always wrong).

Lock identity is canonical ``Owner.attr`` (class name or module
basename), so the ubiquitous attribute name ``_lock`` never aliases
across classes.  Foreign locks (``self._reg._lock``, ``registry._lock``)
resolve through ``__init__``/parameter type annotations — that is how
the real ``FaultPlan._lock -> MetricsRegistry._lock`` nesting edge in
``faults/plan.py`` is modeled (and verified acyclic) rather than
skipped.  An unresolvable lock-shaped expression gets an opaque
per-scope id that cannot alias anything, which keeps the cycle check
sound (no fabricated edges) at the cost of missing aliased orders.

The runtime twin of this pass is ``faults.instrumented_locks()``
(``faults/locks.py``), which observes the same properties — acquisition
order acyclicity, hold times — on live threads during fault drills.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Iterable, Mapping, Optional

from .findings import Finding

__all__ = [
    "lint_concurrency_source",
    "lint_concurrency_sources",
    "lint_concurrency_paths",
]

PASS = "concurrency"

#: Call names never followed through the interprocedural call graph:
#: container/string/builtin methods so common that name-keyed resolution
#: would connect everything to everything.  Deliberately NOT listed:
#: ``inc``/``observe``/``fire``/``beat`` — those are the package's own
#: hot cross-lock calls and following them is the whole point.
_CALL_STOPLIST = frozenset(
    {
        "append", "extend", "insert", "pop", "add", "remove", "discard",
        "clear", "update", "keys", "values", "items", "get", "setdefault",
        "sort", "reverse", "copy", "deepcopy",
        "split", "rsplit", "join", "strip", "lstrip", "rstrip",
        "startswith", "endswith", "format", "replace", "encode", "decode",
        "lower", "upper", "count", "index", "find",
        "len", "str", "int", "float", "bool", "list", "dict", "set",
        "tuple", "frozenset", "sorted", "reversed", "min", "max", "sum",
        "abs", "round", "range", "enumerate", "zip", "map", "filter",
        "next", "iter", "isinstance", "issubclass", "hasattr", "getattr",
        "setattr", "delattr", "id", "repr", "hash", "print", "type",
        "super", "vars", "callable", "any", "all", "open", "read",
        "write", "close", "info", "debug", "warning", "error",
        "exception", "item", "tolist", "group", "match", "search",
    }
)

#: ``.join()`` receivers that look like threads/processes; ``", ".join``
#: and ``os.path.join`` must not trip the blocking rule.
_THREADISH_RE = re.compile(r"thread|proc|worker|child|watcher", re.I)

_SUBPROCESS_WAITERS = frozenset(
    {"run", "call", "check_call", "check_output"}
)

_LOCK_FACTORIES = frozenset(
    {"threading.Lock", "threading.RLock", "threading.Condition"}
)


def _dotted(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _blocking_class(node: ast.Call) -> Optional[tuple[str, str]]:
    """Classify a call as (kind, severity) if it can block, else None."""
    dotted = _dotted(node.func)
    if isinstance(node.func, ast.Attribute):
        leaf = node.func.attr
        recv = _dotted(node.func.value)
    elif isinstance(node.func, ast.Name):
        leaf = node.func.id
        recv = ""
    else:
        return None
    if leaf in ("block_until_ready", "device_put"):
        return (leaf, "error")
    root = dotted.split(".", 1)[0] if dotted else ""
    if root == "subprocess" and leaf in _SUBPROCESS_WAITERS:
        return (dotted, "error")
    if leaf == "communicate" and recv:
        return (f"{recv}.communicate", "error")
    if leaf == "sleep" and (not recv or recv == "time"):
        return ("time.sleep", "error")
    if leaf == "result" and isinstance(node.func, ast.Attribute):
        return (f"{recv or '<expr>'}.result", "error")
    if leaf == "join" and recv and _THREADISH_RE.search(recv):
        return (f"{recv}.join", "error")
    if leaf == "wait" and isinstance(node.func, ast.Attribute):
        return (f"{recv or '<expr>'}.wait", "warning")
    return None


# ---------------------------------------------------------------------------
# phase 1: per-module collection
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Scope:
    """A class (or a module pseudo-scope) that can own locks."""

    name: str            # class name, or module basename for module scope
    filename: str
    is_class: bool
    locks: dict[str, int] = dataclasses.field(default_factory=dict)
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)
    threaded: bool = False
    acquires_any: bool = False  # any method acquires any lock

    def lock_id(self, name: str) -> str:
        return f"{self.name}.{name}"


@dataclasses.dataclass
class _Fn:
    """Summary of one function/method after the held-lock walk."""

    qual: str
    name: str
    filename: str
    scope: Optional[_Scope]
    external_roots: frozenset = frozenset()
    params: dict = dataclasses.field(default_factory=dict)
    acquires: set[str] = dataclasses.field(default_factory=set)
    # every interesting call: (simple, receiver_dotted, lineno)
    call_entries: list = dataclasses.field(default_factory=list)
    # blocking calls anywhere in the body: (kind, severity, lineno)
    blocking_any: list = dataclasses.field(default_factory=list)
    # nested-with acquisition edges: (held, acquired, lineno)
    direct_edges: list = dataclasses.field(default_factory=list)
    # calls made while >=1 lock held: (held_tuple, simple, recv, lineno)
    calls_under: list = dataclasses.field(default_factory=list)
    # blocking calls while >=1 lock held: (held, kind, sev, lineno)
    blocking_under: list = dataclasses.field(default_factory=list)
    # attribute/global writes: (attr_key, held_frozenset, lineno, is_rmw)
    writes: list = dataclasses.field(default_factory=list)


def _is_lock_ctor(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call) and _dotted(node.func) in _LOCK_FACTORIES
    )


def _ann_name(ann: Optional[ast.AST]) -> str:
    """'MetricsRegistry' from an annotation Name/str-Constant/Attribute."""
    if ann is None:
        return ""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip().split(".")[-1]
    d = _dotted(ann)
    return d.split(".")[-1] if d else ""


#: Import roots considered package-internal: relative imports plus
#: absolute imports of the package itself.  Everything else (stdlib,
#: numpy, jax) is external — calls through those names are never
#: resolved into package functions (``subprocess.run`` must not match a
#: package method that happens to be named ``run``).
_PKG_ROOT_NAME = __name__.split(".", 1)[0]


class _Module:
    def __init__(self, filename: str, tree: ast.Module):
        self.filename = filename
        base = os.path.splitext(os.path.basename(filename))[0]
        self.mod_scope = _Scope(base, filename, is_class=False)
        self.external_roots: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    root = a.name.split(".")[0]
                    if root != _PKG_ROOT_NAME:
                        self.external_roots.add(a.asname or root)
            elif isinstance(node, ast.ImportFrom):
                if (
                    node.level == 0
                    and (node.module or "").split(".")[0] != _PKG_ROOT_NAME
                ):
                    for a in node.names:
                        self.external_roots.add(a.asname or a.name)
        self.classes: list[tuple[ast.ClassDef, _Scope]] = []
        self.fns: list[tuple[ast.AST, _Scope]] = []  # walked in phase 2
        for node in tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.mod_scope.locks[t.id] = node.lineno
            elif isinstance(node, ast.ClassDef):
                self.classes.append((node, self._collect_class(node)))
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                self.fns.append((node, self.mod_scope))
        for cnode, scope in self.classes:
            for item in cnode.body:
                if isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    self.fns.append((item, scope))

    def _collect_class(self, cnode: ast.ClassDef) -> _Scope:
        scope = _Scope(cnode.name, self.filename, is_class=True)
        for node in ast.walk(cnode):
            d = _dotted(node) if isinstance(node, ast.Attribute) else ""
            if d == "threading.Thread":
                scope.threaded = True
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        scope.locks[t.attr] = node.lineno
        # self.<attr> = <param> in __init__, param annotated with a class
        # name: the attribute's type, used to resolve self.attr._lock.
        for item in cnode.body:
            if (
                isinstance(item, ast.FunctionDef)
                and item.name == "__init__"
            ):
                ann = {
                    a.arg: _ann_name(a.annotation)
                    for a in (
                        item.args.posonlyargs
                        + item.args.args
                        + item.args.kwonlyargs
                    )
                }
                for node in ast.walk(item):
                    if (
                        isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Attribute)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id == "self"
                        and isinstance(node.value, ast.Name)
                        and ann.get(node.value.id)
                    ):
                        scope.attr_types[node.targets[0].attr] = ann[
                            node.value.id
                        ]
        return scope


class _Package:
    """All modules, cross-referenced: lock attr names, class registry."""

    def __init__(self):
        self.modules: list[_Module] = []
        self.classes: dict[str, _Scope] = {}
        self.lock_attr_names: set[str] = set()
        self.fns: list[_Fn] = []
        self.unparseable: list[Finding] = []

    def add_source(self, filename: str, source: str) -> None:
        try:
            tree = ast.parse(source, filename=filename)
        except SyntaxError as e:
            self.unparseable.append(
                Finding(
                    PASS,
                    "warning",
                    "unparseable",
                    f"{filename}: not parseable as Python ({e.msg} at "
                    f"line {e.lineno}); concurrency pass skipped it",
                    {"file": filename},
                )
            )
            return
        mod = _Module(filename, tree)
        self.modules.append(mod)
        for _, scope in mod.classes:
            self.classes[scope.name] = scope
            self.lock_attr_names.update(scope.locks)
        self.lock_attr_names.update(mod.mod_scope.locks)


# ---------------------------------------------------------------------------
# phase 2: held-lock walk over every function
# ---------------------------------------------------------------------------


def _fn_params(fnnode: ast.AST) -> dict[str, str]:
    args = fnnode.args
    return {
        a.arg: _ann_name(a.annotation)
        for a in (args.posonlyargs + args.args + args.kwonlyargs)
        if a.annotation is not None
    }


def _resolve_lock(
    expr: ast.AST,
    scope: _Scope,
    mod_scope: _Scope,
    pkg: _Package,
    params: dict[str, str],
) -> Optional[str]:
    """Canonical lock id for a with-statement context expression."""
    d = _dotted(expr)
    if not d:
        return None
    parts = d.split(".")
    leaf = parts[-1]
    # self._lock in a class that defines it
    if len(parts) == 2 and parts[0] == "self" and scope.is_class:
        if leaf in scope.locks:
            return scope.lock_id(leaf)
    # bare module-level lock name
    if len(parts) == 1 and leaf in mod_scope.locks:
        return mod_scope.lock_id(leaf)
    if leaf not in pkg.lock_attr_names:
        return None  # not lock-shaped at all (with open(...), with mesh:)
    # self.attr._lock with self.attr's type known from __init__
    if len(parts) == 3 and parts[0] == "self" and scope.is_class:
        owner = pkg.classes.get(scope.attr_types.get(parts[1], ""))
        if owner is not None and leaf in owner.locks:
            return owner.lock_id(leaf)
    # param._lock with the parameter annotated
    if len(parts) == 2:
        owner = pkg.classes.get(params.get(parts[0], ""))
        if owner is not None and leaf in owner.locks:
            return owner.lock_id(leaf)
    # Lock-shaped but unresolvable: opaque per-scope id.  It participates
    # in ordering edges but can never alias another scope's lock, so it
    # cannot fabricate a cycle.
    return f"{scope.name}:{d}"


def _walk_fn(
    fnnode: ast.AST,
    scope: _Scope,
    mod_scope: _Scope,
    pkg: _Package,
    external_roots: frozenset,
    qual_prefix: str = "",
) -> list[_Fn]:
    name = fnnode.name
    qual = f"{qual_prefix or scope.name}.{name}"
    params = _fn_params(fnnode)
    fn = _Fn(qual, name, scope.filename, scope, external_roots, params)
    nested: list[_Fn] = []
    exempt_writes = scope.is_class and name in ("__init__", "__new__")

    def record_write(target: ast.AST, held: tuple, value: ast.AST,
                     is_aug: bool, lineno: int) -> None:
        # unwrap subscript targets: self.x[i] += 1 writes self.x
        while isinstance(target, ast.Subscript):
            target = target.value
        attr_key = None
        attr_name = None
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and scope.is_class
        ):
            attr_key = ("class", scope.name, target.attr)
            attr_name = f"self.{target.attr}"
        elif isinstance(target, ast.Name) and not scope.is_class:
            attr_key = ("module", mod_scope.name, target.id)
            attr_name = target.id
        if attr_key is None or exempt_writes:
            return
        rmw = is_aug
        if not rmw and value is not None:
            for sub in ast.walk(value):
                if _dotted(sub) == _dotted(target) and _dotted(target):
                    rmw = True
                    break
        fn.writes.append(
            (attr_key, attr_name, frozenset(held), lineno, rmw)
        )

    def visit(node: ast.AST, held: tuple) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: runs later (thread target, closure) — walk it
            # as its own function, with no inherited held locks.
            nested.extend(
                _walk_fn(node, scope, mod_scope, pkg, external_roots, qual)
            )
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                visit(item.context_expr, held)
                lock = _resolve_lock(
                    item.context_expr, scope, mod_scope, pkg, params
                )
                if lock is not None:
                    fn.acquires.add(lock)
                    scope.acquires_any = True
                    for h in new_held:
                        if h != lock:
                            fn.direct_edges.append(
                                (h, lock, node.lineno)
                            )
                    new_held = new_held + (lock,)
            for stmt in node.body:
                visit(stmt, new_held)
            return
        if isinstance(node, ast.Call):
            simple, recv = "", ""
            if isinstance(node.func, ast.Name):
                simple = node.func.id
            elif isinstance(node.func, ast.Attribute):
                simple = node.func.attr
                recv = _dotted(node.func.value)
            ext = (recv or simple).split(".")[0] in external_roots
            if simple and simple not in _CALL_STOPLIST and not ext:
                fn.call_entries.append((simple, recv, node.lineno))
                if held:
                    fn.calls_under.append((held, simple, recv, node.lineno))
            blk = _blocking_class(node)
            if blk is not None:
                kind, sev = blk
                fn.blocking_any.append((kind, sev, node.lineno))
                if held:
                    fn.blocking_under.append((held, kind, sev, node.lineno))
            for child in ast.iter_child_nodes(node):
                visit(child, held)
            return
        if isinstance(node, ast.AugAssign):
            record_write(node.target, held, None, True, node.lineno)
            visit(node.value, held)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for el in t.elts if isinstance(t, ast.Tuple) else [t]:
                    record_write(el, held, node.value, False, node.lineno)
            visit(node.value, held)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fnnode.body:
        visit(stmt, ())
    return [fn] + nested


# ---------------------------------------------------------------------------
# phase 3: interprocedural closure + findings
# ---------------------------------------------------------------------------


class _Resolver:
    """Receiver-aware callee lookup: ``self.f()`` resolves in the same
    class, ``f()`` in the same module, ``self.attr.f()`` / ``param.f()``
    through ``__init__``/parameter type annotations; only then does it
    fall back to a global name match.  Calls through externally-imported
    roots never reach here (filtered at collection time)."""

    def __init__(self, fns: list[_Fn], pkg: _Package):
        self.by_name: dict[str, list[_Fn]] = {}
        for f in fns:
            self.by_name.setdefault(f.name, []).append(f)
        self.pkg = pkg

    def callees(self, f: _Fn, simple: str, recv: str) -> list[_Fn]:
        cands = self.by_name.get(simple)
        if not cands:
            return []
        if recv == "self":
            if f.scope is not None and f.scope.is_class:
                same = [g for g in cands if g.scope is f.scope]
                if same:
                    return same
        elif recv.startswith("self.") and recv.count(".") == 1:
            attr = recv.split(".", 1)[1]
            owner = self.pkg.classes.get(
                f.scope.attr_types.get(attr, "") if f.scope else ""
            )
            if owner is not None:
                typed = [g for g in cands if g.scope is owner]
                if typed:
                    return typed
        elif recv and "." not in recv:
            owner = self.pkg.classes.get(f.params.get(recv, ""))
            if owner is not None:
                typed = [g for g in cands if g.scope is owner]
                if typed:
                    return typed
        elif not recv:
            same_file = [g for g in cands if g.filename == f.filename]
            if same_file:
                return same_file
        return cands


def _closure_acquires(
    fns: list[_Fn], resolver: _Resolver
) -> dict[str, set[str]]:
    eff = {f.qual: set(f.acquires) for f in fns}
    changed = True
    while changed:
        changed = False
        for f in fns:
            cur = eff[f.qual]
            before = len(cur)
            for simple, recv, _ in f.call_entries:
                for g in resolver.callees(f, simple, recv):
                    cur |= eff[g.qual]
            if len(cur) != before:
                changed = True
    return eff


def _closure_blocking(
    fns: list[_Fn], resolver: _Resolver
) -> dict[str, dict[str, tuple[str, tuple[str, ...]]]]:
    """qual -> {kind: (severity, via-chain of callee names)}."""
    eff: dict[str, dict[str, tuple[str, tuple[str, ...]]]] = {
        f.qual: {k: (s, ()) for k, s, _ in f.blocking_any} for f in fns
    }
    changed = True
    while changed:
        changed = False
        for f in fns:
            cur = eff[f.qual]
            for simple, recv, _ in f.call_entries:
                for g in resolver.callees(f, simple, recv):
                    for kind, (sev, via) in eff[g.qual].items():
                        if kind not in cur and len(via) < 6:
                            cur[kind] = (sev, (simple,) + via)
                            changed = True
    return eff


def _find_cycles(
    edges: dict[tuple[str, str], tuple[str, int, str]],
) -> list[list[str]]:
    adj: dict[str, list[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    color: dict[str, int] = {}
    stack: list[str] = []
    cycles: list[list[str]] = []
    seen: set[tuple[str, ...]] = set()

    def dfs(u: str) -> None:
        color[u] = 1
        stack.append(u)
        for v in sorted(adj[u]):
            if color.get(v, 0) == 0:
                dfs(v)
            elif color.get(v) == 1:
                cyc = stack[stack.index(v):] + [v]
                ring = cyc[:-1]
                i = ring.index(min(ring))
                key = tuple(ring[i:] + ring[:i])
                if key not in seen:
                    seen.add(key)
                    cycles.append(list(key) + [key[0]])
        stack.pop()
        color[u] = 2

    for node in sorted(adj):
        if color.get(node, 0) == 0:
            dfs(node)
    return cycles


def _analyze(pkg: _Package) -> list[Finding]:
    findings: list[Finding] = list(pkg.unparseable)
    fns: list[_Fn] = []
    for mod in pkg.modules:
        ext = frozenset(mod.external_roots)
        for fnnode, scope in mod.fns:
            fns.extend(_walk_fn(fnnode, scope, mod.mod_scope, pkg, ext))
    resolver = _Resolver(fns, pkg)

    # --- unguarded-shared-write -------------------------------------
    # attr_key -> set of locks seen held during a write (the guards)
    guards: dict[tuple, set[str]] = {}
    for f in fns:
        for attr_key, _, held, _, _ in f.writes:
            if held:
                guards.setdefault(attr_key, set()).update(held)

    def scope_concurrent(s: _Scope) -> bool:
        return s.threaded or bool(s.locks) or s.acquires_any

    for f in fns:
        if f.scope is None or not scope_concurrent(f.scope):
            continue
        for attr_key, attr_name, held, lineno, rmw in f.writes:
            g = guards.get(attr_key)
            if not rmw or not g or (held & g):
                continue
            locks = ", ".join(sorted(g))
            findings.append(
                Finding(
                    PASS,
                    "error",
                    "unguarded-shared-write",
                    f"{f.filename}:{lineno}: {f.qual} read-modify-"
                    f"writes {attr_name} without holding {locks} "
                    f"(guarded: written under that lock elsewhere in "
                    f"{attr_key[1]}) — lost-update race",
                    {
                        "file": f.filename,
                        "line": lineno,
                        "attr": attr_name,
                        "locks": sorted(g),
                        "function": f.qual,
                    },
                )
            )

    # --- lock-order graph + cycles ----------------------------------
    eff_acq = _closure_acquires(fns, resolver)
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}
    for f in fns:
        for a, b, lineno in f.direct_edges:
            edges.setdefault(
                (a, b), (f.filename, lineno, f"nested with in {f.qual}")
            )
        for held, simple, recv, lineno in f.calls_under:
            acq: set[str] = set()
            for g in resolver.callees(f, simple, recv):
                acq |= eff_acq[g.qual]
            for h in held:
                for l in acq:
                    if h != l:
                        edges.setdefault(
                            (h, l),
                            (
                                f.filename,
                                lineno,
                                f"{f.qual} calls {simple}() under {h}",
                            ),
                        )
    for cyc in _find_cycles(edges):
        path = " -> ".join(cyc)
        sites = "; ".join(
            f"{a}->{b} ({edges[(a, b)][0]}:{edges[(a, b)][1]}, "
            f"{edges[(a, b)][2]})"
            for a, b in zip(cyc, cyc[1:])
            if (a, b) in edges
        )
        findings.append(
            Finding(
                PASS,
                "error",
                "lock-order-inversion",
                f"lock acquisition order contains a cycle: {path} — two "
                f"threads taking these in opposite orders deadlock. "
                f"Edges: {sites}",
                {"cycle": cyc, "edges": sites},
            )
        )

    # --- blocking-under-lock ----------------------------------------
    eff_blk = _closure_blocking(fns, resolver)
    emitted: set[tuple] = set()
    for f in fns:
        for held, kind, sev, lineno in f.blocking_under:
            key = (f.filename, lineno, kind)
            if key in emitted:
                continue
            emitted.add(key)
            findings.append(
                Finding(
                    PASS,
                    sev,
                    "blocking-under-lock",
                    f"{f.filename}:{lineno}: {f.qual} calls {kind} "
                    f"while holding {held[-1]} — the lock is "
                    f"unavailable for the full blocking duration",
                    {
                        "file": f.filename,
                        "line": lineno,
                        "call": kind,
                        "lock": held[-1],
                        "function": f.qual,
                    },
                )
            )
        for held, simple, recv, lineno in f.calls_under:
            for g in resolver.callees(f, simple, recv):
                for kind, (sev, via) in eff_blk[g.qual].items():
                    key = (f.filename, lineno, kind)
                    if key in emitted:
                        continue
                    emitted.add(key)
                    chain = " -> ".join((simple,) + via)
                    findings.append(
                        Finding(
                            PASS,
                            sev,
                            "blocking-under-lock",
                            f"{f.filename}:{lineno}: {f.qual} holds "
                            f"{held[-1]} across {chain} which reaches "
                            f"{kind}",
                            {
                                "file": f.filename,
                                "line": lineno,
                                "call": kind,
                                "via": chain,
                                "lock": held[-1],
                                "function": f.qual,
                            },
                        )
                    )
    return findings


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def lint_concurrency_sources(
    sources: Mapping[str, str],
) -> list[Finding]:
    """Run the pass over {filename: source}. Whole-package: lock ids and
    the call graph resolve across all given modules."""
    pkg = _Package()
    for filename, source in sources.items():
        pkg.add_source(filename, source)
    return _analyze(pkg)


def lint_concurrency_source(
    source: str, filename: str = "<source>"
) -> list[Finding]:
    """Single-module convenience wrapper (synthetic-source tests)."""
    return lint_concurrency_sources({filename: source})


def lint_concurrency_paths(paths: Iterable[str]) -> list[Finding]:
    sources: dict[str, str] = {}
    for p in paths:
        with open(p, "r", encoding="utf-8") as fh:
            sources[p] = fh.read()
    return lint_concurrency_sources(sources)
