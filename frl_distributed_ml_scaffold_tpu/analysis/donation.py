"""Pass 4 — donation / aliasing audit.

Buffer donation is a memory invariant, not a numerics one, so nothing
fails when it silently regresses — a train step that stops donating its
optimizer state doubles resident state and only shows up as an OOM three
refactors later.  This pass makes donation machine-checkable at both
stages jax exposes:

- **lowered StableHLO**: a donated input is annotated on the ``@main``
  signature — ``tf.aliasing_output = N : i32`` when the lowering already
  established the alias, or ``jax.buffer_donor = true`` when the decision
  is deferred to the compiler.  This is the cheap, compile-free check the
  CLI runs for every recipe.
- **compiled HLO**: the executable's ``input_output_alias={ ... }`` table
  is the ground truth "actually aliased" fact (donating a buffer the
  compiler cannot alias is legal and silently useless).  Used by the
  targeted pin tests, which afford the compile.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable

from frl_distributed_ml_scaffold_tpu.analysis.findings import Finding


@dataclasses.dataclass
class ArgDonation:
    index: int
    aliased_output: int | None  # tf.aliasing_output target, if resolved
    donor: bool  # jax.buffer_donor marker (deferred alias)

    @property
    def donated(self) -> bool:
        return self.donor or self.aliased_output is not None


def _main_signature(text: str) -> str:
    """The argument list of the public @main func in StableHLO text."""
    start = text.find("@main(")
    if start < 0:
        return ""
    # The signature ends at the ``->`` (or the opening brace for
    # zero-result functions); both appear after the closing paren.
    end = text.find("->", start)
    if end < 0:
        end = text.find("{", start)
    return text[start:end if end > 0 else len(text)]


# The attr dict may carry quoted strings containing braces
# (mhlo.sharding = "{replicated}") and one level of nested braces —
# match accordingly or the donation attrs after a sharding attr vanish.
_ARG = re.compile(
    r"%arg(\d+):\s*tensor<[^>]*>\s*"
    r"(\{(?:[^{}\"]+|\"[^\"]*\"|\{[^{}]*\})*\})?"
)


def lowered_donations(lowered_or_text: Any) -> list[ArgDonation]:
    """Donation markers per @main argument of a lowered module.

    Accepts a ``jax.stages.Lowered`` or its ``as_text()`` string.
    """
    text = (
        lowered_or_text
        if isinstance(lowered_or_text, str)
        else lowered_or_text.as_text()
    )
    sig = _main_signature(text)
    out = []
    for m in _ARG.finditer(sig):
        idx = int(m.group(1))
        attrs = m.group(2) or ""
        alias = re.search(r"tf\.aliasing_output\s*=\s*(\d+)", attrs)
        out.append(
            ArgDonation(
                index=idx,
                aliased_output=int(alias.group(1)) if alias else None,
                donor="jax.buffer_donor" in attrs,
            )
        )
    return out


_ALIAS_ENTRY = re.compile(
    r"\{([0-9,\s]*)\}:\s*\((\d+),\s*\{[0-9,\s]*\},\s*(may-alias|must-alias)\)"
)


def compiled_aliases(compiled_or_text: Any) -> list[dict[str, Any]]:
    """The executable's input/output alias table.

    Accepts a ``jax.stages.Compiled`` or its ``as_text()`` string; each
    entry is ``{"output": (..indices..), "param": n, "kind": "may-alias"}``.
    The table sits on the HloModule header line
    (``input_output_alias={ {1}: (2, {}, may-alias), ... }``); entry
    syntax is specific enough to scan that line directly.
    """
    text = (
        compiled_or_text
        if isinstance(compiled_or_text, str)
        else compiled_or_text.as_text()
    )
    lines = [l for l in text.splitlines() if "input_output_alias=" in l]
    if not lines:
        return []
    out = []
    for e in _ALIAS_ENTRY.finditer(lines[0]):
        idx = tuple(int(x) for x in e.group(1).split(",") if x.strip())
        out.append(
            {"output": idx, "param": int(e.group(2)), "kind": e.group(3)}
        )
    return out


def args_info_donations(lowered: Any) -> list[tuple[str, bool]] | None:
    """``(tree path, donated)`` per argument leaf via
    ``jax.stages.Lowered.args_info`` — the request-level donation record
    in the call's own tree structure, immune to the unused-arg pruning
    that breaks positional text mapping (adafactor's ``(1,)`` stubs are
    pruned from @main but still present here).  Returns None when the
    jax version has no ``args_info``."""
    import jax

    info = getattr(lowered, "args_info", None)
    if info is None:
        return None
    return [
        (jax.tree_util.keystr(path), bool(x.donated))
        for path, x in jax.tree_util.tree_leaves_with_path(
            info, is_leaf=lambda x: hasattr(x, "donated")
        )
    ]


def donation_findings(
    lowered_or_text: Any,
    *,
    arg_paths: list[str] | None = None,
    expect_donated: Callable[[str], bool] | None = None,
    label: str = "",
) -> list[Finding]:
    """Audit a lowered program's donation markers.

    ``arg_paths`` maps flat @main argument order to pytree key paths (from
    ``jax.tree_util.tree_leaves_with_path`` over the call's arguments —
    jit flattens in exactly that order); ``expect_donated(path)`` says
    which leaves the caller pins as donated (e.g. params + opt_state).
    Without expectations the pass reports an info summary only.
    """
    dons = lowered_donations(lowered_or_text)
    n_donated = sum(1 for d in dons if d.donated)
    n_aliased = sum(1 for d in dons if d.aliased_output is not None)
    out = [
        Finding(
            "donation", "info", "summary",
            f"{label}{n_donated}/{len(dons)} args donated "
            f"({n_aliased} with resolved output alias)",
            {"args": len(dons), "donated": n_donated, "aliased": n_aliased},
        )
    ]
    if expect_donated is None:
        return out
    if arg_paths is None or len(arg_paths) != len(dons):
        out.append(
            Finding(
                "donation", "warning", "arg-mapping",
                f"{label}cannot map {len(dons)} lowered args onto "
                f"{len(arg_paths) if arg_paths is not None else 0} tree "
                "leaves (pruned/extra args?); donation audited by count "
                "only",
                {"args": len(dons),
                 "leaves": len(arg_paths) if arg_paths else 0},
            )
        )
        if n_donated == 0:
            out.append(
                Finding(
                    "donation", "error", "not-donated",
                    f"{label}no argument is donated but donation was "
                    "expected",
                    {},
                )
            )
        return out
    for d, path in zip(dons, arg_paths):
        if expect_donated(path) and not d.donated:
            out.append(
                Finding(
                    "donation", "error", "not-donated",
                    f"{label}argument {d.index} ({path}) is expected "
                    "donated but carries no donation marker — resident "
                    "state doubles",
                    {"arg": d.index, "path": path},
                )
            )
    return out
