"""Pass 3 — materialization budget.

Generalizes PR 4's decode pin ("no full-seq_len arrays in a bucketed
decode step"): every eqn OUTPUT in a program is an array the step may
materialize; any one larger than the per-recipe byte budget — or carrying
a forbidden dimension — is a finding.  Program INPUTS (params, caches)
are exempt by construction: only eqn outvars are walked, so a big weight
passing through untouched never trips the budget, exactly like the
original pin's "seq_len appears only in the wpe PARAM" carve-out.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from frl_distributed_ml_scaffold_tpu.analysis.findings import Finding
from frl_distributed_ml_scaffold_tpu.analysis.jaxpr_utils import (
    aval_bytes,
    close,
    iter_eqns,
)


@dataclasses.dataclass(frozen=True)
class Intermediate:
    shape: tuple[int, ...]
    dtype: str
    bytes: int
    primitive: str
    path: tuple[str, ...]

    def to_dict(self) -> dict[str, Any]:
        return {
            "shape": list(self.shape),
            "dtype": self.dtype,
            "bytes": self.bytes,
            "primitive": self.primitive,
            "path": list(self.path),
        }


def intermediates(jaxpr: Any) -> list[Intermediate]:
    """Every eqn output in the program, with its byte size."""
    out = []
    for eqn, path, _trips in iter_eqns(close(jaxpr)):
        for v in eqn.outvars:
            aval = v.aval
            if not hasattr(aval, "shape"):
                continue
            out.append(
                Intermediate(
                    shape=tuple(aval.shape),
                    dtype=str(getattr(aval, "dtype", "?")),
                    bytes=aval_bytes(aval),
                    primitive=str(eqn.primitive),
                    path=path,
                )
            )
    return out


def max_materialized_bytes(jaxpr: Any) -> int:
    """The largest single intermediate in the program (bytes)."""
    return max((i.bytes for i in intermediates(jaxpr)), default=0)


def oversized_intermediates(
    jaxpr: Any, budget_bytes: int
) -> list[Intermediate]:
    """Intermediates whose single-array size exceeds the budget."""
    return [i for i in intermediates(jaxpr) if i.bytes > budget_bytes]


def intermediates_with_dim(jaxpr: Any, dim: int) -> list[Intermediate]:
    """Intermediates carrying ``dim`` in their shape — the decode pin's
    "full-seq_len array materialized" detector."""
    return [i for i in intermediates(jaxpr) if dim in i.shape]


def _itemsize(i: Intermediate) -> int:
    """Element width recovered from the byte census itself (no dtype-
    string parsing: ``bytes / numel`` is already exact)."""
    import numpy as np

    n = int(np.prod(i.shape, dtype=np.int64)) if i.shape else 1
    return i.bytes // max(n, 1)


def wide_intermediates_with_dims(
    jaxpr: Any, dims: tuple[int, ...], *, min_itemsize: int = 2
) -> list[Intermediate]:
    """Float intermediates of element width >= ``min_itemsize`` whose
    shape contains every dim of ``dims`` (with multiplicity, in ANY
    order — a layout transpose must not dodge the pin) — the quantized-
    cache pin's detector: with an int8 KV cache of geometry
    ``(S, H, hd)``, a decode step materializing a wide-float array
    carrying all three dims has dequantized the whole cache, whether in
    the storage layout ``[B, S, H, hd]`` or the kernel's transposed
    ``[B, H, S, hd]`` (the exact allocation the quantized cache exists
    to avoid; its 1-byte cache updates and its small per-chunk/per-scale
    floats all lack the full ``S`` dim and pass)."""
    from collections import Counter

    need = Counter(dims)
    out = []
    for i in intermediates(jaxpr):
        if not i.dtype.startswith(("float", "bfloat")):
            continue
        if _itemsize(i) < min_itemsize:
            continue
        if not need - Counter(i.shape):
            out.append(i)
    return out


def materialization_findings(
    jaxpr: Any,
    *,
    budget_bytes: int | None = None,
    forbidden_dim: int | None = None,
    top_k: int = 3,
    label: str = "",
) -> list[Finding]:
    """Budget + forbidden-dim checks as findings; always reports the
    ``top_k`` largest intermediates as info rows (the diffable census of
    where the memory goes)."""
    out: list[Finding] = []
    ints = intermediates(jaxpr)
    for i in sorted(ints, key=lambda x: -x.bytes)[:top_k]:
        out.append(
            Finding(
                "materialization", "info", "largest-intermediate",
                f"{label}{i.dtype}{list(i.shape)} = {i.bytes} bytes "
                f"({i.primitive})",
                {"intermediate": i.to_dict()},
            )
        )
    if budget_bytes is not None:
        for i in ints:
            if i.bytes > budget_bytes:
                out.append(
                    Finding(
                        "materialization", "error", "over-budget",
                        f"{label}intermediate {i.dtype}{list(i.shape)} is "
                        f"{i.bytes} bytes > budget {budget_bytes} "
                        f"({i.primitive} at {'/'.join(i.path) or 'top'})",
                        {"intermediate": i.to_dict(), "budget": budget_bytes},
                    )
                )
    if forbidden_dim is not None:
        for i in (x for x in ints if forbidden_dim in x.shape):
            out.append(
                Finding(
                    "materialization", "error", "forbidden-dim",
                    f"{label}intermediate {i.dtype}{list(i.shape)} carries "
                    f"forbidden dim {forbidden_dim} ({i.primitive})",
                    {"intermediate": i.to_dict(), "dim": forbidden_dim},
                )
            )
    return out
