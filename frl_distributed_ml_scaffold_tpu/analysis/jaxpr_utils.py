"""Shared jaxpr traversal for every analyzer pass (and ``analysis.pins``).

This is THE walker the per-test copies in tests/test_tp_overlap.py,
tests/test_fsdp_overlap.py and tests/test_decode_attention.py grew from —
promoted here so every pin and pass agrees on what "recurse into
sub-jaxprs" means: scan/while/cond bodies, pjit/remat calls, custom-VJP
closures, and shard_map regions are all descended.
"""

from __future__ import annotations

from typing import Any, Iterator

import numpy as np


def subjaxprs(eqn: Any) -> Iterator[Any]:
    """Yield every sub-jaxpr reachable from one equation's params
    (ClosedJaxpr's inner jaxpr included)."""
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else [v]
        for u in vs:
            if hasattr(u, "eqns"):
                yield u
            elif hasattr(u, "jaxpr") and hasattr(u.jaxpr, "eqns"):
                yield u.jaxpr


def iter_eqns(
    jaxpr: Any, _path: tuple[str, ...] = (), _trips: int = 1
) -> Iterator[tuple[Any, tuple[str, ...], int]]:
    """Yield ``(eqn, enclosing_primitive_path, trip_count)`` over the whole
    program, depth-first.

    ``trip_count`` multiplies the static trip counts of enclosing scans
    (``scan.length``) so a collective inside the layer scan is counted
    once per layer — the number that matters for bytes-on-the-wire.
    """
    for eqn in jaxpr.eqns:
        yield eqn, _path, _trips
        name = str(eqn.primitive)
        trips = _trips
        if name == "scan":
            trips *= int(eqn.params.get("length", 1) or 1)
        for sub in subjaxprs(eqn):
            yield from iter_eqns(sub, _path + (name,), trips)


def close(jaxpr: Any) -> Any:
    """Accept either a ClosedJaxpr or a raw Jaxpr and return the raw one."""
    return jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr


def primitive_shapes(
    jaxpr: Any, prim_name: str
) -> list[tuple[tuple[int, ...], ...]]:
    """Output shapes of every eqn whose primitive name CONTAINS
    ``prim_name`` (substring, the historical test-pin contract), one tuple
    of out-shapes per matching eqn, sub-jaxprs included."""
    found = []
    for eqn, _path, _trips in iter_eqns(close(jaxpr)):
        if prim_name in str(eqn.primitive):
            found.append(tuple(v.aval.shape for v in eqn.outvars))
    return found


def eqn_output_shapes(jaxpr: Any) -> list[tuple[int, ...]]:
    """Every eqn output shape in the program (the decode-pin walker)."""
    acc = []
    for eqn, _path, _trips in iter_eqns(close(jaxpr)):
        for v in eqn.outvars:
            if hasattr(v.aval, "shape"):
                acc.append(tuple(v.aval.shape))
    return acc


def aval_bytes(aval: Any) -> int:
    """Bytes of one abstract value; extended dtypes (PRNG keys) fall back
    to their element-type itemsize, shapeless avals count zero."""
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    dtype = getattr(aval, "dtype", None)
    itemsize = getattr(dtype, "itemsize", None)
    if itemsize is None:
        itemsize = 4
    return int(np.prod(shape, dtype=np.int64)) * int(itemsize) if shape else int(itemsize)


def top_level_scans(jaxpr: Any) -> list[Any]:
    """The top-level scan eqns of a program (forward/backward layer loops,
    grad-accum microbatch loop) — the granularity the blockwise pins count
    collectives at."""
    return [e for e in close(jaxpr).eqns if str(e.primitive) == "scan"]
