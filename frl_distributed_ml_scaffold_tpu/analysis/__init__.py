"""graft-lint: jaxpr/HLO static analysis for performance invariants.

The subsystem behind ``tools/graft_lint.py`` and the ``analysis.pins``
pytest API (docs/static_analysis.md).  Six passes over three program
artifacts:

====================  ==========================  =======================
pass                  artifact                    module
====================  ==========================  =======================
collective census     closed jaxpr + HLO text     analysis.collectives
reshard detector      jaxpr + compiled HLO        analysis.reshard
materialization       closed jaxpr                analysis.materialization
donation audit        lowered + compiled text     analysis.donation
traced-code hygiene   Python AST                  analysis.hygiene
declared schedule     jaxpr + OverlapSchedule     analysis.schedule
====================  ==========================  =======================

``analysis.pins`` wraps the passes as test assertions; ``analysis.runner``
drives them over every registered recipe.  Keep jax imports lazy at the
module level so ``tools/graft_lint.py`` can set platform env vars first.
"""

from frl_distributed_ml_scaffold_tpu.analysis.findings import (  # noqa: F401
    Finding,
    Report,
)
from frl_distributed_ml_scaffold_tpu.analysis import pins  # noqa: F401
