"""graft-lint driver: run the analyzer passes over every registered
recipe's train step (and the serving decode step) on the CPU-sim mesh.

Everything here is TRACE-ONLY: the train step is inspected via
``jax.make_jaxpr`` on abstract inputs (the Trainer's ``state_shapes``
eval_shape tree) and via AOT ``.lower()`` — no XLA compile, so linting
all 17 recipes stays inside the fast-tier budget.  Compile-level checks
(GSPMD-inserted collectives, executable alias tables) are the pin tests'
job, which afford one tiny compile each.

Per-recipe invariants enforced as ``severity:error``:

- donation: every params/opt-state leaf of the train state is donated in
  the lowered step (the jit's ``donate_argnums=(0,)`` actually took).
- overlap recipes: the declared-schedule checker (analysis/schedule.py,
  ISSUE 13) — expectations derived from the recipe's ``OverlapSchedule``
  declaration itself, absorbing PR 3's zero-all_gather and PR 2's
  blockwise/reduce-scatter pins plus PR 6's lowp payload/bytes pins.
  Also emitted per recipe as the ``schedule:<name>`` program family.
- optional materialization budget (``--budget-mb``).

The serving decode lint builds the tiny-GPT decode step at a 16-token
bucket of a 64-token model and pins: no full-``seq_len`` intermediate
(PR 4), and the engine's decode/graft programs donate the cache (PR 5's
leak fix).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import numpy as np

from frl_distributed_ml_scaffold_tpu.analysis.collectives import (
    census_by_dtype,
    census_summary,
    collective_census,
)
from frl_distributed_ml_scaffold_tpu.analysis.donation import (
    donation_findings,
)
from frl_distributed_ml_scaffold_tpu.analysis.findings import Report
from frl_distributed_ml_scaffold_tpu.analysis.materialization import (
    materialization_findings,
)

_COMMON = [
    "precision.policy=fp32",
    "trainer.log_every=100000",
    "checkpoint.enabled=false",
    "optimizer.warmup_steps=0",
]

_GPT_TINY = [
    "model.vocab_size=128", "model.num_layers=2", "model.num_heads=4",
    "model.hidden_dim=64", "model.seq_len=32",
    "data.vocab_size=128", "data.seq_len=32", "data.global_batch_size=16",
    "trainer.grad_accum=2",
]

_VIT_TINY = [
    "model.image_size=32", "model.patch_size=8", "model.hidden_dim=64",
    "model.num_layers=2", "model.num_heads=4", "model.num_classes=8",
    "data.image_size=32", "data.num_classes=8", "data.global_batch_size=16",
]

_RN_TINY = [
    "model.depth=10", "model.num_classes=8",
    "data.image_size=32", "data.num_classes=8", "data.global_batch_size=16",
]

_VIDEO_TINY = [
    "model.image_size=16", "model.num_frames=4", "model.tubelet_size=2,8,8",
    "model.hidden_dim=64", "model.num_layers=2", "model.num_heads=4",
    "model.num_classes=8",
    "data.image_size=16", "data.num_frames=4", "data.num_classes=8",
    "data.global_batch_size=16",
]

_PP_TINY = [
    "model.vocab_size=128", "model.num_layers=8", "model.num_heads=2",
    "model.hidden_dim=32", "model.seq_len=32",
    "model.pipeline_microbatches=4",
    "data.vocab_size=128", "data.seq_len=32", "data.global_batch_size=8",
    "trainer.grad_accum=1",
]

#: Wide-dtype ppermute payloads at or under this many bytes/call are
#: quantization SCALES, not chunk traffic — the carve-out is owned by
#: the declarative schedule checker; aliased here for back-compat.
from frl_distributed_ml_scaffold_tpu.analysis.schedule import (
    SCALE_BYTES_PER_CALL as _SCALE_BYTES_PER_CALL,
)

# CPU-sim (8 virtual devices) shrink overrides per registered recipe —
# the test_recipes.py discipline, centralized. A NEW recipe must either
# inherit a family entry below or add its own; ``lint_recipe`` raises on
# unknown names so the CLI catches unshrunk recipes instead of tracing a
# 345M-param program.
RECIPE_OVERRIDES: dict[str, list[str]] = {
    "mnist_mlp": ["data.global_batch_size=16"],
    "imagenet_rn50_ddp": _RN_TINY + ["mesh.data=8"],
    "imagenet_rn101_ddp": _RN_TINY + ["model.depth=10", "mesh.data=8"],
    "imagenet_vitb_fsdp": _VIT_TINY
    + ["mesh.fsdp=8", "parallel.fsdp_min_size=64"],
    "imagenet_vitl_fsdp": _VIT_TINY
    + ["mesh.fsdp=8", "parallel.fsdp_min_size=64", "trainer.remat=none"],
    "gpt2_medium_zero1": _GPT_TINY + ["mesh.fsdp=8"],
    "gpt2_medium_adafactor": _GPT_TINY + ["mesh.fsdp=8"],
    "ego4d_video_elastic": _VIDEO_TINY
    + ["mesh.fsdp=8", "parallel.fsdp_min_size=64"],
    "gpt2_medium_fsdp_overlap": _GPT_TINY
    + ["mesh.fsdp=8", "parallel.fsdp_min_size=16"],
    "gpt2_medium_tp_overlap": _GPT_TINY
    + ["mesh.data=1", "mesh.model=8"],
    "gpt2_medium_tp_overlap_int8": _GPT_TINY
    + ["mesh.data=1", "mesh.model=8"],
    "gpt2_medium_fsdp_tp_overlap": _GPT_TINY
    + ["mesh.fsdp=4", "mesh.model=2", "parallel.fsdp_min_size=16"],
    "gpt2_medium_fsdp_tp_overlap_int8": _GPT_TINY
    + ["mesh.fsdp=4", "mesh.model=2", "parallel.fsdp_min_size=16"],
    "gpt2_tp": _GPT_TINY + ["mesh.data=4", "mesh.model=2"],
    "gpt2_ring": [
        "model.vocab_size=128", "model.num_layers=2", "model.num_heads=4",
        "model.hidden_dim=64", "model.seq_len=64",
        "data.vocab_size=128", "data.seq_len=64", "data.global_batch_size=8",
        "mesh.data=2", "mesh.seq=4",
    ],
    "gpt2_long": [
        "model.vocab_size=128", "model.num_layers=2", "model.num_heads=4",
        "model.hidden_dim=64", "model.seq_len=256", "model.lm_loss_chunk=64",
        "data.vocab_size=128", "data.seq_len=256", "data.global_batch_size=8",
        "trainer.grad_accum=2", "mesh.data=8",
    ],
    "gpt2_moe": [
        "model.vocab_size=128", "model.num_layers=2", "model.num_heads=4",
        "model.hidden_dim=64", "model.seq_len=32", "model.moe.num_experts=4",
        "data.vocab_size=128", "data.seq_len=32", "data.global_batch_size=16",
        "mesh.data=2", "mesh.expert=4",
    ],
    "gpt2_pp": _PP_TINY + ["mesh.pipe=4", "mesh.data=2"],
    "gpt2_pp_circular": _PP_TINY + ["mesh.pipe=4", "mesh.data=2"],
    "gpt2_pipeline_mpmd": _PP_TINY + ["mesh.pipe=4", "mesh.data=2"],
    "gpt2_medium_serve": _GPT_TINY + ["mesh.data=4", "mesh.model=2"],
}


def _build_trainer(name: str, workdir: str):
    from frl_distributed_ml_scaffold_tpu.config import (
        apply_overrides,
        get_config,
    )
    from frl_distributed_ml_scaffold_tpu.dist.mesh import build_mesh
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    if name not in RECIPE_OVERRIDES:
        raise KeyError(
            f"recipe {name!r} has no CPU-sim shrink overrides in "
            "analysis.runner.RECIPE_OVERRIDES — add one so graft_lint "
            "traces a tiny twin, not the production shapes"
        )
    cfg = apply_overrides(
        get_config(name),
        _COMMON + RECIPE_OVERRIDES[name] + [f"workdir={workdir}"],
    )
    return Trainer(cfg, mesh_env=build_mesh(cfg.mesh))


def _abstract_batch(trainer) -> Any:
    import jax
    import numpy as np

    from frl_distributed_ml_scaffold_tpu.trainer.tasks import example_input

    example = example_input(
        trainer.cfg.data, trainer.cfg.model,
        batch_size=trainer.cfg.data.global_batch_size,
    )
    return {
        k: jax.ShapeDtypeStruct(np.shape(v), np.asarray(v).dtype)
        for k, v in example.items()
    }


def _recipe_schedule(cfg):
    """The recipe's declared overlap schedule (None when it runs the
    plain GSPMD schedules)."""
    from frl_distributed_ml_scaffold_tpu.parallel.schedule import (
        schedule_from_config,
    )

    return schedule_from_config(cfg)


def _lint_recipe_reports(
    name: str,
    *,
    workdir: str = "/tmp/graft_lint",
    budget_bytes: int | None = None,
) -> list[Report]:
    """One trainer build + trace for a recipe, emitted as up to two
    reports: the per-recipe report (every pass) and — when the recipe
    declares an overlap schedule — the ``schedule:<name>`` program
    family report (the declaration-first view of the same schedule
    findings, with the declaration in ``meta`` so
    ``--save-census``/``--against`` diffs key per schedule)."""
    import jax

    report = Report(program=f"recipe:{name}")
    trainer = _build_trainer(name, workdir)
    cfg = trainer.cfg
    if getattr(trainer, "_mpmd", None) is not None:
        # MPMD pipeline recipes (ISSUE 14) have no single train-step
        # program: the recipe report AND the pipeline:stage_program
        # family both come from the per-stage artifacts.
        return _lint_mpmd_reports(name, trainer)
    state_shapes = trainer.state_shapes
    batch = _abstract_batch(trainer)

    jaxpr = trainer._mesh_scoped(jax.make_jaxpr(trainer._train_step_fn))(
        state_shapes, batch
    )

    # -- pass 1: collective census (info; the diffable artifact) --------
    census = collective_census(jaxpr)
    report.meta["collective_census"] = [r.to_dict() for r in census]
    for prim, agg in sorted(census_summary(census).items()):
        report.add(
            "collective_census", "info", "census",
            f"{prim}: {agg['eqns']} eqn(s), {agg['calls']} call(s)/step, "
            f"{agg['total_bytes']} bytes",
            primitive=prim, **agg,
        )

    # -- pass 2: declared-schedule invariants (ISSUE 13) ----------------
    # The recipe's OverlapSchedule declaration IS the expectation: one
    # derivation (analysis/schedule.py) replaces the hand-written
    # tp_overlap zero-all_gather and fsdp_overlap blockwise /
    # reduce-scatter pins this pass used to carry — same finding codes,
    # now derived from what the recipe DECLARES instead of which knob
    # it flipped.
    sched = _recipe_schedule(cfg)
    sched_report = None
    if sched is not None:
        from frl_distributed_ml_scaffold_tpu.analysis.schedule import (
            schedule_findings,
        )
        from frl_distributed_ml_scaffold_tpu.parallel.partition import (
            block_param_slice_shapes,
        )

        report.meta["schedule"] = sched.describe()
        slices = None
        if sched.block_gather() is not None:
            slices = block_param_slice_shapes(
                state_shapes.params, trainer.env.axis_size("model")
            )
        axis_sizes = {
            a: trainer.env.axis_size(a)
            for a in ("data", "fsdp", "model", "seq", "expert", "pipe")
        }
        found = schedule_findings(
            jaxpr, sched, axis_sizes=axis_sizes, param_slices=slices,
            census=census, label=f"{name}: ",
        )
        report.extend(found)
        # The schedule: family rides the SAME trace — no second trainer
        # build for the declaration-first view.
        sched_report = Report(program=f"schedule:{name}")
        sched_report.meta["schedule"] = sched.describe()
        sched_report.meta["collective_census"] = report.meta[
            "collective_census"
        ]
        sched_report.extend(found)
        if sched_report.ok:
            sched_report.add(
                "schedule", "info", "summary",
                f"{name}: program matches its declared schedule "
                f"{sched.render()!r}",
            )
        ring = sched.ring_gather()
        if ring is not None and ring.lowp is not None:
            # Observability: the per-dtype ppermute breakdown next to the
            # declared-lowp errors above.
            for (prim, dtype), agg in sorted(
                census_by_dtype(census).items()
            ):
                if prim != "ppermute":
                    continue
                report.add(
                    "collective_census", "info", "census-by-dtype",
                    f"ppermute[{dtype}]: {agg['eqns']} eqn(s), "
                    f"{agg['calls']} call(s)/step, "
                    f"{agg['total_bytes']} bytes",
                    primitive=prim, dtype=dtype, **agg,
                )

    # -- pass 3: materialization census / budget ------------------------
    report.extend(
        materialization_findings(
            jaxpr, budget_bytes=budget_bytes, label=f"{name}: "
        )
    )

    # -- pass 4: donation audit on the lowered step ---------------------
    lowered = trainer._mesh_scoped(trainer._train_step_jit.lower)(
        state_shapes, batch
    )
    from frl_distributed_ml_scaffold_tpu.analysis.donation import (
        args_info_donations,
        lowered_donations,
    )

    pairs = args_info_donations(lowered)
    text_donated = sum(
        1 for d in lowered_donations(lowered.as_text()) if d.donated
    )
    if pairs is None:
        # Old jax without args_info: fall back to marker counting.
        report.extend(
            donation_findings(lowered.as_text(), label=f"{name}: ")
        )
        if text_donated == 0:
            report.add(
                "donation", "error", "not-donated",
                f"{name}: no lowered argument carries a donation marker "
                "— donate_argnums went missing",
            )
        return [report] + ([sched_report] if sched_report else [])
    missing = [
        p
        for p, donated in pairs
        if (".params" in p or ".opt_state" in p) and not donated
    ]
    n_donated = sum(1 for _, d in pairs if d)
    report.add(
        "donation", "info", "summary",
        f"{name}: {n_donated}/{len(pairs)} arg leaves donated "
        f"({text_donated} donation markers survive in lowered StableHLO)",
        donated=n_donated, args=len(pairs), markers=text_donated,
    )
    for p in missing:
        report.add(
            "donation", "error", "not-donated",
            f"{name}: state leaf {p} is not donated — resident train "
            "state doubles",
            path=p,
        )
    if n_donated and text_donated == 0:
        report.add(
            "donation", "error", "donation-dropped",
            f"{name}: donation requested for {n_donated} leaves but no "
            "marker survives in the lowered module — lowering dropped "
            "the donation",
        )
    return [report] + ([sched_report] if sched_report else [])


def _stage_program_findings(report: Report, arts, *, label: str = "") -> None:
    """The ``pipeline:stage_program`` invariants (ISSUE 14), over the
    runner's abstract per-stage artifacts:

    - **No cross-stage collectives.** A per-stage program may collect
      over its submesh's data/fsdp/model/seq axes (grad reductions, fsdp
      gathers, TP rings, ring attention) but NEVER over ``pipe`` —
      boundary traffic is the driver's explicit ``device_put`` transfers
      only. Any ``pipe``-axis collective means a stage program started
      reaching across the stage boundary (error
      ``cross-stage-collective``).
    - **Stage state donated.** The per-stage update program donates every
      params/opt-state leaf (and the EMA mirror when on) — the per-stage
      face of the train step's ``donate_argnums=(0,)``; a dropped
      donation doubles stage state residency (error
      ``stage-not-donated``).
    """
    from frl_distributed_ml_scaffold_tpu.analysis.donation import (
        args_info_donations,
        lowered_donations,
    )

    census_all = []
    for art in arts:
        j = art["stage"]
        for which in ("fwd_jaxpr", "fwd_bwd_jaxpr"):
            census = collective_census(art[which])
            census_all.extend(r.to_dict() for r in census)
            for r in census:
                if "pipe" in r.axes:
                    report.add(
                        "stage_program", "error", "cross-stage-collective",
                        f"{label}stage {j} {which.replace('_jaxpr', '')} "
                        f"program carries a {r.primitive} over the pipe "
                        f"axis {r.axes} — inter-stage traffic must be the "
                        "driver's explicit transfers, never a collective "
                        "inside a stage program",
                        stage=j, primitive=r.primitive, axes=list(r.axes),
                    )
        lowered = art["update_lowered"]
        pairs = args_info_donations(lowered)
        if pairs is None:
            dons = [d.donated for d in lowered_donations(lowered.as_text())]
            if not any(dons):
                report.add(
                    "donation", "error", "stage-not-donated",
                    f"{label}stage {j} update program carries no donation "
                    "marker — stage params/opt-state double per step",
                    stage=j,
                )
            continue
        # Every state-carrying update arg must be donated: params, opt
        # state, grads — and the EMA mirror when on (the runner records
        # which positions those are; only the clip-factor scalar is
        # legally un-donated).
        expected = tuple(
            f"[0][{i}]" for i in art.get("update_donate_expected", (0, 1))
        )
        undonated = [
            p for p, d in pairs if p.startswith(expected) and not d
        ]
        for p in undonated:
            report.add(
                "donation", "error", "stage-not-donated",
                f"{label}stage {j} update program does not donate state "
                f"leaf {p} — stage params/opt-state double per step",
                stage=j, path=p,
            )
    report.meta["collective_census"] = census_all
    report.meta["stages"] = len(arts)
    if report.ok:
        report.add(
            "stage_program", "info", "summary",
            f"{label}{len(arts)} per-stage programs are free of "
            "cross-stage collectives and donate their stage state",
        )


def _lint_mpmd_reports(name: str, trainer) -> list[Report]:
    """Recipe + ``pipeline:stage_program`` family reports for an MPMD
    pipeline recipe — one artifact build, two views (the schedule:
    family pattern)."""
    from frl_distributed_ml_scaffold_tpu.parallel.mpmd_pipeline import (
        bubble_fraction,
        peak_live_activations,
    )

    runner = trainer._mpmd
    arts = runner.lint_artifacts()
    report = Report(program=f"recipe:{name}")
    report.meta["pipeline"] = {
        "impl": "mpmd",
        "stages": runner.num_stages,
        "microbatches": runner.total_micro,
        "bubble_fraction": bubble_fraction(
            "1f1b", runner.num_stages, runner.total_micro
        ),
        "peak_live_activations": peak_live_activations(
            "1f1b", runner.num_stages, runner.total_micro
        ),
    }
    _stage_program_findings(report, arts, label=f"{name}: ")
    # The stage_program family rides the SAME pass output — no second
    # census/donation walk over identical artifacts (the schedule:
    # family pattern).
    stage_report = Report(program="pipeline:stage_program")
    stage_report.meta["recipe"] = name
    stage_report.meta["pipeline"] = report.meta["pipeline"]
    stage_report.meta["collective_census"] = report.meta[
        "collective_census"
    ]
    stage_report.meta["stages"] = report.meta["stages"]
    stage_report.extend(report.findings)
    return [report, stage_report]


def lint_stage_programs(
    name: str = "gpt2_pipeline_mpmd", *, workdir: str = "/tmp/graft_lint"
) -> Report:
    """The ``pipeline:stage_program`` program family (ISSUE 14) on its
    own: per-stage programs of the MPMD pipeline recipe pinned free of
    cross-stage collectives, stage params/opt-state donation audited.
    Shares the recipe build with ``_lint_mpmd_reports``; mutation-gated
    in tests/test_graft_lint.py."""
    trainer = _build_trainer(name, workdir)
    if getattr(trainer, "_mpmd", None) is None:
        report = Report(program="pipeline:stage_program")
        report.add(
            "stage_program", "error", "not-mpmd",
            f"{name}: recipe does not run the MPMD pipeline backend — "
            "the stage_program family needs pipeline_impl='mpmd'",
        )
        return report
    return _lint_mpmd_reports(name, trainer)[1]


def lint_train_step(
    name: str,
    *,
    workdir: str = "/tmp/graft_lint",
    budget_bytes: int | None = None,
) -> Report:
    """Lint one registered recipe's train step; returns its Report."""
    return _lint_recipe_reports(
        name, workdir=workdir, budget_bytes=budget_bytes
    )[0]


def lint_schedule_program(
    name: str, *, workdir: str = "/tmp/graft_lint"
) -> Report:
    """The ``schedule:`` program family (ISSUE 13): one report per
    overlap recipe whose PROGRAM IS its declared schedule — the recipe's
    train step checked against the expectations derived from its
    ``OverlapSchedule`` declaration alone (analysis/schedule.py), with
    the declaration in ``meta`` so ``--save-census``/``--against`` diffs
    are keyed per schedule, not per recipe. Shares one trainer build +
    trace with the per-recipe report (``_lint_recipe_reports``); a
    recipe with no declared schedule reports ``no-schedule``."""
    reports = _lint_recipe_reports(name, workdir=workdir)
    for r in reports:
        if r.program == f"schedule:{name}":
            return r
    report = Report(program=f"schedule:{name}")
    report.add(
        "schedule", "error", "no-schedule",
        f"{name}: recipe declares no overlap schedule — the "
        "schedule: program family only applies to overlap recipes",
    )
    return report


def build_decode_step_program(
    *, seq_len: int = 96, bucket: int = 16, num_slots: int = 2,
    kv_cache_quant: str = "none",
):
    """The tiny-GPT serving decode step as an ABSTRACT program:
    ``(model, params, cache, tok, jaxpr)``, all shapes eval_shape'd —
    nothing runs. Shared by ``lint_decode_step`` and the perf ledger
    (tools/perf_ledger.py), so the linted program and the one the ledger
    censuses are the same artifact by construction."""
    import jax
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.config.schema import (
        GPTConfig,
        PrecisionConfig,
    )
    from frl_distributed_ml_scaffold_tpu.models.generation import (
        _decode_step,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    model = GPT(
        GPTConfig(
            vocab_size=64, num_layers=2, num_heads=2, hidden_dim=32,
            seq_len=seq_len, dropout=0.0, kv_cache_quant=kv_cache_quant,
        ),
        get_policy(PrecisionConfig(policy="fp32")),
    )
    m = model.clone(cache_len=bucket)
    tok = jax.ShapeDtypeStruct((num_slots, 1), jnp.int32)
    params = jax.eval_shape(
        lambda: model.init(
            {"params": jax.random.key(0)},
            jnp.zeros((num_slots, 4), jnp.int32),
            train=False,
        )["params"]
    )
    _, cache_vars = jax.eval_shape(
        lambda p, t: m.apply(
            {"params": p}, t, decode=True, mutable=["cache"]
        ),
        params, tok,
    )
    cache = cache_vars["cache"]

    jaxpr = jax.make_jaxpr(
        lambda p, c, t: _decode_step(m, p, c, t[:, 0])
    )(params, cache, tok)
    return model, params, cache, tok, jaxpr


def lint_decode_step(
    *, seq_len: int = 96, bucket: int = 16, num_slots: int = 2,
    kv_cache_quant: str = "none",
) -> Report:
    """Lint the serving decode path (tiny GPT, bucketed cache): PR 4's
    no-full-seq_len pin as a materialization-budget finding, plus the
    engine decode/graft donation audit.

    With ``kv_cache_quant`` set, the program is the QUANTIZED decode step
    and gains the ISSUE-6 pin: no wide-float intermediate carrying the
    cache geometry ``(bucket, H, hd)`` — a step that dequantizes the
    whole cache (instead of per chunk) is an error
    (``analysis.materialization.wide_intermediates_with_dims``)."""
    import jax
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.serving.engine import ServingEngine

    quant = kv_cache_quant != "none"
    report = Report(
        program="serving:decode_step_int8kv" if quant
        else "serving:decode_step"
    )
    model, params, cache, tok, jaxpr = build_decode_step_program(
        seq_len=seq_len, bucket=bucket, num_slots=num_slots,
        kv_cache_quant=kv_cache_quant,
    )

    census = collective_census(jaxpr)
    report.meta["collective_census"] = [r.to_dict() for r in census]
    report.extend(
        materialization_findings(
            jaxpr, forbidden_dim=seq_len, label="decode_step: "
        )
    )
    if quant:
        from frl_distributed_ml_scaffold_tpu.analysis.materialization import (
            wide_intermediates_with_dims,
        )

        h = model.config.num_heads
        hd = model.config.hidden_dim // h
        for i in wide_intermediates_with_dims(jaxpr, (bucket, h, hd)):
            report.add(
                "materialization", "error", "dequantized-cache",
                f"quantized decode step materializes a wide-float cache-"
                f"geometry array {i.dtype}{list(i.shape)} ({i.bytes} "
                f"bytes, {i.primitive}) — the whole cache was "
                "dequantized instead of per split-KV chunk",
                intermediate=i.to_dict(), geometry=[bucket, h, hd],
            )

    # Engine decode/graft donation: the KV cache is the serving-side
    # optimizer state — it must be donated or every decode step holds
    # two caches live.
    from frl_distributed_ml_scaffold_tpu.analysis.donation import (
        lowered_donations,
    )

    from frl_distributed_ml_scaffold_tpu.analysis.donation import (
        args_info_donations,
    )

    eng = ServingEngine(model, params, num_slots=num_slots, temperature=0.0)
    rng = jax.eval_shape(lambda: jax.random.key(0))
    flat_tok = jax.ShapeDtypeStruct((num_slots,), jnp.int32)
    dec_lowered = eng._decode_fn(bucket).lower(params, cache, flat_tok, rng)
    n_cache = len(jax.tree.leaves(cache))
    pairs = args_info_donations(dec_lowered)
    if pairs is None:
        # Old jax without args_info: count-level fallback only.
        dons = [d.donated for d in lowered_donations(dec_lowered.as_text())]
        if sum(dons) < n_cache:
            report.add(
                "donation", "error", "cache-not-donated",
                f"serving decode step donates {sum(dons)} args but the "
                f"cache alone has {n_cache} leaves — the engine holds two "
                "caches live per step",
                donated=sum(dons), cache_leaves=n_cache,
            )
        return report
    # Per-path: every CACHE leaf specifically must be donated (a refactor
    # donating params instead would pass a count-only gate). args_info
    # paths root at (args, kwargs): cache is positional arg 1 → "[0][1]".
    undonated_cache = [
        p for p, d in pairs if p.startswith("[0][1]") and not d
    ]
    for p in undonated_cache:
        report.add(
            "donation", "error", "cache-not-donated",
            f"serving decode step does not donate cache leaf {p} — the "
            "engine holds two caches live per step",
            path=p,
        )
    if not undonated_cache:
        report.add(
            "donation", "info", "summary",
            f"decode step donates all {n_cache} cache leaves "
            f"({sum(1 for _, d in pairs if d)}/{len(pairs)} args donated)",
        )
    return report


def build_paged_decode_step_program(
    *, seq_len: int = 96, block_size: int = 16, pool_blocks: int = 9,
    num_slots: int = 2, kv_cache_quant: str = "none",
):
    """The tiny-GPT PAGED serving decode step as an ABSTRACT program
    (ISSUE 10): ``(model, params, cache, tok, jaxpr)``, all shapes
    eval_shape'd — nothing runs. The cache is the block POOL (per-layer
    K/V block pools + block tables + index bookkeeping), so the program
    is the block-table decode shape the paged engine compiles ONCE.
    Shared by ``lint_paged_decode_step`` and the perf ledger, like its
    bucketed sibling ``build_decode_step_program``."""
    import jax
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.config.schema import (
        GPTConfig,
        PrecisionConfig,
    )
    from frl_distributed_ml_scaffold_tpu.models.generation import (
        _decode_step,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    model = GPT(
        GPTConfig(
            vocab_size=64, num_layers=2, num_heads=2, hidden_dim=32,
            seq_len=seq_len, dropout=0.0, kv_cache_quant=kv_cache_quant,
        ),
        get_policy(PrecisionConfig(policy="fp32")),
    )
    m = model.clone(kv_block_size=block_size, kv_pool_blocks=pool_blocks)
    tok = jax.ShapeDtypeStruct((num_slots, 1), jnp.int32)
    params = jax.eval_shape(
        lambda: model.init(
            {"params": jax.random.key(0)},
            jnp.zeros((num_slots, 4), jnp.int32),
            train=False,
        )["params"]
    )
    _, cache_vars = jax.eval_shape(
        lambda p, t: m.apply(
            {"params": p}, t, decode=True, mutable=["cache"]
        ),
        params, tok,
    )
    cache = cache_vars["cache"]

    jaxpr = jax.make_jaxpr(
        lambda p, c, t: _decode_step(m, p, c, t[:, 0])
    )(params, cache, tok)
    return model, params, cache, tok, jaxpr


def build_verify_step_program(
    *, seq_len: int = 96, block_size: int = 16, pool_blocks: int = 9,
    num_slots: int = 2, speculate_k: int = 2, kv_cache_quant: str = "none",
):
    """The tiny-GPT speculative VERIFY step as an ABSTRACT program
    (ISSUE 11): ``(model, params, cache, tile, jaxpr)``, all shapes
    eval_shape'd — nothing runs. The tile is the fixed ``[B, k+1]``
    token block the paged engine compiles ONCE (no per-k ladder); the
    cache is the same block pool as the paged decode step — the verify
    program reads/writes it through the identical table indirection, so
    the same no-cache-clone/no-logical-view pins apply. Shared by
    ``lint_verify_step`` and the perf ledger, like its siblings."""
    import jax
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.config.schema import (
        GPTConfig,
        PrecisionConfig,
    )
    from frl_distributed_ml_scaffold_tpu.models.generation import (
        _verify_step,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    model = GPT(
        GPTConfig(
            vocab_size=64, num_layers=2, num_heads=2, hidden_dim=32,
            seq_len=seq_len, dropout=0.0, kv_cache_quant=kv_cache_quant,
        ),
        get_policy(PrecisionConfig(policy="fp32")),
    )
    m = model.clone(kv_block_size=block_size, kv_pool_blocks=pool_blocks)
    tok = jax.ShapeDtypeStruct((num_slots, 1), jnp.int32)
    tile = jax.ShapeDtypeStruct((num_slots, speculate_k + 1), jnp.int32)
    params = jax.eval_shape(
        lambda: model.init(
            {"params": jax.random.key(0)},
            jnp.zeros((num_slots, 4), jnp.int32),
            train=False,
        )["params"]
    )
    _, cache_vars = jax.eval_shape(
        lambda p, t: m.apply(
            {"params": p}, t, decode=True, mutable=["cache"]
        ),
        params, tok,
    )
    cache = cache_vars["cache"]

    jaxpr = jax.make_jaxpr(
        lambda p, c, t: _verify_step(m, p, c, t)
    )(params, cache, tile)
    return model, params, cache, tile, jaxpr


def lint_verify_step(
    *, seq_len: int = 96, block_size: int = 16, pool_blocks: int = 9,
    num_slots: int = 2, speculate_k: int = 2, kv_cache_quant: str = "none",
) -> Report:
    """Lint the speculative VERIFY step (ISSUE 11) — the paged decode
    pins re-armed on the k+1-position tile:

    - no full-``seq_len`` intermediate: the verify tile must score
      against the pool through the table indirection, never a gathered
      logical view (k+1 queries make the gather temptation bigger, not
      smaller);
    - materialization budget == the largest pool leaf: the step's
      biggest legal array is still the donated in-place pool update —
      a per-k cache clone or a widened score materialization trips it;
    - donation audit on the engine's ONE compiled verify program
      (``ServingEngine._verify_fn``): every cache leaf donated, or each
      verify holds two pools live.

    Mutation-gated in tests/test_graft_lint.py alongside the paged
    decode gates."""
    import jax
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.serving.engine import ServingEngine

    quant = kv_cache_quant != "none"
    report = Report(
        program="serving:verify_step_paged_int8kv" if quant
        else "serving:verify_step_paged"
    )
    model, params, cache, tile, jaxpr = build_verify_step_program(
        seq_len=seq_len, block_size=block_size, pool_blocks=pool_blocks,
        num_slots=num_slots, speculate_k=speculate_k,
        kv_cache_quant=kv_cache_quant,
    )

    census = collective_census(jaxpr)
    report.meta["collective_census"] = [r.to_dict() for r in census]
    report.meta["verify_positions"] = speculate_k + 1
    report.extend(
        materialization_findings(
            jaxpr, forbidden_dim=seq_len, label="verify_step: "
        )
    )
    budget = _max_pool_leaf_bytes(cache)
    report.meta["pool_leaf_bytes"] = budget
    from frl_distributed_ml_scaffold_tpu.analysis.materialization import (
        oversized_intermediates,
    )

    for i in oversized_intermediates(jaxpr, budget):
        report.add(
            "materialization", "error", "cache-clone",
            f"verify step materializes {i.dtype}{list(i.shape)} "
            f"({i.bytes} bytes > the {budget}-byte pool leaf, "
            f"{i.primitive}) — the k+1 tile must ride the table "
            "indirection, never clone or widen the pool",
            intermediate=i.to_dict(), budget_bytes=budget,
        )

    # Engine donation audit on the ONE compiled verify program.
    from frl_distributed_ml_scaffold_tpu.analysis.donation import (
        args_info_donations,
        lowered_donations,
    )

    eng = ServingEngine(
        model, params, num_slots=num_slots, temperature=0.0,
        kv_block_size=block_size, kv_pool_blocks=pool_blocks,
        speculate="ngram", speculate_k=speculate_k,
    )
    ver_lowered = eng._verify_fn().lower(params, cache, tile)
    n_cache = len(jax.tree.leaves(cache))
    pairs = args_info_donations(ver_lowered)
    if pairs is None:
        dons = [d.donated for d in lowered_donations(ver_lowered.as_text())]
        if sum(dons) < n_cache:
            report.add(
                "donation", "error", "cache-not-donated",
                f"verify step donates {sum(dons)} args but the pool "
                f"cache has {n_cache} leaves — two POOLS live per "
                "verify",
                donated=sum(dons), cache_leaves=n_cache,
            )
        return report
    undonated_cache = [
        p for p, d in pairs if p.startswith("[0][1]") and not d
    ]
    for p in undonated_cache:
        report.add(
            "donation", "error", "cache-not-donated",
            f"verify step does not donate cache leaf {p} — the engine "
            "holds two POOLS live per verify",
            path=p,
        )
    if not undonated_cache:
        report.add(
            "donation", "info", "summary",
            f"verify step donates all {n_cache} cache leaves "
            f"({sum(1 for _, d in pairs if d)}/{len(pairs)} args donated)",
        )
    return report


def build_handoff_program(
    *, seq_len: int = 96, block_size: int = 16, pool_blocks: int = 9,
    num_slots: int = 2, prompt_tokens: int = 40, m_shared: int = 0,
    kv_cache_quant: str = "none",
):
    """The prefill→decode HANDOFF SPLICE as an ABSTRACT program (ISSUE
    12): ``(model, pool_cache, slot_cache, blk_ids, jaxpr)``, all shapes
    eval_shape'd — nothing runs. The jaxpr is
    ``generation.splice_pool_blocks`` — the EXACT function both the
    colocated paged graft and the disaggregated handoff jit
    (``ServingEngine._paged_graft_fn``), so the linted artifact and the
    served one cannot drift. The slot cache is the contiguous prefill
    output at the prompt's cache bucket; ``blk_ids`` are the private
    blocks that change owner (``m_shared`` leading blocks stay put —
    the shared-prefix case). Shared with the perf ledger's
    ``serving:handoff`` row, like its decode/verify siblings."""
    import jax
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.config.schema import (
        GPTConfig,
        PrecisionConfig,
    )
    from frl_distributed_ml_scaffold_tpu.models.generation import (
        blocks_for_tokens,
        next_cache_bucket,
        splice_pool_blocks,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    model = GPT(
        GPTConfig(
            vocab_size=64, num_layers=2, num_heads=2, hidden_dim=32,
            seq_len=seq_len, dropout=0.0, kv_cache_quant=kv_cache_quant,
        ),
        get_policy(PrecisionConfig(policy="fp32")),
    )
    tok = jax.ShapeDtypeStruct((num_slots, 1), jnp.int32)
    params = jax.eval_shape(
        lambda: model.init(
            {"params": jax.random.key(0)},
            jnp.zeros((num_slots, 4), jnp.int32),
            train=False,
        )["params"]
    )
    mp = model.clone(kv_block_size=block_size, kv_pool_blocks=pool_blocks)
    _, pool_vars = jax.eval_shape(
        lambda p, t: mp.apply(
            {"params": p}, t, decode=True, mutable=["cache"]
        ),
        params, tok,
    )
    pool_cache = pool_vars["cache"]
    s_c = next_cache_bucket(seq_len, prompt_tokens, floor=block_size)
    mc = model.clone(cache_len=s_c)
    slot_tok = jax.ShapeDtypeStruct((1, 1), jnp.int32)
    _, slot_vars = jax.eval_shape(
        lambda p, t: mc.apply(
            {"params": p}, t, decode=True, mutable=["cache"]
        ),
        params, slot_tok,
    )
    slot_cache = slot_vars["cache"]
    n_priv = blocks_for_tokens(prompt_tokens, block_size) - m_shared
    blk_ids = jax.ShapeDtypeStruct((n_priv,), jnp.int32)
    m0 = jax.ShapeDtypeStruct((), jnp.int32)
    slot = jax.ShapeDtypeStruct((), jnp.int32)

    import functools

    jaxpr = jax.make_jaxpr(
        functools.partial(splice_pool_blocks, block_size=block_size)
    )(pool_cache, slot_cache, blk_ids, m0, slot)
    return model, pool_cache, slot_cache, blk_ids, jaxpr


def lint_handoff(
    *, seq_len: int = 96, block_size: int = 16, pool_blocks: int = 9,
    num_slots: int = 2, prompt_tokens: int = 40,
) -> Report:
    """Lint the prefill→decode HANDOFF splice (ISSUE 12) — the mutation
    gate behind the disaggregated engine's zero-logical-cache-copy
    claim, three teeth:

    - ZERO collectives: the splice is a scatter of owned blocks plus a
      host-side table-row write — any collective in its jaxpr means the
      handoff started resharding (the compiled-HLO reshard-free pin
      lives in tests/test_serving.py under a live model mesh);
    - no full-``seq_len`` intermediate and a materialization budget of
      ONE pool leaf (the donated in-place update): a gather-based
      handoff — materialize the logical cache view, rewrite the pool —
      has to exceed the budget and trips it;
    - donation audit: the engine's splice program donates the pool, or
      every handoff holds two pools live.

    Mutation-gated in tests/test_graft_lint.py (a gather-based handoff
    mutant must trip)."""
    import jax
    import jax.numpy as jnp

    report = Report(program="serving:handoff")
    model, pool_cache, slot_cache, blk_ids, jaxpr = build_handoff_program(
        seq_len=seq_len, block_size=block_size, pool_blocks=pool_blocks,
        num_slots=num_slots, prompt_tokens=prompt_tokens,
    )

    census = collective_census(jaxpr)
    report.meta["collective_census"] = [r.to_dict() for r in census]
    table_blocks = seq_len // block_size
    report.meta["splice_table_bytes"] = table_blocks * 4
    for r in census:
        report.add(
            "reshard", "error", "handoff-collective",
            f"handoff splice carries a {r.primitive} of "
            f"{[list(s) for s in r.shapes]} — the splice moves only "
            "owned blocks; any collective means the handoff is "
            "resharding the cache",
            primitive=r.primitive, shapes=[list(s) for s in r.shapes],
        )
    report.extend(
        materialization_findings(
            jaxpr, forbidden_dim=seq_len, label="handoff: "
        )
    )
    budget = _max_pool_leaf_bytes(pool_cache)
    report.meta["pool_leaf_bytes"] = budget
    from frl_distributed_ml_scaffold_tpu.analysis.materialization import (
        oversized_intermediates,
    )

    for i in oversized_intermediates(jaxpr, budget):
        report.add(
            "materialization", "error", "cache-copy",
            f"handoff splice materializes {i.dtype}{list(i.shape)} "
            f"({i.bytes} bytes > the {budget}-byte pool leaf, "
            f"{i.primitive}) — the handoff must move only the blocks "
            "that change owner (ownership is a table-row write), never "
            "a logical-cache copy",
            intermediate=i.to_dict(), budget_bytes=budget,
        )

    # Donation audit: jit the splice exactly as the engine does
    # (``_paged_graft_fn``: same function, same donate_argnums) and
    # lower it on the abstract trees — no engine state needed.
    from frl_distributed_ml_scaffold_tpu.analysis.donation import (
        args_info_donations,
        lowered_donations,
    )

    import functools

    from frl_distributed_ml_scaffold_tpu.models.generation import (
        splice_pool_blocks,
    )

    splice_jit = jax.jit(
        functools.partial(splice_pool_blocks, block_size=block_size),
        donate_argnums=(0,),
    )
    m0 = jax.ShapeDtypeStruct((), jnp.int32)
    slot = jax.ShapeDtypeStruct((), jnp.int32)
    lowered = splice_jit.lower(pool_cache, slot_cache, blk_ids, m0, slot)
    n_cache = len(jax.tree.leaves(pool_cache))
    pairs = args_info_donations(lowered)
    if pairs is None:
        dons = [d.donated for d in lowered_donations(lowered.as_text())]
        if sum(dons) < n_cache:
            report.add(
                "donation", "error", "cache-not-donated",
                f"handoff splice donates {sum(dons)} args but the pool "
                f"has {n_cache} leaves — two POOLS live per handoff",
                donated=sum(dons), cache_leaves=n_cache,
            )
        return report
    undonated = [p for p, d in pairs if p.startswith("[0][0]") and not d]
    for p in undonated:
        report.add(
            "donation", "error", "cache-not-donated",
            f"handoff splice does not donate pool leaf {p} — two POOLS "
            "live per handoff",
            path=p,
        )
    if not undonated:
        report.add(
            "donation", "info", "summary",
            f"handoff splice donates all {n_cache} pool leaves; splice "
            f"ownership cost is {table_blocks * 4} table bytes/slot",
        )
    return report


def _max_pool_leaf_bytes(cache) -> int:
    """The largest block-pool leaf in a paged cache tree — the paged
    decode step's legal materialization ceiling (its biggest intermediate
    is the donated in-place pool update, which is exactly pool-sized)."""
    import jax
    import numpy as np

    from frl_distributed_ml_scaffold_tpu.models.generation import (
        SLOT_LEAF_OF,
    )

    best = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        if getattr(path[-1], "key", None) in SLOT_LEAF_OF:
            best = max(
                best,
                int(np.prod(leaf.shape, dtype=np.int64))
                * np.dtype(leaf.dtype).itemsize,
            )
    return best


def lint_paged_decode_step(
    *, seq_len: int = 96, block_size: int = 16, pool_blocks: int = 9,
    num_slots: int = 2, kv_cache_quant: str = "none",
) -> Report:
    """Lint the PAGED serving decode step (ISSUE 10) — the
    ``assert_no_cache_clone`` discipline, as two teeth:

    - no full-``seq_len`` intermediate: gathering the logical cache view
      out of the pool (``pool[tables]`` reshaped contiguous) is exactly
      the full-context materialization paging exists to avoid;
    - materialization budget == the largest pool leaf: the step's
      biggest legal array is the donated in-place pool update, so any
      clone-per-grow regression (pad the pool, copy it wider) has to
      materialize MORE than one pool and trips the budget.

    Plus the engine donation audit: the paged decode program donates
    every cache leaf (pool included) — without it each step holds two
    POOLS live, a far bigger spike than the bucketed double-cache.
    Mutation-gated in tests/test_graft_lint.py (a clone-per-grow mutant
    and a gather-the-logical-cache mutant must both trip)."""
    import jax
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.serving.engine import ServingEngine

    quant = kv_cache_quant != "none"
    report = Report(
        program="serving:decode_step_paged_int8kv" if quant
        else "serving:decode_step_paged"
    )
    model, params, cache, tok, jaxpr = build_paged_decode_step_program(
        seq_len=seq_len, block_size=block_size, pool_blocks=pool_blocks,
        num_slots=num_slots, kv_cache_quant=kv_cache_quant,
    )

    census = collective_census(jaxpr)
    report.meta["collective_census"] = [r.to_dict() for r in census]
    report.extend(
        materialization_findings(
            jaxpr, forbidden_dim=seq_len, label="paged_decode_step: "
        )
    )
    budget = _max_pool_leaf_bytes(cache)
    report.meta["pool_leaf_bytes"] = budget
    from frl_distributed_ml_scaffold_tpu.analysis.materialization import (
        oversized_intermediates,
    )

    for i in oversized_intermediates(jaxpr, budget):
        report.add(
            "materialization", "error", "cache-clone",
            f"paged decode step materializes {i.dtype}{list(i.shape)} "
            f"({i.bytes} bytes > the {budget}-byte pool leaf, "
            f"{i.primitive}) — growth must append a block to a table, "
            "never clone/pad the pool",
            intermediate=i.to_dict(), budget_bytes=budget,
        )

    # Engine donation audit on the ONE paged decode program.
    from frl_distributed_ml_scaffold_tpu.analysis.donation import (
        args_info_donations,
        lowered_donations,
    )

    eng = ServingEngine(
        model, params, num_slots=num_slots, temperature=0.0,
        kv_block_size=block_size, kv_pool_blocks=pool_blocks,
    )
    rng = jax.eval_shape(lambda: jax.random.key(0))
    flat_tok = jax.ShapeDtypeStruct((num_slots,), jnp.int32)
    dec_lowered = eng._paged_decode_fn().lower(params, cache, flat_tok, rng)
    n_cache = len(jax.tree.leaves(cache))
    pairs = args_info_donations(dec_lowered)
    if pairs is None:
        dons = [d.donated for d in lowered_donations(dec_lowered.as_text())]
        if sum(dons) < n_cache:
            report.add(
                "donation", "error", "cache-not-donated",
                f"paged decode step donates {sum(dons)} args but the "
                f"pool cache has {n_cache} leaves — two POOLS live per "
                "step",
                donated=sum(dons), cache_leaves=n_cache,
            )
        return report
    undonated_cache = [
        p for p, d in pairs if p.startswith("[0][1]") and not d
    ]
    for p in undonated_cache:
        report.add(
            "donation", "error", "cache-not-donated",
            f"paged decode step does not donate cache leaf {p} — the "
            "engine holds two POOLS live per step",
            path=p,
        )
    if not undonated_cache:
        report.add(
            "donation", "info", "summary",
            f"paged decode step donates all {n_cache} cache leaves "
            f"({sum(1 for _, d in pairs if d)}/{len(pairs)} args donated)",
        )
    return report


#: The redistribution executor's same-mesh program classes (ISSUE 15),
#: one per seam shape, on the 8-device sim. ``reshard:<src>to<dst>``
#: naming; ``even_src`` derives the source from the restore layout
#: (redistribute.restore_layout_spec — the elastic-restore seam's even
#: read), ``no_gather`` arms the zero-all_gather pin (a pure axis MOVE
#: must be ONE all_to_all; any all_gather means replicated staging).
RESHARD_PROGRAMS: dict[str, dict] = {
    "reshard:fsdp_to_tp": dict(
        mesh=dict(data=1, fsdp=4, model=2), shape=(64, 64),
        src=("fsdp", None), dst=(None, "model"),
    ),
    "reshard:tp_row_to_col": dict(
        mesh=dict(data=1, model=8), shape=(64, 64),
        src=("model", None), dst=(None, "model"), no_gather=True,
    ),
    "reshard:restore_even_to_fsdp": dict(
        mesh=dict(data=2, fsdp=4), shape=(64, 64),
        src=None, dst=("fsdp", None), even_src=True,
    ),
}


def build_reshard_program(name: str):
    """One redistribution executor program as an ABSTRACT artifact:
    ``(plan, jaxpr, lowered)`` — the jaxpr is the EXACT
    ``redistribute.executor.collective_callable`` the executor jits
    (same body, same shard_map specs), so the linted artifact and the
    executed one cannot drift; the lowered form carries the executor's
    donation (``donate_argnums=(0,)``). Shared with the perf ledger's
    ``redistribute:*`` rows."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        MeshConfig,
        build_mesh,
    )
    from frl_distributed_ml_scaffold_tpu.redistribute import (
        compile_leaf_plan,
        restore_layout_spec,
    )
    from frl_distributed_ml_scaffold_tpu.redistribute.executor import (
        collective_callable,
    )

    if name not in RESHARD_PROGRAMS:
        raise ValueError(
            f"unknown reshard program {name!r} "
            f"(have {sorted(RESHARD_PROGRAMS)})"
        )
    cfg = RESHARD_PROGRAMS[name]
    env = build_mesh(MeshConfig(**cfg["mesh"]))
    shape = cfg["shape"]
    dst_spec = P(*cfg["dst"])
    src_spec = (
        restore_layout_spec(shape, dst_spec, env.mesh)
        if cfg.get("even_src")
        else P(*cfg["src"])
    )
    plan = compile_leaf_plan(
        shape, jnp.float32,
        NamedSharding(env.mesh, src_spec),
        NamedSharding(env.mesh, dst_spec),
        path=name,
    )
    if plan.kind != "collective":
        raise RuntimeError(
            f"{name}: expected a collective plan, compiled {plan.kind!r} "
            "— the program classes graft-lint pins must stay on the "
            "collective executor"
        )
    fn = collective_callable(plan)
    jaxpr = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct(shape, jnp.float32))
    lowered = jax.jit(fn, donate_argnums=(0,)).lower(
        jax.ShapeDtypeStruct(
            shape, jnp.float32, sharding=plan.src_sharding
        )
    )
    return plan, jaxpr, lowered


def _shard_map_inner(jaxpr):
    """The shard_map eqn's body jaxpr (per-device LOCAL shapes — the
    altitude the scratch budget is written at; the outer eqn's outvar is
    the global array, which no single device materializes)."""
    for eqn in jaxpr.jaxpr.eqns:
        if "shard_map" in eqn.primitive.name:
            return eqn.params["jaxpr"]
    return jaxpr


def lint_reshard(name: str) -> Report:
    """Lint one redistribution executor program (ISSUE 15) — the
    zero-replicated-staging contract (arXiv 2112.01075), three teeth:

    - materialization budget == the plan's ``peak_scratch_bytes`` (one
      source shard + one destination shard per device), checked on the
      shard_map BODY: a naive gather-then-scatter materializes the full
      logical array on every device and trips it;
    - pure axis MOVES (``no_gather`` programs) additionally pin ZERO
      all_gather: the move is ONE all_to_all — any gather is staging;
    - donation audit: the executor's jitted program donates its source
      (or every reshard holds two copies live).

    Mutation-gated in tests/test_graft_lint.py via the executor's
    ``_NAIVE_GATHER_SCATTER`` reference switch."""
    from frl_distributed_ml_scaffold_tpu.analysis.donation import (
        lowered_donations,
    )
    from frl_distributed_ml_scaffold_tpu.analysis.materialization import (
        oversized_intermediates,
    )

    report = Report(program=name)
    plan, jaxpr, lowered = build_reshard_program(name)
    census = collective_census(jaxpr)
    report.meta["collective_census"] = [r.to_dict() for r in census]
    report.meta["plan"] = plan.to_dict()

    budget = plan.peak_scratch_bytes
    for i in oversized_intermediates(_shard_map_inner(jaxpr), budget):
        report.add(
            "materialization", "error", "replicated-staging",
            f"reshard program materializes {i.dtype}{list(i.shape)} "
            f"({i.bytes} bytes > the {budget}-byte scratch budget, "
            f"{i.primitive}) per device — a redistribution must move "
            "shard deltas, never stage the logical array",
            intermediate=i.to_dict(), budget_bytes=budget,
        )
    if RESHARD_PROGRAMS[name].get("no_gather"):
        for r in census:
            if "all_gather" in r.primitive:
                report.add(
                    "reshard", "error", "gather-on-move",
                    f"pure axis move carries an all_gather of "
                    f"{[list(s) for s in r.shapes]} — the move is ONE "
                    "all_to_all; a gather is replicated staging",
                    primitive=r.primitive,
                    shapes=[list(s) for s in r.shapes],
                )
    dons = lowered_donations(lowered)
    if sum(1 for d in dons if d.donated) < 1:
        report.add(
            "donation", "error", "source-not-donated",
            "reshard program does not donate its source array — every "
            "redistribution holds two copies live",
        )
    else:
        report.add(
            "donation", "info", "summary",
            f"source donated; plan moves {plan.bytes_moved} bytes "
            f"(lower bound {plan.bytes_lower_bound}) at peak scratch "
            f"{plan.peak_scratch_bytes}",
        )
    return report


def lint_reshard_programs() -> list[Report]:
    """All registered ``reshard:*`` executor program classes."""
    return [lint_reshard(name) for name in sorted(RESHARD_PROGRAMS)]


def build_tiny_gpt():
    """THE shrink-shape GPT twin for the redistribute seam artifacts —
    one definition shared by ``build_train_to_serve_plan`` (perf ledger
    + CLI train→serve seam) and ``tools/reshard_plan.py``'s restore /
    respread seams, so editing the twin cannot desynchronize the gated
    ledger row from the operator dry-runs. Returns ``(model,
    abstract_params)``; nothing runs."""
    import jax
    import jax.numpy as jnp

    from frl_distributed_ml_scaffold_tpu.config.schema import (
        GPTConfig,
        PrecisionConfig,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import GPT
    from frl_distributed_ml_scaffold_tpu.precision import get_policy

    model = GPT(
        GPTConfig(
            vocab_size=128, num_layers=2, num_heads=4, hidden_dim=64,
            seq_len=32, dropout=0.0,
        ),
        get_policy(PrecisionConfig(policy="fp32")),
    )
    params = jax.eval_shape(
        lambda: model.init(
            {"params": jax.random.key(0)},
            jnp.zeros((2, 8), jnp.int32), train=False,
        )["params"]
    )
    return model, params


def build_train_to_serve_plan():
    """The tiny-GPT train→serve handoff as an ABSTRACT tree plan: params
    shaped/sharded the way the fsdp×model trainer would hold them
    (fsdp=4 × model=2 over the 8-device sim), re-planned onto a 2-device
    serving TP mesh — nothing runs. ONE twin shared by the perf-ledger
    ``redistribute:train_to_serve`` row and the ``reshard_plan.py``
    CLI, so the gated numbers and the operator's dry-run cannot
    drift."""
    import jax

    from frl_distributed_ml_scaffold_tpu import redistribute
    from frl_distributed_ml_scaffold_tpu.config.schema import ParallelConfig
    from frl_distributed_ml_scaffold_tpu.dist.mesh import (
        MeshConfig,
        build_mesh,
    )
    from frl_distributed_ml_scaffold_tpu.models.gpt import gpt_tp_rules
    from frl_distributed_ml_scaffold_tpu.parallel.partition import (
        param_specs,
        shardings_from_specs,
    )

    _model, params = build_tiny_gpt()
    train_env = build_mesh(MeshConfig(data=1, fsdp=4, model=2))
    p_specs = param_specs(
        params,
        ParallelConfig(param_sharding="fsdp", fsdp_min_size=16),
        train_env.mesh,
        gpt_tp_rules(),
    )
    src_sh = shardings_from_specs(p_specs, train_env.mesh)
    params = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        params, src_sh,
    )
    serve_env = build_mesh(
        MeshConfig(data=1, model=2), devices=jax.devices()[:2]
    )
    plan = redistribute.train_to_serve_plan(
        params, serve_env, gpt_tp_rules()
    )
    return plan, train_env, serve_env


def lint_hygiene(paths: Iterable[str] | None = None) -> Report:
    """AST hygiene lint over the repo's traced modules."""
    import glob
    import os

    from frl_distributed_ml_scaffold_tpu.analysis.hygiene import lint_file

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if paths is None:
        paths = (
            sorted(glob.glob(os.path.join(pkg, "ops", "*.py")))
            + sorted(glob.glob(os.path.join(pkg, "parallel", "*.py")))
            + sorted(glob.glob(os.path.join(pkg, "models", "*.py")))
            + [os.path.join(pkg, "trainer", "train_step.py")]
        )
    report = Report(program="hygiene:traced-modules")
    n = 0
    for p in paths:
        n += 1
        report.extend(lint_file(p))
    report.meta["files"] = n
    return report


def lint_robustness(paths: Iterable[str] | None = None) -> Report:
    """Failure-semantics lint (ISSUE 9) over the WHOLE package — host
    orchestration included, because that is exactly where exceptions get
    swallowed and retry loops spin (the traced-module file list the
    hygiene pass uses would miss the engine, the supervisor, and the
    checkpointer)."""
    import glob
    import os

    from frl_distributed_ml_scaffold_tpu.analysis.hygiene import (
        lint_robustness_file,
    )

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if paths is None:
        paths = sorted(
            p
            for p in glob.glob(os.path.join(pkg, "**", "*.py"), recursive=True)
            if "__pycache__" not in p
        )
    report = Report(program="robustness:package")
    n = 0
    for p in paths:
        n += 1
        report.extend(lint_robustness_file(p))
    report.meta["files"] = n
    return report


def lint_concurrency(paths: Iterable[str] | None = None) -> Report:
    """Lock-discipline lint (ISSUE 20) over the WHOLE package: guarded-
    attribute inference (``unguarded-shared-write``), the interprocedural
    lock-acquisition-order graph (``lock-order-inversion``), and blocking
    calls under held locks (``blocking-under-lock``).  Whole-package like
    ``robustness:package`` — lock identities and the call graph resolve
    ACROSS modules (the FaultPlan -> MetricsRegistry nesting edge lives
    in two files)."""
    import glob
    import os

    from frl_distributed_ml_scaffold_tpu.analysis.concurrency import (
        lint_concurrency_paths,
    )

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if paths is None:
        paths = sorted(
            p
            for p in glob.glob(os.path.join(pkg, "**", "*.py"), recursive=True)
            if "__pycache__" not in p
        )
    paths = list(paths)
    report = Report(program="concurrency:package")
    report.extend(lint_concurrency_paths(paths))
    report.meta["files"] = len(paths)
    return report


def lint_all(
    *,
    recipes: Iterable[str] | None = None,
    serving: bool = True,
    reshard: bool = True,
    hygiene: bool = True,
    robustness: bool = True,
    concurrency: bool = True,
    workdir: str = "/tmp/graft_lint",
    budget_bytes: int | None = None,
    on_report: Callable[[Report], None] | None = None,
) -> list[Report]:
    """Lint every registered recipe (or the named subset) + extras."""
    from frl_distributed_ml_scaffold_tpu.config import list_configs

    names = list(recipes) if recipes is not None else list_configs()
    reports = []

    def emit(r: Report) -> None:
        reports.append(r)
        if on_report is not None:
            on_report(r)

    for name in names:
        try:
            # One build + trace per recipe: the recipe report plus, for
            # overlap recipes, the schedule: program family report
            # (ISSUE 13 — the declaration-first view of the same
            # findings).
            for r in _lint_recipe_reports(
                name, workdir=workdir, budget_bytes=budget_bytes
            ):
                emit(r)
        except Exception as e:  # surface as a finding, not a crash
            r = Report(program=f"recipe:{name}")
            r.add(
                "runner", "error", "lint-crashed",
                f"linting {name} raised {type(e).__name__}: {e}",
            )
            emit(r)
    if serving:
        emit(lint_decode_step())
        # The quantized-cache decode step is its own compiled-shape class
        # in production (model.kv_cache_quant) — lint it as its own
        # program, with the dequantized-cache pin armed.
        emit(lint_decode_step(kv_cache_quant="int8"))
        # The paged (block-table) decode step (ISSUE 10): the engine's
        # ONE compiled decode shape, with the no-cache-clone budget and
        # the no-logical-gather pin armed — plus its int8-pool flavor.
        emit(lint_paged_decode_step())
        emit(lint_paged_decode_step(kv_cache_quant="int8"))
        # The speculative verify step (ISSUE 11): the ONE [B, k+1]
        # compiled verify shape, same pins at tile width.
        emit(lint_verify_step())
        # The prefill→decode handoff splice (ISSUE 12): the block-table
        # re-own pinned clone-free — zero collectives, no logical-cache
        # copy, pool donated.
        emit(lint_handoff())
    if reshard:
        # The redistribution executor's program classes (ISSUE 15):
        # same-mesh reshards pinned staging-free + donated.
        for r in lint_reshard_programs():
            emit(r)
    if hygiene:
        emit(lint_hygiene())
    if robustness:
        emit(lint_robustness())
    if concurrency:
        emit(lint_concurrency())
    return reports
