"""Pass 5 — traced-code hygiene lint (AST level, no tracing needed).

Static Python-source checks for the bug classes that only bite under
``jit``:

- **host-sync** (error): ``.item()`` / ``jax.device_get`` / ``np.asarray``
  inside a function that manipulates tracers — each is a device→host
  round trip that serializes the step (SURVEY call stack (b): the host's
  only per-step job is dispatch).
- **python-rng** (error): stdlib ``random.*`` or ``np.random.*`` inside
  traced code — traced once, frozen forever; every step replays the
  values baked in at trace time.
- **axis-typo** (error): a string axis name passed to a collective or
  ``shard_map`` that is not one of the mesh's axes.  GSPMD errors on
  these eventually, but from deep inside a trace with an opaque message;
  the lint names the file/line.
- **host-sync-cast** (warning): ``float()``/``int()``/``bool()`` on an
  operand that provably references array code (jnp/lax/jax in its
  subtree) inside traced code — on a tracer each is a device→host sync.
  Shape-time casts (``float(np.prod(shape))``, config ints) stay quiet.
- **numpy-in-traced** (warning): other ``np.*`` calls inside a traced
  function.  Often legal shape-time arithmetic (``np.prod(shape)``), so
  an allowlist of shape-time helpers keeps this quiet; the rest is worth
  a look — on a tracer it either crashes or silently constant-folds.
- **metrics-in-traced** (error): a telemetry mutation (``.inc()`` /
  ``.observe()`` / a non-``.at[...]`` ``.set(v)`` / a
  ``registry.counter|gauge|histogram(...)`` lookup / a ``.span(...)``
  start / anything reached through a ``telemetry``/``tracing``/
  ``tracer`` attribute chain) inside traced code.  The telemetry
  layer's contract (ISSUE 7/8, the veScale single-controller argument)
  is HOST-SIDE ONLY: inside a trace a metric mutation or span
  start/stop either runs once at trace time and silently freezes, or
  drags a host clock read + sync into every step — both defeat the
  signal.  ``x.at[idx].set(v)`` is the jnp functional update and stays
  exempt (the receiver is a subscript).

"Traced function" is approximated as: a function whose body references
``jnp.`` / ``jax.lax`` / ``lax.`` — exactly the modules the repo's traced
code imports. Host-side orchestration (engine scheduling, data loading)
does not match and is not linted.
"""

from __future__ import annotations

import ast
from typing import Any, Iterable

from frl_distributed_ml_scaffold_tpu.analysis.findings import Finding

# Axes of the repo's meshes (config.schema.MeshConfig fields).
DEFAULT_KNOWN_AXES = frozenset(
    {"data", "fsdp", "model", "pipe", "seq", "expert"}
)

# lax collectives and the positional index their axis name rides at
# (psum(x, axis_name) → 1; axis_index(axis_name) → 0), besides axis_name=.
_COLLECTIVE_FNS = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "ppermute": 1,
    "all_gather": 1, "psum_scatter": 1, "all_to_all": 1, "pswapaxes": 1,
    "axis_index": 0, "axis_size": 0,
}

# np.* attrs that are legitimately shape-time inside traced code.
_NP_SHAPE_TIME = {
    "prod", "dtype", "float32", "float16", "bfloat16", "float64", "int32",
    "int64", "int8", "uint8", "bool_", "ndarray", "shape", "ceil", "floor",
    "log2", "sqrt", "pi", "inf", "finfo", "iinfo", "arange", "cumsum",
    "lcm", "gcd", "isscalar",
}

_HOST_SYNC_CALLS = {"device_get", "block_until_ready"}
_NP_HOST_SYNC = {"asarray", "array"}

# Telemetry mutators/constructors (telemetry/metrics.py). ``set`` is
# handled separately: only non-subscript receivers count (x.at[i].set is
# the jnp functional update, not a gauge).
_METRIC_MUTATORS = {"inc", "observe"}
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}

# Span/tracing API (telemetry/tracing.py): ``.span(...)`` starts a span;
# ``begin``/``emit``/``end`` are too generic to flag on their own, so
# they are caught via the receiver-chain rule instead (any dotted chain
# through ``telemetry``/``tracing``/``tracer`` — the repo's attribute
# names for the layer). Same contract as metrics: a span started inside
# traced code either freezes at trace time or drags a per-step host
# clock read + sync into the program.
_SPAN_MUTATORS = {"span"}
_TELEMETRY_CHAIN_NAMES = {"telemetry", "tracing", "tracer"}


# Array/stdlib modules whose methods legitimately collide with metric
# names (jnp.histogram, np.histogram, jax.numpy.histogram): never metric
# receivers. Chained-call receivers (reg.counter("x").inc()) dotted to ''
# stay flagged.
_ARRAY_MODULE_ROOTS = {"jnp", "np", "numpy", "jax", "lax", "scipy"}


def _is_metric_call(node: ast.Call, name: str) -> bool:
    """A telemetry mutation/lookup — metric mutators/factories AND span
    starts (see module docstring) — only meaningful inside traced code."""
    if _TELEMETRY_CHAIN_NAMES & set(name.split(".")):
        return True
    if not isinstance(node.func, ast.Attribute):
        return False
    recv = _dotted(node.func.value)
    if recv and recv.split(".")[0] in _ARRAY_MODULE_ROOTS:
        return False
    # node.func.attr, not the dotted-name leaf: chained calls like
    # reg.counter("x").inc() have a Call receiver, where _dotted gives ''.
    attr = node.func.attr
    if attr in _METRIC_MUTATORS or attr in _METRIC_FACTORIES:
        return True
    if attr in _SPAN_MUTATORS:
        return True
    if (
        attr == "set"
        and not isinstance(node.func.value, ast.Subscript)
        and len(node.args) == 1
    ):
        # gauge.set(v): exactly one arg, plain receiver. x.at[i].set(v)
        # has a Subscript receiver; threading's Event.set() has no args.
        return True
    return False


def _dotted(node: ast.AST) -> str:
    """'np.random.randint' for nested Attribute/Name chains, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_array_expr(node: ast.AST) -> bool:
    """Does the expression subtree reference jnp/lax/jax — i.e. is its
    value provably an array (tracer) rather than host shape math?"""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Attribute, ast.Name)):
            d = _dotted(sub)
            if d.startswith(("jnp.", "lax.", "jax.")):
                return True
    return False


def _is_traced_fn(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        d = _dotted(node) if isinstance(node, (ast.Attribute, ast.Name)) else ""
        if d.startswith(("jnp.", "lax.", "jax.lax", "jax.nn")):
            return True
    return False


def _axis_literals(call: ast.Call) -> list[str]:
    """String axis names passed to a collective-ish call."""
    out = []

    def strings(node):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.append(node.value)
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                strings(e)

    for kw in call.keywords:
        if kw.arg in ("axis_name", "axes"):
            strings(kw.value)
    name = _dotted(call.func)
    leaf = name.rsplit(".", 1)[-1]
    pos = _COLLECTIVE_FNS.get(leaf)
    if pos is not None and len(call.args) > pos:
        strings(call.args[pos])
    return out


def lint_source(
    source: str,
    filename: str = "<source>",
    *,
    known_axes: Iterable[str] = DEFAULT_KNOWN_AXES,
    extra_axes: Iterable[str] = (),
) -> list[Finding]:
    """Lint one module's (or function's) source text."""
    known = set(known_axes) | set(extra_axes)
    try:
        tree = ast.parse(source)
    except SyntaxError as e:  # pragma: no cover - repo sources parse
        return [
            Finding(
                "hygiene", "warning", "unparseable",
                f"{filename}: {e}", {"file": filename},
            )
        ]
    findings: list[Finding] = []

    def where(node: ast.AST) -> dict[str, Any]:
        return {"file": filename, "line": getattr(node, "lineno", 0)}

    # Walk top-level and nested function defs; lint only traced-looking ones.
    for fn in [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]:
        traced = _is_traced_fn(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            leaf = name.rsplit(".", 1)[-1]
            # Axis-name typos: checked in every function — the literal is
            # an axis name regardless of how host-y the caller looks.
            for ax in _axis_literals(node):
                if ax not in known:
                    findings.append(
                        Finding(
                            "hygiene", "error", "axis-typo",
                            f"{filename}:{node.lineno} function "
                            f"{fn.name!r} uses unknown mesh axis {ax!r} "
                            f"(known: {sorted(known)})",
                            {**where(node), "axis": ax, "function": fn.name},
                        )
                    )
            if not traced:
                continue
            if _is_metric_call(node, name):
                findings.append(
                    Finding(
                        "hygiene", "error", "metrics-in-traced",
                        f"{filename}:{node.lineno} function {fn.name!r} "
                        f"mutates a telemetry metric or span "
                        f"({name or leaf}()) inside traced code — "
                        "telemetry is host-side only (trace-time freeze "
                        "or a per-step host sync); record around the "
                        "jitted call instead",
                        {**where(node), "call": name or leaf,
                         "function": fn.name},
                    )
                )
                continue
            if name.startswith(("random.", "np.random.", "numpy.random.")):
                findings.append(
                    Finding(
                        "hygiene", "error", "python-rng",
                        f"{filename}:{node.lineno} function {fn.name!r} "
                        f"calls {name} inside traced code — the value is "
                        "baked in at trace time; use jax.random",
                        {**where(node), "call": name, "function": fn.name},
                    )
                )
            elif (
                leaf == "item"
                and isinstance(node.func, ast.Attribute)
                or leaf in _HOST_SYNC_CALLS
                and name.startswith("jax.")
            ):
                findings.append(
                    Finding(
                        "hygiene", "error", "host-sync",
                        f"{filename}:{node.lineno} function {fn.name!r} "
                        f"calls {name or leaf}() inside traced code — a "
                        "device→host sync per step",
                        {**where(node), "call": name or leaf,
                         "function": fn.name},
                    )
                )
            elif (
                name in ("float", "int", "bool")
                and node.args
                and _is_array_expr(node.args[0])
            ):
                # float(tracer)/int(tracer) forces a device→host sync
                # (ISSUE host-sync class). Flagged only when the operand
                # subtree provably references array code (jnp/lax/jax) —
                # float(np.prod(x.shape)) and float(static_config_arg)
                # are legal shape-time arithmetic and stay quiet.
                findings.append(
                    Finding(
                        "hygiene", "warning", "host-sync-cast",
                        f"{filename}:{node.lineno} function {fn.name!r} "
                        f"calls {name}() on a non-literal inside traced "
                        "code — on a tracer this is a per-step host sync "
                        "(use the array dtype ops instead)",
                        {**where(node), "call": name, "function": fn.name},
                    )
                )
            elif name.startswith(("np.", "numpy.")):
                attr = name.split(".", 1)[1]
                root = attr.split(".", 1)[0]
                if root in _NP_HOST_SYNC:
                    findings.append(
                        Finding(
                            "hygiene", "error", "host-sync",
                            f"{filename}:{node.lineno} function "
                            f"{fn.name!r} calls {name}() inside traced "
                            "code — materializes the tracer on host",
                            {**where(node), "call": name,
                             "function": fn.name},
                        )
                    )
                elif root not in _NP_SHAPE_TIME:
                    findings.append(
                        Finding(
                            "hygiene", "warning", "numpy-in-traced",
                            f"{filename}:{node.lineno} function "
                            f"{fn.name!r} calls {name}() inside traced "
                            "code — on a tracer this crashes or "
                            "constant-folds silently",
                            {**where(node), "call": name,
                             "function": fn.name},
                        )
                    )
    return findings


# --------------------------------------------------------------------------
# Robustness lint (ISSUE 9) — failure-semantics hygiene over PACKAGE code
# (not just traced modules): the bug classes that turn recoverable faults
# into silent corruption or livelock.
# --------------------------------------------------------------------------

#: Exception types whose pass-only swallow is an ERROR: catching
#: everything and doing NOTHING hides torn writes, poison requests, and
#: dead filesystems from every recovery path above it. Narrow types
#: (OSError on a best-effort unlink) stay legal.
_SWALLOW_WIDE = frozenset({"Exception", "BaseException"})

#: A retry loop is "bounded or backing off" if it calls any of these —
#: sleep/wait primitives or the unified policy's own surface
#: (faults/retry.py delay/delays/call). Deliberately NOT "join": too
#: common as str.join inside error formatting, which would exempt a
#: genuine busy-spin.
_BACKOFF_CALLS = frozenset({"sleep", "wait", "backoff", "delay", "delays", "call"})


def _handler_type_names(handler: ast.ExceptHandler) -> list[str]:
    t = handler.type
    if t is None:
        return ["<bare>"]
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for e in elts:
        if isinstance(e, ast.Name):
            out.append(e.id)
        elif isinstance(e, ast.Attribute):
            out.append(e.attr)
    return out


def _body_only_pass(body: list[ast.stmt]) -> bool:
    return all(
        isinstance(s, ast.Pass)
        or (
            isinstance(s, ast.Expr)
            and isinstance(s.value, ast.Constant)
            and s.value.value is ...
        )
        for s in body
    )


def lint_robustness_source(
    source: str, filename: str = "<source>"
) -> list[Finding]:
    """Failure-semantics lint over one module's source:

    - **swallowed-exception** (error): ``except:`` / ``except Exception:``
      / ``except BaseException:`` (alone or in a tuple) whose body is
      only ``pass``/``...`` — the fault disappears with no log, no
      counter, no typed completion. Handle it, log it, or narrow the
      type.
    - **unbounded-retry** (warning): a ``while True`` loop containing a
      ``try`` whose handler neither re-raises nor breaks, with no
      sleep/backoff/budget call anywhere in the loop — a dead dependency
      turns it into a busy-spin that also never escalates. Adopt
      ``faults/retry.py``.
    """
    try:
        tree = ast.parse(source)
    except SyntaxError as e:  # pragma: no cover - repo sources parse
        return [
            Finding(
                "robustness", "warning", "unparseable",
                f"{filename}: {e}", {"file": filename},
            )
        ]
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler):
            wide = _SWALLOW_WIDE & set(_handler_type_names(node))
            if wide or node.type is None:
                if _body_only_pass(node.body):
                    caught = (
                        "bare except" if node.type is None
                        else f"except {sorted(wide)[0]}"
                    )
                    findings.append(
                        Finding(
                            "robustness", "error", "swallowed-exception",
                            f"{filename}:{node.lineno} {caught}: pass — "
                            "the fault vanishes with no log, counter, or "
                            "typed resolution; handle it, log it, or "
                            "narrow the type",
                            {"file": filename, "line": node.lineno},
                        )
                    )
        elif isinstance(node, ast.While):
            test = node.test
            if not (isinstance(test, ast.Constant) and test.value is True):
                continue
            trys = [
                s for s in ast.walk(node) if isinstance(s, ast.Try)
            ]
            if not trys:
                continue
            calls = set()
            for c in ast.walk(node):
                if isinstance(c, ast.Call):
                    if isinstance(c.func, ast.Attribute):
                        calls.add(c.func.attr)
                    elif isinstance(c.func, ast.Name):
                        calls.add(c.func.id)
            if calls & _BACKOFF_CALLS:
                continue
            swallowing = any(
                not any(
                    isinstance(s, (ast.Raise, ast.Break, ast.Return))
                    for s in ast.walk(h)
                )
                for t in trys
                for h in t.handlers
            )
            if swallowing:
                findings.append(
                    Finding(
                        "robustness", "warning", "unbounded-retry",
                        f"{filename}:{node.lineno} while True retry loop "
                        "with no backoff/budget call and an exception "
                        "handler that never escalates — a dead dependency "
                        "becomes a busy-spin; adopt faults/retry.py",
                        {"file": filename, "line": node.lineno},
                    )
                )
    return findings


def lint_robustness_file(path: str) -> list[Finding]:
    with open(path) as fh:
        return lint_robustness_source(fh.read(), path)


def lint_file(
    path: str,
    *,
    known_axes: Iterable[str] = DEFAULT_KNOWN_AXES,
    extra_axes: Iterable[str] = (),
) -> list[Finding]:
    with open(path) as fh:
        return lint_source(
            fh.read(), path, known_axes=known_axes, extra_axes=extra_axes
        )


def lint_fn(fn: Any, **kw: Any) -> list[Finding]:
    """Lint one Python function object (via its source)."""
    import inspect
    import textwrap

    src = textwrap.dedent(inspect.getsource(fn))
    filename = f"{getattr(fn, '__module__', '?')}.{getattr(fn, '__qualname__', '?')}"
    return lint_source(src, filename, **kw)
