"""Pass 1 — collective census.

Every collective in a program, with axis names, element counts and
estimated bytes, at two levels:

- **jaxpr level** (hand-placed collectives: the explicit fsdp_overlap
  gathers/scatters, tp_overlap ppermute rings, pipeline collectives) —
  GSPMD-inserted collectives do NOT exist at this level; and
- **HLO level** (``lowered.as_text()`` / ``compiled.as_text()``) — where
  GSPMD's partitioner has already inserted its collectives, so the diff
  jaxpr-census vs HLO-census is exactly "what GSPMD added".

Census rows are diffable across two program versions (``census_diff``):
the promoted form of PR 3's "4 rings/block, zero all_gather" pin is "the
census of the step is unchanged".
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any

from frl_distributed_ml_scaffold_tpu.analysis.jaxpr_utils import (
    aval_bytes,
    close,
    iter_eqns,
)

# Exact jaxpr primitive names of the cross-device collectives.
COLLECTIVE_PRIMITIVES = (
    "all_gather",
    "reduce_scatter",
    "ppermute",
    "psum",
    "all_to_all",
    "pbroadcast",
    "pmax",
    "pmin",
)

# HLO op mnemonics (compiled text); -start suffixes are the async forms.
HLO_COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
    "collective-broadcast",
)


@dataclasses.dataclass(frozen=True)
class CollectiveRecord:
    """One collective equation occurrence."""

    primitive: str
    axes: tuple[str, ...]
    shapes: tuple[tuple[int, ...], ...]  # output shapes
    dtype: str
    bytes_per_call: int
    trip_count: int  # product of enclosing scan lengths
    path: tuple[str, ...]  # enclosing primitive names (scan, custom_vjp, ...)

    @property
    def total_bytes(self) -> int:
        return self.bytes_per_call * self.trip_count

    def key(self) -> tuple:
        """Identity for census diffing: where the eqn sits, what moves,
        and how often — trip_count included so a scan-length change (same
        eqn, 12x the wire bytes) still registers as drift."""
        return (
            self.primitive, self.axes, self.shapes, self.dtype, self.path,
            self.trip_count,
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "primitive": self.primitive,
            "axes": list(self.axes),
            "shapes": [list(s) for s in self.shapes],
            "dtype": self.dtype,
            "bytes_per_call": self.bytes_per_call,
            "trip_count": self.trip_count,
            "total_bytes": self.total_bytes,
            "path": list(self.path),
        }


def _eqn_axes(eqn: Any) -> tuple[str, ...]:
    """Axis names of a collective eqn (``axes`` on psum/pmax/pmin,
    ``axis_name`` on the rest), normalized to a string tuple."""
    for k in ("axes", "axis_name"):
        if k in eqn.params:
            v = eqn.params[k]
            if isinstance(v, (tuple, list)):
                return tuple(str(a) for a in v)
            return (str(v),)
    return ()


def collective_census(jaxpr: Any) -> list[CollectiveRecord]:
    """All collective eqns in the program (sub-jaxprs included)."""
    records = []
    for eqn, path, trips in iter_eqns(close(jaxpr)):
        name = str(eqn.primitive)
        if name not in COLLECTIVE_PRIMITIVES:
            continue
        shapes = tuple(
            tuple(getattr(v.aval, "shape", ())) for v in eqn.outvars
        )
        dtype = str(getattr(eqn.outvars[0].aval, "dtype", "?")) if eqn.outvars else "?"
        nbytes = sum(aval_bytes(v.aval) for v in eqn.outvars)
        records.append(
            CollectiveRecord(
                primitive=name,
                axes=_eqn_axes(eqn),
                shapes=shapes,
                dtype=dtype,
                bytes_per_call=nbytes,
                trip_count=trips,
                path=path,
            )
        )
    return records


def census_summary(records: list[CollectiveRecord]) -> dict[str, Any]:
    """Aggregate census: per primitive, counts and total bytes."""
    agg: dict[str, dict[str, int]] = {}
    for r in records:
        a = agg.setdefault(
            r.primitive, {"eqns": 0, "calls": 0, "total_bytes": 0}
        )
        a["eqns"] += 1
        a["calls"] += r.trip_count
        a["total_bytes"] += r.total_bytes
    return agg


def census_by_dtype(
    records: list[CollectiveRecord],
) -> dict[tuple[str, str], dict[str, int]]:
    """Aggregate census keyed ``(primitive, element dtype)`` — the view
    the low-precision fast path is pinned through: an int8 collective-
    matmul ring shows its wire bytes under ``("ppermute", "int8")`` with
    only scalar scales left under the wide-float dtypes, and a silent
    fall-back to bf16/fp32 payloads moves the bytes back where
    ``assert_collective_bytes_within`` (analysis/pins.py) and the
    graft-lint wide-ppermute check will refuse them."""
    agg: dict[tuple[str, str], dict[str, int]] = {}
    for r in records:
        a = agg.setdefault(
            (r.primitive, r.dtype), {"eqns": 0, "calls": 0, "total_bytes": 0}
        )
        a["eqns"] += 1
        a["calls"] += r.trip_count
        a["total_bytes"] += r.total_bytes
    return agg


def census_diff(
    old: list[CollectiveRecord], new: list[CollectiveRecord]
) -> dict[str, list[dict[str, Any]]]:
    """Diff two censuses by record identity; multiplicity-aware.

    Returns ``{"added": [...], "removed": [...]}`` where each entry is the
    record dict plus a ``count`` delta — the artifact to stare at when a
    refactor changes a step's communication.
    """

    def counted(records):
        acc: dict[tuple, list[CollectiveRecord]] = {}
        for r in records:
            acc.setdefault(r.key(), []).append(r)
        return acc

    o, n = counted(old), counted(new)
    added, removed = [], []
    for k in n.keys() - o.keys():
        added.append({**n[k][0].to_dict(), "count": len(n[k])})
    for k in o.keys() - n.keys():
        removed.append({**o[k][0].to_dict(), "count": len(o[k])})
    for k in o.keys() & n.keys():
        d = len(n[k]) - len(o[k])
        if d > 0:
            added.append({**n[k][0].to_dict(), "count": d})
        elif d < 0:
            removed.append({**o[k][0].to_dict(), "count": -d})
    return {"added": added, "removed": removed}


# --------------------------------------------------------------------- HLO

# Dtype tokens are letters possibly mixed with digits (f32, bf16, pred,
# f8e4m3fn) — a letters-then-digits pattern would miss pred entirely.
_HLO_SHAPE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}


@dataclasses.dataclass(frozen=True)
class HloCollective:
    """One collective op line in HLO/StableHLO text."""

    op: str  # e.g. "all-gather"
    shapes: tuple[tuple[int, ...], ...]  # result shapes on the line
    dtypes: tuple[str, ...]
    bytes_total: int
    line: str

    def to_dict(self) -> dict[str, Any]:
        return {
            "op": self.op,
            "shapes": [list(s) for s in self.shapes],
            "dtypes": list(self.dtypes),
            "bytes_total": self.bytes_total,
            "line": self.line[:200],
        }


def hlo_collective_census(text: str) -> list[HloCollective]:
    """Collective ops in compiled (or lowered) HLO text.

    Matches the op mnemonic as the instruction being assigned on each
    line (``%x = f32[...] all-gather(...)``; async ``-start`` forms are
    counted once, their ``-done`` halves skipped), and records every
    result shape on the left of the op name — that is the materialized
    result, i.e. the wire cost upper bound.
    """
    out = []
    for raw in text.splitlines():
        line = raw.strip()
        for op in HLO_COLLECTIVES:
            # "<shapes> op(" or "<shapes> op-start("; skip -done/-update.
            m = re.search(rf"=\s+(.*?)\s({op})(-start)?\(", line)
            if not m:
                continue
            lhs = m.group(1)
            shapes, dtypes, nbytes = [], [], 0
            for dt, dims in _HLO_SHAPE.findall(lhs):
                shape = tuple(int(x) for x in dims.split(",")) if dims else ()
                shapes.append(shape)
                dtypes.append(dt)
                n = 1
                for d in shape:
                    n *= d
                nbytes += n * _HLO_DTYPE_BYTES.get(dt, 4)
            out.append(
                HloCollective(
                    op=op,
                    shapes=tuple(shapes),
                    dtypes=tuple(dtypes),
                    bytes_total=nbytes,
                    line=line,
                )
            )
            break
    return out


def hlo_census_summary(records: list[HloCollective]) -> dict[str, Any]:
    agg: dict[str, dict[str, int]] = {}
    for r in records:
        a = agg.setdefault(r.op, {"ops": 0, "total_bytes": 0})
        a["ops"] += 1
        a["total_bytes"] += r.bytes_total
    return agg
