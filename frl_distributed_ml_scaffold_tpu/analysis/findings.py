"""Structured findings: the common currency of every graft-lint pass.

A pass inspects one program artifact (closed jaxpr, lowered StableHLO,
compiled HLO, or Python source) and emits ``Finding`` records; a
``Report`` aggregates them per linted program and serializes to the JSON
the CLI emits.  Severity semantics are fixed repo-wide:

- ``error``   — a pinned performance invariant is violated; the CLI exits
                non-zero (and ``analysis.pins`` raises AssertionError).
- ``warning`` — suspicious but not pinned (e.g. a numpy call inside a
                traced function that may be shape-time arithmetic).
- ``info``    — observability output (collective census rows, largest
                intermediates) used for diffing program versions.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Iterable

SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass
class Finding:
    """One analyzer observation, machine-readable and diffable."""

    pass_name: str  # "collective_census" | "reshard" | "materialization" | "donation" | "hygiene"
    severity: str   # "error" | "warning" | "info"
    code: str       # stable short slug, e.g. "exposed-all-gather"
    message: str    # human-readable one-liner
    context: dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity {self.severity!r} not in {SEVERITIES}"
            )

    def to_dict(self) -> dict[str, Any]:
        return {
            "pass": self.pass_name,
            "severity": self.severity,
            "code": self.code,
            "message": self.message,
            "context": _jsonable(self.context),
        }


def _jsonable(obj: Any) -> Any:
    """Best-effort conversion of context payloads (shape tuples, dtypes,
    numpy scalars) into JSON-serializable structures."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (str, bool, type(None))):
        return obj
    if isinstance(obj, (int, float)):
        return obj
    if hasattr(obj, "item"):  # numpy scalar
        return obj.item()
    return str(obj)


@dataclasses.dataclass
class Report:
    """All findings for one linted program (e.g. one recipe's train step)."""

    program: str
    findings: list[Finding] = dataclasses.field(default_factory=list)
    meta: dict[str, Any] = dataclasses.field(default_factory=dict)

    def add(
        self,
        pass_name: str,
        severity: str,
        code: str,
        message: str,
        **context: Any,
    ) -> Finding:
        f = Finding(pass_name, severity, code, message, context)
        self.findings.append(f)
        return f

    def extend(self, findings: Iterable[Finding]) -> None:
        self.findings.extend(findings)

    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == "warning"]

    @property
    def ok(self) -> bool:
        return not self.errors()

    def to_dict(self) -> dict[str, Any]:
        return {
            "program": self.program,
            "ok": self.ok,
            "counts": {
                s: sum(1 for f in self.findings if f.severity == s)
                for s in SEVERITIES
            },
            "meta": _jsonable(self.meta),
            "findings": [f.to_dict() for f in self.findings],
        }

    def to_json(self, **kw: Any) -> str:
        return json.dumps(self.to_dict(), **kw)

    def summary_lines(self, *, max_info: int = 0) -> list[str]:
        """Human-readable per-program summary for the CLI table."""
        counts = self.to_dict()["counts"]
        head = (
            f"{'FAIL' if not self.ok else 'ok  '} {self.program}: "
            f"{counts['error']} error(s), {counts['warning']} warning(s), "
            f"{counts['info']} info"
        )
        lines = [head]
        shown_info = 0
        for f in self.findings:
            if f.severity == "info":
                shown_info += 1
                if shown_info > max_info:
                    continue
            lines.append(f"    [{f.severity}] {f.pass_name}/{f.code}: {f.message}")
        return lines
