"""Optimizer + LR-schedule factory (part of SURVEY C3).

Thin optax composition: clip → optimizer → schedule. Kept as one factory so
the ZeRO layer (parallel/partition.py) can derive optimizer-state sharding
from ``jax.eval_shape(tx.init, params)`` for anything built here.
"""

from __future__ import annotations

import optax

from frl_distributed_ml_scaffold_tpu.config.schema import OptimizerConfig, TrainerConfig


def make_schedule(cfg: OptimizerConfig, total_steps: int) -> optax.Schedule:
    base = cfg.learning_rate
    decay_steps = max(total_steps - cfg.warmup_steps, 1)
    if cfg.schedule == "constant":
        sched = optax.constant_schedule(base)
    elif cfg.schedule == "cosine":
        sched = optax.cosine_decay_schedule(base, decay_steps)
    elif cfg.schedule == "linear":
        sched = optax.linear_schedule(base, 0.0, decay_steps)
    elif cfg.schedule == "wsd":
        # Warmup-stable-decay: hold the peak LR, then linear-decay over the
        # final ``wsd_decay_fraction`` of the run — the LM schedule that
        # decouples total-steps choice from the cosine's fixed horizon.
        decay = max(int(decay_steps * cfg.wsd_decay_fraction), 1)
        stable = max(decay_steps - decay, 0)
        sched = optax.join_schedules(
            [optax.constant_schedule(base),
             optax.linear_schedule(base, 0.0, decay)],
            [stable],
        )
    else:
        raise KeyError(f"unknown schedule {cfg.schedule!r}")
    if cfg.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, base, cfg.warmup_steps)
        return optax.join_schedules([warmup, sched], [cfg.warmup_steps])
    return sched


def _decoupled_decay(
    weight_decay: float, schedule: optax.Schedule
) -> optax.GradientTransformation:
    """AdamW-style decoupled weight decay: update -= lr(step) * wd * param.

    Runs AFTER the optimizer in the chain (whose output is already the
    final descent update including the -lr scaling), so the decay term is
    added directly in update space.
    """
    import jax
    import jax.numpy as jnp

    def init_fn(params):
        del params
        return optax.ScaleByScheduleState(count=jnp.zeros([], jnp.int32))

    def update_fn(updates, state, params):
        if params is None:
            raise ValueError("decoupled decay requires params")
        lr = schedule(state.count)
        updates = jax.tree.map(
            lambda u, p: u - lr * weight_decay * p, updates, params
        )
        return updates, optax.ScaleByScheduleState(
            count=optax.safe_int32_increment(state.count)
        )

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(
    cfg: OptimizerConfig, trainer_cfg: TrainerConfig
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """Build the optax chain; returns (tx, schedule) — schedule exposed for
    LR logging."""
    schedule = make_schedule(cfg, trainer_cfg.total_steps)
    parts = []
    if cfg.grad_clip_norm is not None:
        parts.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    # b2=None -> each optimizer's canonical default (schema contract).
    adam_b2 = 0.999 if cfg.b2 is None else cfg.b2
    if cfg.name == "fused_adamw":
        # Single-Pallas-pass AdamW (ops/fused_adamw.py, BACKLOG-5
        # experiment). Returned UNCHAINED: optax.chain would hide the
        # fused_apply fast path the train step dispatches on. grad clip is
        # a global-norm reduction across the whole tree — inherently a
        # separate pass — so the combination is refused rather than
        # silently de-fused.
        if cfg.grad_clip_norm is not None:
            raise ValueError(
                "optimizer.name=fused_adamw does not compose with "
                "grad_clip_norm (global-norm clipping defeats the "
                "single-pass fusion); use adamw"
            )
        from frl_distributed_ml_scaffold_tpu.ops.fused_adamw import (
            fused_adamw,
        )

        return (
            fused_adamw(
                schedule,
                b1=cfg.b1,
                b2=adam_b2,
                eps=cfg.eps,
                weight_decay=cfg.weight_decay,
            ),
            schedule,
        )
    if cfg.name == "adamw":
        parts.append(
            optax.adamw(
                schedule,
                b1=cfg.b1,
                b2=adam_b2,
                eps=cfg.eps,
                weight_decay=cfg.weight_decay,
            )
        )
    elif cfg.name == "adam":
        parts.append(optax.adam(schedule, b1=cfg.b1, b2=adam_b2, eps=cfg.eps))
    elif cfg.name == "sgd":
        if cfg.weight_decay:
            parts.append(optax.add_decayed_weights(cfg.weight_decay))
        parts.append(optax.sgd(schedule, momentum=cfg.momentum, nesterov=True))
    elif cfg.name == "lion":
        # Sign-of-momentum optimizer: half the state memory of Adam (one
        # moment, bf16-friendly) with decoupled weight decay built in.
        # Canonical LRs are ~3-10x smaller than AdamW's for the same run.
        # b2=None -> Lion's canonical 0.99 (NOT the adam family's 0.999);
        # an explicit value — including 0.999 — is honored as-is.
        parts.append(
            optax.lion(
                schedule,
                b1=cfg.b1,
                b2=0.99 if cfg.b2 is None else cfg.b2,
                weight_decay=cfg.weight_decay,
            )
        )
    elif cfg.name == "adafactor":
        # Sublinear-memory LM optimizer (factored second moment). Note for
        # ZeRO users: its v_row/v_col state leaves are not param-shaped, so
        # opt_sharding=zero1 cannot mirror param specs onto them — they
        # stay replicated and partition.opt_state_specs warns (they are
        # sublinear in size, so the lost sharding is small by design).
        # cfg.eps is the Adam-family epsilon (default 1e-8); Adafactor's
        # canonical eps is 1e-30 and passing Adam's would floor the RMS
        # denominator 22 orders of magnitude too high — use optax's own
        # default rather than silently changing Adafactor's update rule.
        parts.append(optax.adafactor(schedule))
        if cfg.weight_decay:
            # optax.adafactor's own weight_decay_rate is a RAW per-step
            # multiplier (not lr-scaled): a config tuned for adamw
            # (decay/step = lr*wd) would decay ~1000x too hard. Apply
            # AdamW-semantics decoupled decay instead so weight_decay means
            # the same thing for every optimizer here.
            parts.append(_decoupled_decay(cfg.weight_decay, schedule))
    else:
        raise KeyError(f"unknown optimizer {cfg.name!r}")
    return optax.chain(*parts), schedule
