"""Task loss functions: the glue between models and the compiled step.

``make_loss_fn`` returns the ``loss_fn(params, extras, batch, rng, train)``
contract that train_step.py consumes (``extras`` = non-param variable
collections like BatchNorm stats; ``{}`` for stateless models). Loss math
runs in fp32 regardless of compute dtype (softmax/CE in bf16 loses too much
precision).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax


def _apply(model, params, extras, x, rng, train: bool):
    """Apply with mutable non-param collections in train mode."""
    variables = {"params": params, **extras}
    rngs = {"dropout": rng} if train else None
    mutable = list(extras.keys()) if (train and extras) else False
    out = model.apply(variables, x, train=train, rngs=rngs, mutable=mutable)
    if mutable:
        y, new_extras = out
        return y, dict(new_extras)
    return out, extras


def make_classification_loss(model, input_key: str = "image"):
    def loss_fn(params, extras, batch, rng, train):
        logits, new_extras = _apply(
            model, params, extras, batch[input_key], rng, train
        )
        logits = logits.astype(jnp.float32)
        labels = batch["label"]
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
        acc = (jnp.argmax(logits, -1) == labels).astype(jnp.float32).mean()
        return loss, ({"accuracy": acc}, new_extras)

    return loss_fn


def make_lm_loss(model):
    """Next-token CE over ``batch["tokens"]`` (shape [B, L+1])."""

    def loss_fn(params, extras, batch, rng, train):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        out, new_extras = _apply(model, params, extras, inputs, rng, train)
        # MoE models return (logits, aux_loss); dense return logits.
        aux_loss = jnp.zeros((), jnp.float32)
        if isinstance(out, tuple):
            logits, aux_loss = out
        else:
            logits = out
        logits = logits.astype(jnp.float32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()
        loss = ce + aux_loss
        metrics = {"ce_loss": ce, "perplexity": jnp.exp(ce)}
        if isinstance(out, tuple):
            metrics["aux_loss"] = aux_loss
        return loss, (metrics, new_extras)

    return loss_fn


def make_loss_fn(model, data_name: str):
    if data_name in ("mnist", "synthetic_mnist", "imagenet", "synthetic_imagenet"):
        return make_classification_loss(model, "image")
    if data_name in ("video", "video_synthetic"):
        return make_classification_loss(model, "video")
    if data_name in ("lm", "lm_synthetic"):
        return make_lm_loss(model)
    raise KeyError(f"no task for dataset {data_name!r}")


def example_input(data_cfg, model_cfg, batch_size: int = 1) -> dict[str, Any]:
    """A tiny batch for model init/shape inference.

    ``batch_size`` must divide over the mesh batch axes when the model embeds
    shard_map regions (ring/Ulysses attention) — the Trainer passes the mesh
    batch-axis size.
    """
    import numpy as np

    name = data_cfg.name
    if name in ("mnist", "synthetic_mnist", "imagenet", "synthetic_imagenet"):
        return {
            "image": np.zeros(
                (batch_size, data_cfg.image_size, data_cfg.image_size, data_cfg.channels),
                np.float32,
            ),
            "label": np.zeros((batch_size,), np.int32),
        }
    if name in ("video", "video_synthetic"):
        return {
            "video": np.zeros(
                (
                    batch_size,
                    data_cfg.num_frames,
                    data_cfg.image_size,
                    data_cfg.image_size,
                    data_cfg.channels,
                ),
                np.float32,
            ),
            "label": np.zeros((batch_size,), np.int32),
        }
    if name in ("lm", "lm_synthetic"):
        return {"tokens": np.zeros((batch_size, data_cfg.seq_len + 1), np.int32)}
    raise KeyError(f"no example input for dataset {name!r}")
