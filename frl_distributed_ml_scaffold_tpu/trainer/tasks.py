"""Task loss functions: the glue between models and the compiled step.

``make_loss_fn`` returns the ``loss_fn(params, extras, batch, rng, train)``
contract that train_step.py consumes (``extras`` = non-param variable
collections like BatchNorm stats; ``{}`` for stateless models). Loss math
runs in fp32 regardless of compute dtype (softmax/CE in bf16 loses too much
precision).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax


def _apply(model, params, extras, x, rng, train: bool, **kw):
    """Apply with mutable non-param collections in train mode."""
    variables = {"params": params, **extras}
    rngs = {"dropout": rng} if train else None
    mutable = list(extras.keys()) if (train and extras) else False
    out = model.apply(
        variables, x, train=train, rngs=rngs, mutable=mutable, **kw
    )
    if mutable:
        y, new_extras = out
        return y, dict(new_extras)
    return out, extras


def make_classification_loss(model, input_key: str = "image"):
    def loss_fn(params, extras, batch, rng, train):
        logits, new_extras = _apply(
            model, params, extras, batch[input_key], rng, train
        )
        logits = logits.astype(jnp.float32)
        labels = batch["label"]
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
        acc = (jnp.argmax(logits, -1) == labels).astype(jnp.float32).mean()
        metrics = {"accuracy": acc}
        if logits.shape[-1] > 5:
            # Top-5: the standard ImageNet companion metric. top_k on the
            # MXU-unfriendly class dim is cheap relative to the step and
            # only runs when there are more than 5 classes to rank.
            _, top5 = jax.lax.top_k(logits, 5)
            metrics["accuracy_top5"] = (
                (top5 == labels[..., None]).any(-1).astype(jnp.float32).mean()
            )
        return loss, (metrics, new_extras)

    return loss_fn


def make_lm_loss(model):
    """Next-token CE over ``batch["tokens"]`` (shape [B, L+1]).

    With ``model.config.lm_loss_chunk > 0`` the weight-tied head and the
    cross-entropy run chunk-by-chunk over the sequence inside a
    ``jax.checkpoint``-ed scan, so only ``[B, chunk, vocab]`` logits ever
    exist (and are recomputed in the backward) — the memory that otherwise
    caps the GPT microbatch size is the full ``[B, T, vocab]`` tensor.
    """
    chunk = int(getattr(getattr(model, "config", None), "lm_loss_chunk", 0) or 0)

    def _split(out):
        # MoE models return (logits|feats, aux_loss); dense return one.
        if isinstance(out, tuple):
            return out[0], out[1], True
        return out, jnp.zeros((), jnp.float32), False

    def _chunked_ce(feats, emb, targets):
        b, t, d = feats.shape
        n = t // chunk
        f = feats.reshape(b, n, chunk, d).swapaxes(0, 1)  # [n, B, c, d]
        tg = targets.reshape(b, n, chunk).swapaxes(0, 1)

        @jax.checkpoint
        def body(acc, xs):
            fc, tc = xs
            # Exactly wte.attend's math on one chunk: dtype-matmul with
            # fp32 softmax-CE after.
            logits = (fc @ emb.T).astype(jnp.float32)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, tc
            ).sum()
            return acc + ce, None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (f, tg))
        return total / (b * t)

    warned = []

    def loss_fn(params, extras, batch, rng, train):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        use_chunks = chunk > 0 and inputs.shape[1] % chunk == 0
        if chunk > 0 and not use_chunks and not warned:
            # Trace-time (not step-time) path, so plain logging is fine.
            from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

            get_logger().warning(
                "lm_loss_chunk=%d does not divide the sequence length %d: "
                "falling back to the dense [B, T, vocab] head (the memory "
                "saving is OFF)", chunk, inputs.shape[1],
            )
            warned.append(True)
        out, new_extras = _apply(
            model, params, extras, inputs, rng, train,
            **({"return_features": True} if use_chunks else {}),
        )
        if use_chunks:
            feats, aux_loss, is_moe = _split(out)
            emb = params["wte"]["embedding"].astype(feats.dtype)
            ce = _chunked_ce(feats, emb, targets)
        else:
            logits, aux_loss, is_moe = _split(out)
            logits = logits.astype(jnp.float32)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets
            ).mean()
        loss = ce + aux_loss
        metrics = {"ce_loss": ce, "perplexity": jnp.exp(ce)}
        if is_moe:
            metrics["aux_loss"] = aux_loss
        return loss, (metrics, new_extras)

    return loss_fn


def make_loss_fn(model, data_name: str):
    if data_name in ("mnist", "synthetic_mnist", "imagenet", "synthetic_imagenet"):
        return make_classification_loss(model, "image")
    if data_name in ("video", "video_synthetic"):
        return make_classification_loss(model, "video")
    if data_name in ("lm", "lm_synthetic"):
        return make_lm_loss(model)
    raise KeyError(f"no task for dataset {data_name!r}")


def example_input(data_cfg, model_cfg, batch_size: int = 1) -> dict[str, Any]:
    """A tiny batch for model init/shape inference.

    ``batch_size`` must divide over the mesh batch axes when the model embeds
    shard_map regions (ring/Ulysses attention) — the Trainer passes the mesh
    batch-axis size.
    """
    import numpy as np

    name = data_cfg.name
    if name in ("mnist", "synthetic_mnist", "imagenet", "synthetic_imagenet"):
        return {
            "image": np.zeros(
                (batch_size, data_cfg.image_size, data_cfg.image_size, data_cfg.channels),
                np.float32,
            ),
            "label": np.zeros((batch_size,), np.int32),
        }
    if name in ("video", "video_synthetic"):
        return {
            "video": np.zeros(
                (
                    batch_size,
                    data_cfg.num_frames,
                    data_cfg.image_size,
                    data_cfg.image_size,
                    data_cfg.channels,
                ),
                np.float32,
            ),
            "label": np.zeros((batch_size,), np.int32),
        }
    if name in ("lm", "lm_synthetic"):
        return {"tokens": np.zeros((batch_size, data_cfg.seq_len + 1), np.int32)}
    raise KeyError(f"no example input for dataset {name!r}")
