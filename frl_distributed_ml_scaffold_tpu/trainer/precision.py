"""Re-export shim: the precision policy lives at the package top level so
models/ can import it without pulling in the trainer package (which imports
models — a cycle otherwise)."""

from frl_distributed_ml_scaffold_tpu.precision import Policy, get_policy

__all__ = ["Policy", "get_policy"]
