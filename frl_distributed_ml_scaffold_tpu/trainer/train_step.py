"""The compiled training step (SURVEY C3, C11, C12; call stack (b)).

Reference hot loop: autocast forward → ``loss.backward()`` with DDP hooks
firing bucketed NCCL allreduces → ``optimizer.step()``. TPU-native, all of
that is ONE XLA program: forward, backward, gradient collectives (inserted
by GSPMD from shardings), and the optax update, compiled together so XLA's
latency-hiding scheduler overlaps collectives with compute. The host's only
per-step job is dispatching this function — anything else per-step on host
is a bug (SURVEY call stack (b)).

- Grad accumulation (C12): ``lax.scan`` over microbatches with an fp32
  accumulator, inside the same compiled program.
- Remat (C11): ``jax.checkpoint`` around the loss fn ("full") or with the
  save-dots policy ("dots").
- AMP (C10): params cast to the policy's compute dtype for fwd/bwd;
  gradients cast back to fp32 for the optimizer update.

The model-facing contract (built in trainer/tasks.py):

    loss_fn(params, extras, batch, rng, train)
        -> (loss, (metrics_dict, new_extras))

``extras`` carries non-parameter variable collections (BatchNorm stats);
models without any use ``{}``.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax

from frl_distributed_ml_scaffold_tpu.precision import Policy
from frl_distributed_ml_scaffold_tpu.trainer.train_state import TrainState

LossFn = Callable[..., tuple[jax.Array, tuple[dict[str, jax.Array], Any]]]


def _remat_wrap(loss_fn: LossFn, remat: str) -> LossFn:
    if remat == "none":
        return loss_fn
    if remat == "full":
        return jax.checkpoint(loss_fn, static_argnums=(4,))
    if remat == "dots":
        return jax.checkpoint(
            loss_fn,
            policy=jax.checkpoint_policies.checkpoint_dots,
            static_argnums=(4,),
        )
    raise KeyError(f"unknown remat mode {remat!r}")


def make_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    policy: Policy,
    *,
    seed: int = 0,
    grad_accum: int = 1,
    remat: str = "none",
    ema_decay: float = 0.0,
    offload_opt_state: bool = False,
    grad_shardings: Any = None,
) -> Callable[[TrainState, Any], tuple[TrainState, dict[str, jax.Array]]]:
    """Build the (unjitted) step function; the Trainer jits it with shardings.

    RNG: derived inside the program as ``fold_in(key(seed), step)`` — every
    process computes the same key with zero host traffic, which is what keeps
    multi-host dropout/augmentation coherent.
    """
    wrapped = _remat_wrap(loss_fn, remat)
    grad_fn = jax.value_and_grad(wrapped, has_aux=True)

    def single(params_c, extras, batch, rng):
        (loss, (metrics, new_extras)), grads = grad_fn(
            params_c, extras, batch, rng, True
        )
        return loss, metrics, new_extras, grads

    def accumulated(params_c, extras, batch, rng):
        def reshape(x):
            if x.shape[0] % grad_accum:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by grad_accum={grad_accum}"
                )
            return x.reshape((grad_accum, x.shape[0] // grad_accum) + x.shape[1:])

        micro = jax.tree.map(reshape, batch)
        rngs = jax.random.split(rng, grad_accum)
        zero_grads = jax.tree.map(
            lambda p: jnp.zeros(p.shape, policy.reduce_dtype), params_c
        )
        if grad_shardings is not None:
            # Anchor the fp32 accumulator in the PARAMS' (sharded) layout:
            # under FSDP the per-microbatch grads come out of the backward
            # as shards (reduce-scatter is the gather's transpose), and an
            # unconstrained scan carry would let the partitioner pick a
            # replicated accumulator — i.e. accumulate GATHERED grads,
            # re-materializing full-model-sized fp32 state every step.
            zero_grads = jax.lax.with_sharding_constraint(
                zero_grads, grad_shardings
            )
        first_micro = jax.tree.map(lambda x: x[0], micro)
        metrics_shape = jax.eval_shape(
            lambda: wrapped(params_c, extras, first_micro, rngs[0], True)[1][0]
        )
        zero_metrics = jax.tree.map(
            lambda _: jnp.zeros((), jnp.float32), metrics_shape
        )

        def body(carry, xs):
            g_acc, l_acc, m_acc, ex = carry
            mb, r = xs
            (loss, (metrics, new_ex)), grads = grad_fn(params_c, ex, mb, r, True)
            g_acc = jax.tree.map(
                lambda a, g: a + g.astype(policy.reduce_dtype), g_acc, grads
            )
            m_acc = jax.tree.map(lambda a, m: a + m, m_acc, metrics)
            return (g_acc, l_acc + loss, m_acc, new_ex), None

        (grads, loss, metrics, new_extras), _ = lax.scan(
            body,
            (zero_grads, jnp.zeros((), jnp.float32), zero_metrics, extras),
            (micro, rngs),
        )
        inv = 1.0 / grad_accum
        return (
            loss * inv,
            jax.tree.map(lambda m: m * inv, metrics),
            new_extras,
            jax.tree.map(lambda g: g * inv, grads),
        )

    def step_fn(state: TrainState, batch: Any) -> tuple[TrainState, dict]:
        rng = jax.random.fold_in(jax.random.key(seed), state.step)
        params_c = policy.cast_to_compute(state.params)
        if grad_accum > 1:
            loss, metrics, new_extras, grads = accumulated(
                params_c, state.extras, batch, rng
            )
        else:
            loss, metrics, new_extras, grads = single(
                params_c, state.extras, batch, rng
            )
        grads = policy.cast_to_param(grads)
        opt_state = state.opt_state
        if offload_opt_state:
            # Host-offloaded optimizer state (trainer.offload_opt_state):
            # stream it into HBM for the update, write it back out. The
            # explicit space moves keep the update math on-device; XLA
            # schedules the copies around the backward.
            import jax.memory as jm

            opt_state = jax.device_put(opt_state, jm.Space.Device)
        fused = getattr(tx, "fused_apply", None)
        if fused is not None:
            # Fused-optimizer fast path (ops/fused_adamw.py): params and
            # state come back from one kernel pass — no separate
            # apply_updates traversal.
            new_params, new_opt_state = fused(grads, opt_state, state.params)
        else:
            updates, new_opt_state = tx.update(grads, opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
        if offload_opt_state:
            new_opt_state = jax.device_put(new_opt_state, jm.Space.Host)
        out_metrics = dict(metrics)
        out_metrics["loss"] = loss.astype(jnp.float32)
        out_metrics["grad_norm"] = optax.global_norm(grads).astype(jnp.float32)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            extras=new_extras,
        )
        if ema_decay > 0.0:
            # Inside the same compiled program: fused with the update, and
            # the EMA tree inherits the params' sharding via the out specs.
            new_state = new_state.replace(
                ema_params=jax.tree.map(
                    lambda e, p: e * ema_decay + p.astype(e.dtype) * (1.0 - ema_decay),
                    state.ema_params,
                    new_params,
                )
            )
        return new_state, out_metrics

    return step_fn


def make_eval_step(loss_fn: LossFn, policy: Policy, *, seed: int = 0):
    """Forward-only metrics step (call stack (e))."""

    def eval_fn(state: TrainState, batch: Any) -> dict[str, jax.Array]:
        rng = jax.random.fold_in(jax.random.key(seed + 1), state.step)
        params_c = policy.cast_to_compute(state.params)
        loss, (metrics, _) = loss_fn(params_c, state.extras, batch, rng, False)
        out = dict(metrics)
        out["loss"] = loss.astype(jnp.float32)
        return out

    return eval_fn
