"""Train state: the one pytree that is sharded, stepped, and checkpointed.

Kept to pure arrays (step/params/opt_state) — apply_fn and the optimizer are
closed over by the compiled step instead of stored as static fields, so the
state maps 1:1 onto sharding-spec trees and Orbax checkpoints with no
filtering.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax


class TrainState(flax.struct.PyTreeNode):
    step: Any  # int32 scalar array
    params: Any
    opt_state: Any
    # Non-parameter variable collections (e.g. BatchNorm ``batch_stats``).
    # Under GSPMD these are logically global arrays, so BN statistics reduce
    # over the *global* batch — sync-BN semantics with zero extra code.
    extras: Any
    # EMA of params when trainer.ema_decay > 0, else None (None is an empty
    # subtree to jax, so specs/checkpoints are unaffected when off).
    ema_params: Any = None

    @classmethod
    def create(
        cls,
        params: Any,
        tx: optax.GradientTransformation,
        extras: Any = None,
        *,
        with_ema: bool = False,
    ) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            extras={} if extras is None else extras,
            # jnp.copy, not an alias: the compiled step donates the state,
            # and a shared buffer would be donated twice (XLA rejects it).
            ema_params=jax.tree.map(jnp.copy, params) if with_ema else None,
        )
