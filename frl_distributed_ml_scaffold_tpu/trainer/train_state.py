"""Train state: the one pytree that is sharded, stepped, and checkpointed.

Kept to pure arrays (step/params/opt_state) — apply_fn and the optimizer are
closed over by the compiled step instead of stored as static fields, so the
state maps 1:1 onto sharding-spec trees and Orbax checkpoints with no
filtering.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax.numpy as jnp
import optax


class TrainState(flax.struct.PyTreeNode):
    step: Any  # int32 scalar array
    params: Any
    opt_state: Any
    # Non-parameter variable collections (e.g. BatchNorm ``batch_stats``).
    # Under GSPMD these are logically global arrays, so BN statistics reduce
    # over the *global* batch — sync-BN semantics with zero extra code.
    extras: Any

    @classmethod
    def create(
        cls,
        params: Any,
        tx: optax.GradientTransformation,
        extras: Any = None,
    ) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            extras={} if extras is None else extras,
        )
