"""Trainer (SURVEY C3): the step loop as one compiled XLA program.

``Trainer`` replaces the reference's fit-loop + DDP/FSDP wrapping + AMP
autocast + GradScaler with: sharded state init, a single jit-compiled
``train_step`` (donated state, GSPMD-inserted collectives), a step-indexed
data pipeline, device-side metrics, and checkpoint/eval hooks.
"""

from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
from frl_distributed_ml_scaffold_tpu.precision import Policy, get_policy
from frl_distributed_ml_scaffold_tpu.trainer.train_state import TrainState
from frl_distributed_ml_scaffold_tpu.trainer.train_step import (
    make_eval_step,
    make_train_step,
)
