"""Trainer: sharded init + compiled step + host dispatch loop (SURVEY C3).

Call stack (a)/(b) TPU-native: build mesh → init state *directly sharded*
(``jit(create_state, out_shardings=...)`` — parameters materialize on their
home devices, no host-side full copy, which is what makes FSDP-init of
models bigger than one chip's HBM possible) → dispatch loop. The loop's only
per-step work is building the next batch and dispatching the async step;
metrics are fetched every ``log_every`` steps.
"""

from __future__ import annotations

import os
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from frl_distributed_ml_scaffold_tpu import faults
from frl_distributed_ml_scaffold_tpu.config.schema import ExperimentConfig
from frl_distributed_ml_scaffold_tpu.data.pipeline import build_pipeline
from frl_distributed_ml_scaffold_tpu.dist.mesh import MeshEnv, build_mesh
from frl_distributed_ml_scaffold_tpu.models import create_model
from frl_distributed_ml_scaffold_tpu.parallel.partition import (
    PartitionRules,
    opt_state_specs,
    param_specs,
    shardings_from_specs,
)
from frl_distributed_ml_scaffold_tpu.trainer.optimizers import make_optimizer
from frl_distributed_ml_scaffold_tpu.precision import get_policy
from frl_distributed_ml_scaffold_tpu.trainer.tasks import example_input, make_loss_fn
from frl_distributed_ml_scaffold_tpu.trainer.train_state import TrainState
from frl_distributed_ml_scaffold_tpu.trainer.train_step import (
    make_eval_step,
    make_train_step,
)
from frl_distributed_ml_scaffold_tpu.utils.logging import MetricLogger, get_logger
from frl_distributed_ml_scaffold_tpu.utils.timing import StepTimer
from frl_distributed_ml_scaffold_tpu.utils.trees import tree_param_count


def model_partition_rules(model_cfg: Any, env: MeshEnv) -> PartitionRules | None:
    """TP/EP/PP rules when the model/expert/pipe axis is populated
    (SURVEY C6/C7/C9).

    The rules name all axes; size-1 axes in a spec are no-ops, so applying
    them with model=1, expert=4 still shards the MoE expert weights.
    """
    pipelined = getattr(model_cfg, "pipeline_stages", 1) > 1
    if (
        env.axis_size("model") <= 1
        and env.axis_size("expert") <= 1
        and not pipelined
    ):
        return None
    family = getattr(model_cfg, "family", None)
    if family == "gpt":
        from frl_distributed_ml_scaffold_tpu.models.gpt import gpt_tp_rules
        from frl_distributed_ml_scaffold_tpu.parallel.pipeline import circular_repeat

        return gpt_tp_rules(
            pipelined=pipelined, circular=circular_repeat(model_cfg) > 1
        )
    if family in ("vit", "video"):
        from frl_distributed_ml_scaffold_tpu.models.vit import vit_tp_rules

        return vit_tp_rules()
    if env.axis_size("model") > 1:
        # ResNet has no TP rules by design (conv channel counts don't split
        # Megatron-style); a model>1 mesh would silently replicate — refuse.
        raise ValueError(
            f"model family {family!r} has no tensor-parallel partition "
            "rules; mesh.model must be 1"
        )
    return None


class Trainer:
    """End-to-end training driver for one ExperimentConfig."""

    def __init__(self, cfg: ExperimentConfig, *, mesh_env: MeshEnv | None = None):
        self.cfg = cfg
        self.logger = get_logger()
        # Labels/tokens >= the model's output range make the CE loss NaN
        # while the grads stay finite (XLA clamps the out-of-bounds label
        # gather), which trains garbage that *looks* alive in the logs —
        # refuse up front. num_classes covers the classifiers, vocab_size
        # the LMs; the invariant is the same label-range one.
        for attr in ("num_classes", "vocab_size"):
            d_v = getattr(cfg.data, attr, None)
            m_v = getattr(cfg.model, attr, None)
            if d_v is not None and m_v is not None and d_v != m_v:
                raise ValueError(
                    f"config {cfg.name}: data.{attr}={d_v} != "
                    f"model.{attr}={m_v}; labels out of the model's range "
                    "silently NaN the loss — override both together"
                )
        if cfg.optimizer.name == "fused_adamw" and (
            cfg.parallel.opt_sharding != "like_params"
            or cfg.parallel.param_sharding != "replicated"
            or cfg.mesh.model > 1
            or cfg.mesh.expert > 1
            or cfg.mesh.pipe > 1
        ):
            # The fused kernel is opaque to GSPMD: sharded mu/nu/params
            # would be silently all-gathered every step, defeating the
            # exact memory savings ZeRO/FSDP exist for (ops/fused_adamw.py
            # honesty contract) — refuse rather than de-optimize quietly.
            # mesh.model/expert/pipe > 1 shard params via partition rules
            # even under param_sharding=replicated (TP column/row splits,
            # expert stacks, pipeline stage dims), so those meshes are
            # refused on the same grounds as ZeRO/FSDP.
            raise ValueError(
                "optimizer.name=fused_adamw requires replicated state "
                "(parallel.param_sharding=replicated, "
                "opt_sharding=like_params) on a mesh with model=1, "
                "expert=1 and pipe=1; use adamw for sharded-state configs"
            )
        # The unified overlap-schedule layer (parallel/schedule.py,
        # ROADMAP item 2): derive the declared per-axis gather/scatter
        # schedule — from the legacy fsdp_overlap/tp_overlap/low_precision
        # knobs or an explicit parallel.schedule string — and refuse
        # contradictory declarations HERE, with a typed ScheduleError
        # naming the attribute, instead of as shape errors in the scan
        # body. (lowp without a ring axis, prefetch out of window, and
        # the per-mechanism family/pipeline/sequence checks all live in
        # schedule_from_config/validate_schedule_config.)
        from frl_distributed_ml_scaffold_tpu.parallel.schedule import (
            schedule_from_config,
            validate_schedule_config,
        )

        self.overlap_schedule = schedule_from_config(cfg)
        if self.overlap_schedule is not None:
            validate_schedule_config(self.overlap_schedule, cfg)
        self.env = mesh_env if mesh_env is not None else build_mesh(cfg.mesh)
        self.policy = get_policy(cfg.precision)
        self.model = create_model(cfg.model, self.policy)
        self.tx, self.schedule = make_optimizer(cfg.optimizer, cfg.trainer)
        self.loss_fn = make_loss_fn(self.model, cfg.data.name)
        # Pipeline backend selection (ISSUE 14): ``pipeline_impl="mpmd"``
        # replaces the single compiled step with per-stage programs + the
        # host-side 1F1B driver (parallel/mpmd_pipeline.py). The runner
        # owns state layout ({"stage_j": ...} trees on pipe-slice
        # submeshes), per-stage init/shardings, and both steps; the rest
        # of the Trainer (fit loop, telemetry, checkpointing surface)
        # drives it through the same train_step/eval_step contract.
        self._mpmd = None
        impl = getattr(cfg.model, "pipeline_impl", "spmd")
        if getattr(cfg.model, "pipeline_stages", 1) > 1:
            if impl == "mpmd":
                from frl_distributed_ml_scaffold_tpu.parallel.mpmd_pipeline import (
                    MpmdPipelineRunner,
                )

                self._mpmd = MpmdPipelineRunner(cfg, self.env, self.policy)
            elif impl != "spmd":
                raise KeyError(
                    f"unknown model.pipeline_impl={impl!r} (spmd | mpmd)"
                )
        self.pipeline = build_pipeline(cfg.data, self.env, split="train")
        self._eval_pipeline = None
        self.checkpointer = None  # attached by attach_checkpointer()
        if cfg.checkpoint.enabled:
            from frl_distributed_ml_scaffold_tpu.checkpoint.manager import (
                Checkpointer,
            )

            self.attach_checkpointer(
                Checkpointer(os.path.join(cfg.workdir, cfg.name, "ckpt"), cfg.checkpoint)
            )

        if self._mpmd is not None:
            # The runner already derived per-stage shapes/specs/shardings
            # (and attached the overlap schedule per stage program).
            self.state_shapes = self._mpmd.state_shapes
            self.state_specs = self._mpmd.state_specs
            self.state_shardings = self._mpmd.state_shardings
            self._train_step_fn = None
            self._train_step_jit = None
            self.train_step = self._mpmd.train_step
            self.eval_step = self._mpmd.eval_step
        else:
            self._build_state_shardings()
            if self.overlap_schedule is not None:
                # Hooks need the partition specs, so they attach only after
                # the (unhooked) model produced the state shapes above; the
                # params tree is identical with hooks on or off.
                self._attach_schedule()
            self._compile_steps()

    # ---------------------------------------------------------------- setup

    def _init_state_fn(self, rng):
        # The init example must stay batch-axis-divisible AFTER the pipeline
        # splits it into microbatches (each microbatch crosses the ring/
        # Ulysses shard_map batch specs on its own).
        from frl_distributed_ml_scaffold_tpu.parallel.pipeline import (
            effective_microbatches,
        )

        micro = effective_microbatches(self.cfg.model)
        x = example_input(
            self.cfg.data, self.cfg.model, batch_size=self.env.batch_axis_size * micro
        )
        key = "tokens" if "tokens" in x else ("video" if "video" in x else "image")
        inp = jnp.asarray(x[key][:, :-1] if key == "tokens" else x[key])
        variables = dict(self.model.init({"params": rng}, inp, train=False))
        params = variables.pop("params")
        return TrainState.create(
            params,
            self.tx,
            extras=variables,
            with_ema=self.cfg.trainer.ema_decay > 0.0,
        )

    def _build_state_shardings(self) -> None:
        cfg, env = self.cfg, self.env
        rng = jax.random.key(cfg.trainer.seed)
        state_shapes = self._mesh_scoped(jax.eval_shape)(self._init_state_fn, rng)
        rules = model_partition_rules(cfg.model, env)
        p_specs = param_specs(state_shapes.params, cfg.parallel, env.mesh, rules)
        o_specs = opt_state_specs(
            state_shapes.opt_state, state_shapes.params, p_specs, cfg.parallel, env.mesh
        )
        # Non-param collections (BatchNorm stats etc.) are small — replicate.
        e_specs = jax.tree.map(lambda _: P(), state_shapes.extras)
        self.state_specs = TrainState(
            step=P(),
            params=p_specs,
            opt_state=o_specs,
            extras=e_specs,
            # EMA mirrors params exactly, so it rides the same specs.
            ema_params=p_specs if state_shapes.ema_params is not None else None,
        )
        self.state_shardings = shardings_from_specs(self.state_specs, env.mesh)
        if cfg.trainer.offload_opt_state:
            # Probe a device THIS process owns: on a multi-host mesh,
            # devices.flat[0] belongs to process 0 and its
            # addressable_memories() is not queryable from other hosts.
            dev0 = next(
                (d for d in env.mesh.devices.flat
                 if d.process_index == jax.process_index()),
                env.mesh.devices.flat[0],
            )
            kinds = {m.kind for m in dev0.addressable_memories()}
            # The CPU backend LISTS pinned_host but its SPMD partitioner
            # cannot place arrays there (RET_CHECK crash) — refuse by
            # platform, not just by advertised memory kinds.
            if dev0.platform == "cpu" or "pinned_host" not in kinds:
                raise ValueError(
                    "trainer.offload_opt_state=true is a TPU capacity "
                    f"feature (platform={dev0.platform!r}, memory kinds "
                    f"{sorted(kinds)}); the CPU sim cannot partition "
                    "host-memory arrays — see docs/perf_playbook.md"
                )
            self.state_shardings = self.state_shardings.replace(
                opt_state=jax.tree.map(
                    lambda s: s.with_memory_kind("pinned_host"),
                    self.state_shardings.opt_state,
                )
            )
        self.state_shapes = state_shapes
        self._rng = rng

    def _attach_schedule(self) -> None:
        """Rebind the loss model to the declared overlap schedule
        (parallel/schedule.py ``hooked_model``): a blockwise fsdp gather
        rule lowers to the explicit per-block all-gather / reduce-scatter
        hooks, a ring-chunk model rule to the collective-matmul ppermute
        rings — both stacked onto one clone when the schedule declares
        both axes, so the gathers and rings overlap in the same scan
        body. Hooked clone for APPLY only (train/eval loss): the hook
        mechanisms cannot create params, so init/eval_shape keep the
        plain self.model — the params tree is identical either way.
        Requires the partition specs from _build_state_shardings."""
        from frl_distributed_ml_scaffold_tpu.parallel.schedule import (
            hooked_model,
        )

        model = hooked_model(
            self.overlap_schedule, self.model, self.cfg, self.env,
            self.state_specs.params,
        )
        if self.overlap_schedule.block_gather() is not None:
            # Kept for introspection/back-compat: the fsdp-hooked clone
            # (without the ring hooks when both are declared).
            self._overlap_model = self.model.clone(
                param_hooks=model.param_hooks
            )
        if self.overlap_schedule.ring_gather() is not None:
            self._tp_model = model
        self.loss_fn = make_loss_fn(model, self.cfg.data.name)

    def _mesh_scoped(self, fn):
        """Run ``fn`` with this trainer's mesh as the ambient context.

        Tracing is lazy — the context must hold when a compiled fn first
        traces (ring/Ulysses shard_map regions read it), not at Trainer
        construction, or two Trainers with different meshes would poison
        each other's traces.
        """
        from frl_distributed_ml_scaffold_tpu.dist.mesh import mesh_context

        def wrapped(*args, **kwargs):
            with mesh_context(self.env):
                return fn(*args, **kwargs)

        return wrapped

    def init_state(self) -> TrainState:
        """Initialize the train state directly into its shardings."""
        if self._mpmd is not None:
            state = self._mpmd.init_state()
            if self.cfg.trainer.init_params_path:
                host = self._load_init_params_plain(
                    self.cfg.trainer.init_params_path
                )
                new_params = self._mpmd.place_plain_params(host)
                replacements = {"params": new_params}
                if state.ema_params is not None:
                    replacements["ema_params"] = self._mpmd.place_plain_params(
                        host
                    )
                state = state.replace(**replacements)
            self.logger.info(
                "initialized %s (mpmd pipeline): %.2fM params over mesh %s",
                self.cfg.name,
                tree_param_count(state.params) / 1e6,
                dict(self.env.mesh.shape),
            )
            from frl_distributed_ml_scaffold_tpu.parallel.pipeline import (
                pipeline_summary,
            )

            summary = pipeline_summary(self.cfg.model)
            if summary:
                self.logger.info("%s", summary)
            return state
        state = self._mesh_scoped(
            jax.jit(self._init_state_fn, out_shardings=self.state_shardings)
        )(self._rng)
        if self.cfg.trainer.init_params_path:
            host = self._load_init_params(self.cfg.trainer.init_params_path)
            # Free the random-init buffers BEFORE transferring the loaded
            # ones: otherwise peak HBM transiently holds 2x params, which
            # can OOM a model that otherwise fits. The EMA (when on) must
            # start from the loaded weights too — seeding it with the
            # discarded random init would make early evals score garbage.
            stale = [state.params] + (
                [state.ema_params] if state.ema_params is not None else []
            )
            for leaf in jax.tree.leaves(stale):
                if hasattr(leaf, "delete"):
                    leaf.delete()
            new_params = jax.device_put(host, self.state_shardings.params)
            replacements = {"params": new_params}
            if state.ema_params is not None:
                replacements["ema_params"] = jax.device_put(
                    host, self.state_shardings.params
                )
            state = state.replace(**replacements)
        n_params = tree_param_count(state.params)
        self.logger.info(
            "initialized %s: %.2fM params over mesh %s",
            self.cfg.name,
            n_params / 1e6,
            dict(self.env.mesh.shape),
        )
        from frl_distributed_ml_scaffold_tpu.parallel.pipeline import (
            pipeline_summary,
        )

        summary = pipeline_summary(self.cfg.model)
        if summary:
            # GPipe fill/drain cost — the number to watch when tuning
            # pipeline_microbatches (amortizes as M grows).
            self.logger.info("%s", summary)
        return state

    def _load_init_params_plain(self, path: str):
        """MPMD variant of ``_load_init_params``: checkpoint files carry
        the PLAIN (stages=1) layout, so validation runs against the plain
        twin's init shapes; the runner slices the result into per-stage
        trees (``place_plain_params``)."""
        import dataclasses as _dc

        plain = create_model(
            _dc.replace(self.cfg.model, pipeline_stages=1), self.policy
        )
        x = example_input(
            self.cfg.data, self.cfg.model, batch_size=self.env.batch_axis_size
        )
        inp = jnp.asarray(x["tokens"][:, :-1])
        shapes = jax.eval_shape(
            lambda r: plain.init({"params": r}, inp, train=False)["params"],
            jax.random.key(0),
        )
        return self._load_init_params(path, params_shapes=shapes)

    def _load_init_params(self, path: str, params_shapes=None):
        """Load + validate a flax-msgpack params pytree
        (tools/import_hf_gpt2.py output); returns HOST numpy arrays in the
        policy's param dtype (the caller places them into shardings).

        Structure and shapes are validated against the model's own init
        shapes BEFORE any device transfer — a mismatched checkpoint fails
        with the offending paths, not an opaque XLA shape error.
        """
        from flax import serialization

        with open(path, "rb") as fh:
            loaded = serialization.msgpack_restore(fh.read())
        got_paths = {
            jax.tree_util.keystr(k): tuple(v.shape)
            for k, v in jax.tree_util.tree_leaves_with_path(loaded)
        }
        want_paths = {
            jax.tree_util.keystr(k): tuple(v.shape)
            for k, v in jax.tree_util.tree_leaves_with_path(
                self.state_shapes.params
                if params_shapes is None else params_shapes
            )
        }
        if got_paths.keys() != want_paths.keys():
            missing = sorted(want_paths.keys() - got_paths.keys())[:5]
            extra = sorted(got_paths.keys() - want_paths.keys())[:5]
            raise ValueError(
                f"init_params_path {path!r} does not match the model tree "
                f"(missing {missing}, unexpected {extra})"
            )
        bad = [
            k for k in want_paths
            if tuple(got_paths[k]) != tuple(want_paths[k])
        ]
        if bad:
            raise ValueError(
                f"init_params_path {path!r} shape mismatches at {bad[:5]}: "
                + ", ".join(
                    f"{k}: {got_paths[k]} != {want_paths[k]}" for k in bad[:5]
                )
            )
        dtype = self.policy.param_dtype
        loaded = jax.tree.map(lambda x: np.asarray(x, dtype), loaded)
        self.logger.info(
            "initialized params from %s (%.2fM params)",
            path,
            tree_param_count(loaded) / 1e6,
        )
        return loaded

    def _batch_shardings(self, batch: dict) -> dict:
        return self.pipeline.shardings_for(
            {k: np.asarray(v) for k, v in batch.items()}
        )

    def _compile_steps(self) -> None:
        cfg = self.cfg
        step_fn = make_train_step(
            self.loss_fn,
            self.tx,
            self.policy,
            seed=cfg.trainer.seed,
            grad_accum=cfg.trainer.grad_accum,
            remat=cfg.trainer.remat,
            ema_decay=cfg.trainer.ema_decay,
            offload_opt_state=cfg.trainer.offload_opt_state,
            # FSDP: pin the grad-accum accumulator to the params' sharded
            # layout, so microbatch grads accumulate as SHARDS (post
            # reduce-scatter), never as gathered full-model fp32 tensors.
            grad_shardings=(
                self.state_shardings.params
                if cfg.parallel.param_sharding == "fsdp"
                else None
            ),
        )
        # Batch shardings are inferred from the example batch structure.
        example = example_input(cfg.data, cfg.model, batch_size=self.env.batch_axis_size)
        batch_sh = self._batch_shardings(example)
        self._train_step_fn = step_fn  # unjitted, for jaxpr-level analysis
        self._train_step_jit = jax.jit(
            step_fn,
            in_shardings=(self.state_shardings, batch_sh),
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,),
        )
        self.train_step = self._mesh_scoped(self._train_step_jit)
        eval_fn = make_eval_step(self.loss_fn, self.policy, seed=cfg.trainer.seed)
        self.eval_step = self._mesh_scoped(
            jax.jit(eval_fn, in_shardings=(self.state_shardings, batch_sh))
        )

    def step_cost_analysis(self, state, batch) -> dict | None:
        """FLOPs (and, when supported, bytes) of ONE compiled train step.
        Used by bench.py to report model FLOPs and MFU (BASELINE.md
        protocol)."""
        if self._mpmd is not None:
            # Per-stage programs have no single lowered step; the runner
            # sums jaxpr FLOPs over stages x microbatches.
            return self._mpmd.step_cost_analysis()
        try:
            lowered = self._mesh_scoped(self._train_step_jit.lower)(state, batch)
            # Pre-optimization analysis: no backend compile (the jit call
            # path would not reuse an AOT executable, so compiling here
            # would double the heaviest compile), and theoretical model
            # FLOPs — the MFU convention — rather than post-fusion counts.
            cost = lowered.cost_analysis()
            if isinstance(cost, (list, tuple)):  # older jax returns [dict]
                cost = cost[0] if cost else None
            if cost and float(cost.get("flops", 0.0)) > 0:
                return dict(cost)
        except Exception as e:
            self.logger.debug(
                "XLA cost analysis unavailable (%s); trying the jaxpr "
                "FLOPs counter", e,
            )
        # Backends without cost analysis (the axon TPU plugin): count
        # matmul/conv FLOPs straight off the train-step jaxpr — exact for
        # fwd+bwd+optimizer, no backend needed.
        try:
            from frl_distributed_ml_scaffold_tpu.utils.flops import fn_flops

            flops = self._mesh_scoped(fn_flops)(
                self._train_step_fn, state, batch
            )
            return {"flops": float(flops), "flops_source": "jaxpr"}
        except Exception as e:
            # A missing-FLOPs protocol line must be diagnosable: "backend
            # has no cost analysis AND the jaxpr counter failed" is a bug
            # report, not a silent shrug.
            self.logger.warning(
                "step_cost_analysis: XLA cost analysis unavailable and the "
                "jaxpr FLOPs fallback failed (%s: %s); protocol records "
                "will carry no model_flops/mfu",
                type(e).__name__,
                e,
            )
            return None

    # ----------------------------------------------------------------- loop

    def attach_checkpointer(self, checkpointer) -> None:
        self.checkpointer = checkpointer

    def fit(
        self,
        state: TrainState | None = None,
        *,
        num_steps: int | None = None,
        on_step: Callable[[int, dict], None] | None = None,
    ) -> tuple[TrainState, dict]:
        """Run the training loop; returns (final_state, last_metrics)."""
        cfg = self.cfg
        total = num_steps if num_steps is not None else cfg.trainer.total_steps

        if state is None:
            if self.checkpointer is not None and cfg.checkpoint.resume:
                state = self.checkpointer.restore_or_init(self)
            else:
                state = self.init_state()
        # The state's own step counter is the resume point — holds for both
        # checkpoint restores and explicitly passed states, and keeps the
        # step-indexed data stream aligned with what the model has seen.
        start_step = int(jax.device_get(state.step))

        # The resolved config IS the experiment record: offline tools
        # (tools/avg_checkpoints.py) and future resumes rebuild the exact
        # model/optimizer from it without guessing CLI overrides.
        from frl_distributed_ml_scaffold_tpu.config import config_to_dict
        from frl_distributed_ml_scaffold_tpu.utils.logging import (
            is_primary_process,
        )

        run_dir = os.path.join(cfg.workdir, cfg.name)
        if is_primary_process():
            os.makedirs(run_dir, exist_ok=True)
            import json as _json

            with open(os.path.join(run_dir, "config.json"), "w") as fh:
                _json.dump(config_to_dict(cfg), fh, indent=1)

        metric_logger = MetricLogger(
            os.path.join(run_dir, "metrics.jsonl"),
            tb_dir=(
                os.path.join(run_dir, "tb")
                if cfg.trainer.tensorboard
                else None
            ),
        )
        timer = StepTimer(warmup=1)  # first window contains compile
        samples_per_step = cfg.data.global_batch_size
        last_record: dict = {}
        last_logged = start_step

        from frl_distributed_ml_scaffold_tpu.utils.profiling import (
            WindowProfiler,
            annotate,
            annotate_step,
            device_memory_stats,
        )

        # Telemetry (ISSUE 7): one registry per fit() run, exported at
        # every log boundary as a JSONL snapshot record (telemetry.jsonl,
        # next to the metrics.jsonl record of truth) and an atomic
        # Prometheus sidecar file (metrics.prom — textfile-collector
        # shape). The per-step timeline (load_batch/dispatch phases)
        # ring-buffers between boundaries and drains into the same JSONL.
        from frl_distributed_ml_scaffold_tpu.telemetry import (
            MetricsRegistry,
            StallWatchdog,
            Timeline,
            Tracer,
            jsonl_record,
            write_prometheus_file,
        )
        from frl_distributed_ml_scaffold_tpu.utils.logging import JsonlWriter
        from frl_distributed_ml_scaffold_tpu.utils.flops import (
            peak_flops_per_chip,
        )

        telem = MetricsRegistry()
        timeline = Timeline()
        # Tracing (ISSUE 8): per-step spans (step → load_batch/dispatch,
        # plus checkpoint/eval) on one "train" lane. The span context
        # managers wrap jax.profiler Trace/StepTrace annotations
        # (annotate=True), so when the profiler window above is armed the
        # host spans line up with the device trace; the ring additionally
        # exports Chrome-trace-event JSON (<run_dir>/trace_events.json)
        # for runs where no window was armed. Spans tee into the Timeline
        # → telemetry.jsonl, replacing the old bare timeline events.
        tracer = Tracer(
            enabled=cfg.trainer.tracing, annotate=True, timeline=timeline
        )
        train_trace = tracer.new_trace(cfg.name)
        if tracer.enabled:
            def _span_load(step):
                return tracer.span("load_batch", cat="train", step=step)

            def _span_disp(step):
                return tracer.span(
                    "dispatch", cat="train", step=step, step_num=step
                )
        else:
            # tracing=false must not strip the profiler annotations the
            # profile_steps window relies on — the two knobs are
            # independent (a disabled tracer's spans carry no annotation).
            _span_load = lambda step: annotate("load_batch")  # noqa: E731
            _span_disp = annotate_step
        telemetry_jsonl = JsonlWriter(os.path.join(run_dir, "telemetry.jsonl"))
        prom_path = os.path.join(run_dir, "metrics.prom")
        m_step = telem.histogram(
            "train_step_seconds",
            help="per-step e2e wall time (window average, post-warmup)",
        )
        m_wait = telem.histogram(
            "train_data_wait_seconds",
            help="host wait for the next batch, per step",
        )
        m_sps = telem.gauge(
            "train_samples_per_sec_per_chip", help="the north-star metric"
        )
        m_mfu = telem.gauge("train_mfu", help="model FLOPs / chip peak")
        m_wait_frac = telem.gauge(
            "train_data_wait_fraction",
            help="data-wait share of the step (input-bound when near 1)",
        )
        m_hbm_used = telem.gauge("train_hbm_in_use_gib")
        m_hbm_peak = telem.gauge(
            "train_hbm_peak_gib", help="HBM high-watermark per log window"
        )
        m_steps = telem.counter("train_steps_total")
        watchdog = StallWatchdog(
            cfg.trainer.stall_timeout_s,
            name=cfg.name,
            registry=telem,
            timeline=timeline,
            dump_path=os.path.join(run_dir, "stall_dump.txt"),
            # Beats only flow once dispatch does: the first deadline must
            # absorb the initial XLA compile, not false-fire on it.
            first_beat_scale=cfg.trainer.stall_timeout_first_beat_scale,
        )
        if self._mpmd is not None:
            # 1F1B driver telemetry (ISSUE 14): per-stage idle gauges +
            # bubble fraction + boundary-transfer counter into THIS fit's
            # registry, stage-lane spans on the tracer, and watchdog
            # beats from inside the driver loop (a wedged inter-stage
            # transfer fires the stall dump instead of hanging silently).
            self._mpmd.attach_telemetry(
                registry=telem, tracer=tracer, trace=train_trace,
                watchdog=watchdog,
            )
        flops_per_step: float | None = None  # lazy; False once probing failed
        window_wait = 0.0

        profiler = WindowProfiler(
            os.path.join(run_dir, "trace"),
            start_step=start_step + cfg.trainer.profile_start_step,
            num_steps=cfg.trainer.profile_steps,
        )

        # Graceful preemption (TPU maintenance events deliver SIGTERM):
        # finish the in-flight step, checkpoint, exit cleanly. On a
        # full-slice preemption every host gets the signal, so the
        # collective Orbax save below has all participants. Handlers are
        # process-wide state — install only from the main thread and always
        # restore (the Trainer may be driven from tests or a supervisor).
        import signal as _signal
        import threading as _threading

        preempt = {"signum": None}
        prev_handlers = {}
        if _threading.current_thread() is _threading.main_thread():
            for _sig in (_signal.SIGTERM,):
                def _graceful(signum, frame, _p=preempt):
                    _p["signum"] = signum

                prev_handlers[_sig] = _signal.signal(_sig, _graceful)

        try:
            import time as _time

            for step in range(start_step, total):
                profiler.step_start(step)
                with tracer.span(
                    "step", trace=train_trace, cat="train", step=step
                ):
                    t_load = _time.perf_counter()
                    with _span_load(step):
                        batch = self.pipeline.global_batch(step)
                    data_wait = _time.perf_counter() - t_load
                    window_wait += data_wait
                    m_wait.observe(data_wait)
                    # H2D + enqueue of the async device step: the
                    # StepTraceAnnotation (step_num) groups it with the
                    # device timeline in the profiler trace.
                    t_disp = _time.perf_counter()
                    with _span_disp(step):
                        state, metrics = self.train_step(state, batch)
                # Fault sites (ISSUE 9, faults/plan.py): a hung step is
                # the stall watchdog's prey (the sleep lands between
                # beats, exactly like a wedged collective); a preempt
                # fires our own SIGTERM so the graceful checkpoint-and-
                # exit path below runs. Both no-op unarmed.
                faults.maybe_hang("trainer.hung_step", key=step)
                if faults.fire("trainer.preempt", key=step) is not None:
                    os.kill(os.getpid(), _signal.SIGTERM)
                if not tracer.enabled:
                    # tracing=false must not silence telemetry.jsonl's
                    # phase records — fall back to bare timeline events.
                    timeline.event("load_batch", dur_s=data_wait, step=step)
                    timeline.event(
                        "dispatch",
                        dur_s=_time.perf_counter() - t_disp, step=step,
                    )
                watchdog.beat()
                if (step + 1) % cfg.trainer.log_every == 0 or step + 1 == total:
                    win_steps = step + 1 - last_logged
                    dt = timer.tick_window(metrics["loss"], win_steps)
                    last_logged = step + 1
                    perf = timer.summary(samples_per_step)
                    # Step split: the host waits data_wait for the batch;
                    # the rest of the e2e step is device compute (the loop
                    # only blocks at this boundary, so the split is
                    # window-averaged — the veScale host-side discipline).
                    avg_wait = window_wait / max(win_steps, 1)
                    window_wait = 0.0
                    mem = device_memory_stats()
                    extra = {
                        "lr": float(self.schedule(step)),
                        **{
                            k: round(v, 6)
                            for k, v in perf.items()
                            if k in (
                                "step_time_median_s",
                                "step_time_p50_s",
                                "step_time_p95_s",
                                "step_time_p99_s",
                                "samples_per_sec_per_chip",
                            )
                        },
                        "data_wait_s": round(avg_wait, 6),
                        **mem,
                    }
                    if dt is not None:
                        m_step.observe(dt)
                        extra["compute_s"] = round(max(dt - avg_wait, 0.0), 6)
                        m_wait_frac.set(min(avg_wait / max(dt, 1e-12), 1.0))
                        # MFU: probe step FLOPs once, lazily, and only
                        # after the warmup window (single-boundary test
                        # fits never pay the AOT lower it costs).
                        if flops_per_step is None:
                            try:
                                cost = self.step_cost_analysis(state, batch)
                                flops_per_step = (
                                    float(cost["flops"]) if cost else False
                                )
                            except Exception:
                                flops_per_step = False
                    med = perf.get("step_time_median_s", 0.0)
                    if flops_per_step and med > 0:
                        mfu = flops_per_step / (
                            med * jax.device_count() * peak_flops_per_chip()
                        )
                        extra["mfu"] = mfu
                        m_mfu.set(mfu)
                    m_sps.set(perf.get("samples_per_sec_per_chip", 0.0))
                    m_hbm_used.set(mem.get("hbm_in_use_gib", 0.0))
                    m_hbm_peak.set(mem.get("hbm_peak_gib", 0.0))
                    m_steps.inc(win_steps)
                    last_record = metric_logger.log(step + 1, metrics, extra)
                    for rec in timeline.drain():
                        telemetry_jsonl.write(rec)
                    telemetry_jsonl.write(jsonl_record(telem, step=step + 1))
                    if is_primary_process():
                        write_prometheus_file(telem, prom_path)
                if on_step is not None:
                    on_step(step, metrics)
                if (
                    self.checkpointer is not None
                    and (step + 1) % cfg.checkpoint.save_every == 0
                ):
                    with tracer.span(
                        "checkpoint", trace=train_trace, cat="train",
                        step=step + 1,
                    ):
                        self.checkpointer.save(step + 1, state)
                if cfg.trainer.eval_every and (step + 1) % cfg.trainer.eval_every == 0:
                    with tracer.span(
                        "eval", trace=train_trace, cat="train", step=step + 1
                    ):
                        eval_metrics = self.evaluate(state)
                    metric_logger.log(step + 1, eval_metrics, {"split": "eval"})
                if preempt["signum"] is not None:
                    self.logger.warning(
                        "signal %d: checkpointing at step %d and exiting "
                        "cleanly (preemption)", preempt["signum"], step + 1
                    )
                    if self.checkpointer is not None:
                        # Skip the forced save when the periodic one just
                        # covered this step — re-serializing an identical
                        # checkpoint burns the fixed preemption grace window.
                        # trainer.preempt_save=false skips the forced save
                        # entirely (externally managed checkpoints) but
                        # still waits: in-flight periodic saves must land
                        # their commit markers before the clean exit.
                        if (
                            cfg.trainer.preempt_save
                            and (step + 1) % cfg.checkpoint.save_every != 0
                        ):
                            self.checkpointer.save(step + 1, state, force=True)
                        self.checkpointer.wait()
                    last_record = metric_logger.log(
                        step + 1, metrics, {"event": "preempted"}
                    )
                    preempt["exited_early"] = True
                    break
            # Final-state save runs INSIDE the signal-protected region: a
            # SIGTERM here (e.g. preemption right as the run finishes) just
            # sets the flag while the save completes, instead of killing
            # the process mid-serialization with default disposition. Only
            # the mid-run preemption break skips it — that path already
            # saved and waited.
            if not preempt.get("exited_early") and self.checkpointer is not None:
                if total % cfg.checkpoint.save_every != 0:
                    # Final state not yet covered by the periodic save above.
                    with tracer.span(
                        "checkpoint", trace=train_trace, cat="train",
                        step=total, final=True,
                    ):
                        self.checkpointer.save(total, state, force=True)
                self.checkpointer.wait()
        finally:
            # A crash mid-window must still flush the captured trace (and
            # release the process-wide profiler) — the crash run is exactly
            # when the trace is wanted. Same for telemetry: the final
            # snapshot + timeline tail are most valuable on the bad exit.
            profiler.stop()
            watchdog.stop()
            try:
                for rec in timeline.drain():
                    telemetry_jsonl.write(rec)
                telemetry_jsonl.write(jsonl_record(telem, step=last_logged))
                if is_primary_process():
                    write_prometheus_file(telem, prom_path)
                    if tracer.enabled:
                        # The span tree (ring tail on long runs) as
                        # Chrome-trace-event JSON — the Perfetto view of
                        # what the host loop was doing, crash runs
                        # included.
                        tracer.write_chrome_trace(
                            os.path.join(run_dir, "trace_events.json")
                        )
            except Exception as e:  # observability must not mask the real error
                self.logger.warning(
                    "final telemetry flush failed (%s: %s); continuing "
                    "shutdown", type(e).__name__, e,
                )
            telemetry_jsonl.close()
            if hasattr(self.pipeline, "close"):
                self.pipeline.close()  # stop prefetch worker + in-flight work
            for _sig, _prev in prev_handlers.items():
                _signal.signal(_sig, _prev)
        metric_logger.close()
        return state, last_record

    def evaluate(self, state: TrainState, num_steps: int | None = None) -> dict:
        if self._eval_pipeline is None:
            self._eval_pipeline = build_pipeline(self.cfg.data, self.env, split="eval")
        if state.ema_params is not None:
            # The point of keeping an EMA: evaluation runs with it. Same
            # TrainState structure/shardings, so the compiled eval reuses.
            state = state.replace(params=state.ema_params)
        n = num_steps or self.cfg.trainer.eval_steps
        acc: dict[str, Any] = {}
        for step in range(n):
            batch = self._eval_pipeline.global_batch(step)
            m = self.eval_step(state, batch)
            acc = m if not acc else jax.tree.map(lambda a, b: a + b, acc, m)
        mean = jax.tree.map(lambda x: x / n, acc)
        return {f"eval_{k}": v for k, v in jax.device_get(mean).items()}
