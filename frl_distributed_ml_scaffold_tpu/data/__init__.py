"""Input pipelines (SURVEY C16): per-host sharded loaders.

Reference: per-rank DataLoader shards. TPU-native: each *process* produces
its local slice of the global batch as numpy; the trainer assembles the
global sharded ``jax.Array`` with ``make_array_from_process_local_data`` so
no batch element ever crosses hosts.

Real-dataset loaders (MNIST/ImageNet/LM/video) check ``data_dir`` and fall
back to deterministic *learnable* synthetic data (class-prototype images,
rule-generated token streams) when absent — this zero-egress environment has
no datasets, and smoke/acceptance tests need losses that actually decrease
(SURVEY §4 integration tier).
"""

from frl_distributed_ml_scaffold_tpu.data.pipeline import (
    Batch,
    DataPipeline,
    build_pipeline,
)
