"""ctypes bindings for the native data-loader core (SURVEY C16).

Loads ``native/libfrl_data.so`` (building it from ``native/frl_data.cpp``
with g++ on first use, cached by source mtime). Every entry point has a
pure-numpy fallback with identical semantics, so environments without a
toolchain degrade gracefully — ``native_available()`` reports which path is
live, and the parity tests assert C++ == numpy bit-for-bit where the
contract is exact (gather) and distributionally where it involves RNG.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import threading

import numpy as np

from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_SRC = os.path.join(_NATIVE_DIR, "frl_data.cpp")


def _host_arch_tag() -> str:
    """Host/microarch tag for the cached .so filename.

    The library is built with ``-march=native`` and cached next to the
    source; on a shared filesystem a multi-host launch could otherwise load
    a lib built for a different CPU and die with SIGILL. Tag = machine arch
    + a hash of the CPU feature flags, so each distinct microarchitecture
    builds (and loads) its own copy.
    """
    flags = ""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.lower().startswith(("flags", "features")):
                    flags = line
                    break
    except OSError:
        pass
    h = hashlib.sha256(flags.encode()).hexdigest()[:8]
    return f"{platform.machine()}-{h}"


_LIB = os.path.join(_NATIVE_DIR, f"libfrl_data.{_host_arch_tag()}.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False
# Set once the claiming loader has published its result (success or
# fallback) — racing callers park on this OUTSIDE the lock.
_done = threading.Event()


def _build() -> bool:
    # Compile to a process-unique temp path and rename into place: rename is
    # atomic on POSIX, so concurrent first-use builds (multi-process launch,
    # shared filesystem) can never load a torn .so.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
        "-pthread", _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
        get_logger().warning(
            "native data core build failed (%s); using numpy fallback", e
        )
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    # _lock only claims/publishes; the g++ build (subprocess.run, up to
    # 120 s) and the dlopen run LOCK-FREE.  Holding the module lock
    # across them was graft-lint concurrency finding blocking-under-lock
    # (data/native.py _load -> _build -> subprocess.run): every data
    # thread's first native call would queue behind one compile.
    # Concurrent builds are already safe without the lock — _build
    # compiles to a pid-unique temp path and os.replace is atomic.
    with _lock:
        claimed = not _tried
        _tried = True
    if not claimed:
        _done.wait()
        return _lib
    try:
        lib = _load_uncached()
        with _lock:
            _lib = lib
        return lib
    finally:
        _done.set()


def _load_uncached() -> ctypes.CDLL | None:
    """Build/bind the library (no caching, no locks held)."""
    if os.environ.get("FRL_TPU_NO_NATIVE"):
        return None
    # A lib shipped without its source is simply trusted (no mtime to
    # compare against) — graceful degradation must not raise.
    stale = not os.path.exists(_LIB) or (
        os.path.exists(_SRC)
        and os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
    )
    if stale and not _build():
        return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError as e:
        get_logger().warning("native data core load failed (%s)", e)
        return None
    try:
        lib.frl_version.restype = ctypes.c_int
        version = lib.frl_version()
        if version < 3 and os.path.exists(_SRC):
            # Stale binary the mtime check missed (checkout ordering,
            # clock skew) but the source is right here — rebuild once.
            del lib
            if _build():
                lib = ctypes.CDLL(_LIB)
                lib.frl_version.restype = ctypes.c_int
                version = lib.frl_version()
        if version < 3:
            # A prebuilt .so shipped without source can predate newer
            # entry points; binding them would raise mid-training.
            # Degrade, don't crash.
            get_logger().warning(
                "native data core is v%d (< v3, missing gather_windows);"
                " using numpy fallback — rebuild from frl_data.cpp",
                version,
            )
            return None
        f64 = ctypes.POINTER(ctypes.c_float)
        i64 = ctypes.POINTER(ctypes.c_int64)
        u8 = ctypes.POINTER(ctypes.c_uint8)
        lib.frl_gather_rows.argtypes = [f64, i64, f64, ctypes.c_int64,
                                        ctypes.c_int64]
        lib.frl_gather_rows_u8.argtypes = [u8, i64, f64, ctypes.c_int64,
                                           ctypes.c_int64]
        lib.frl_augment_batch.argtypes = [
            f64, f64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_uint64, ctypes.c_int,
            f64, f64,
        ]
        i32 = ctypes.POINTER(ctypes.c_int32)
        u16 = ctypes.POINTER(ctypes.c_uint16)
        u32 = ctypes.POINTER(ctypes.c_uint32)
        lib.frl_gather_windows_u16.argtypes = [
            u16, i64, i32, ctypes.c_int64, ctypes.c_int64
        ]
        lib.frl_gather_windows_u32.argtypes = [
            u32, i64, i32, ctypes.c_int64, ctypes.c_int64
        ]
    except AttributeError as e:
        get_logger().warning(
            "native data core missing symbols (%s); using numpy fallback",
            e,
        )
        return None
    get_logger().info("native data core loaded (v%d)", version)
    return lib


def native_available() -> bool:
    return _load() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """dst[i] = src[idx[i]] as float32 (row = trailing dims).

    ``src`` is typically an ``np.load(mmap_mode="r")`` shard, used zero-copy
    (an ``ascontiguousarray`` here would fault the entire mmap into RAM);
    the parallel per-row copy is where the page faults happen, across the
    worker pool. float32 rows are memcpy'd; uint8 rows convert + scale to
    [0, 1] in the same pass. Other dtypes take the numpy fallback.
    """
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    # Validated here so both code paths fail identically: the native kernel
    # would memcpy out of bounds where numpy raises (or, worse, silently
    # wraps negatives) — reject both, before either path runs.
    if idx.size and (idx.min() < 0 or idx.max() >= len(src)):
        bad = idx[(idx < 0) | (idx >= len(src))][0]
        raise IndexError(
            f"gather_rows index {bad} out of bounds for {len(src)} rows"
        )
    lib = _load()
    u8 = src.dtype == np.uint8
    if lib is None or not src.flags["C_CONTIGUOUS"] or (
        src.dtype != np.float32 and not u8
    ):
        out = np.ascontiguousarray(src[idx], dtype=np.float32)
        return out / np.float32(255.0) if u8 else out
    out = np.empty((len(idx),) + src.shape[1:], np.float32)
    row = int(np.prod(src.shape[1:], dtype=np.int64))
    iptr = idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    if u8:
        # uint8 shards convert + scale to [0,1] in the gather pass itself.
        lib.frl_gather_rows_u8(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), iptr,
            _fptr(out), len(idx), row,
        )
    else:
        lib.frl_gather_rows(_fptr(src), iptr, _fptr(out), len(idx), row)
    return out


def gather_windows(src: np.ndarray, starts: np.ndarray, window: int) -> np.ndarray:
    """dst[i] = src[starts[i] : starts[i] + window] as int32.

    The LM token-bin read path: ``src`` is a 1-D uint16/uint32 memmap;
    windows start at arbitrary offsets (plain row-gather can't express
    this). Native path parallelizes the page-faulting copies; the numpy
    fallback is bit-identical.
    """
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    if starts.size and (
        starts.min() < 0 or starts.max() + window > len(src)
    ):
        bad = starts[(starts < 0) | (starts + window > len(src))][0]
        raise IndexError(
            f"gather_windows start {bad} (+{window}) out of bounds for "
            f"{len(src)} tokens"
        )
    lib = _load()
    fname = {
        np.dtype(np.uint16): "frl_gather_windows_u16",
        np.dtype(np.uint32): "frl_gather_windows_u32",
    }.get(src.dtype)
    if lib is None or fname is None or not src.flags["C_CONTIGUOUS"]:
        out = np.empty((len(starts), window), np.int32)
        for i, s in enumerate(starts):
            out[i] = src[s : s + window]
        return out
    out = np.empty((len(starts), window), np.int32)
    ptr_t = ctypes.c_uint16 if src.dtype == np.uint16 else ctypes.c_uint32
    getattr(lib, fname)(
        src.ctypes.data_as(ctypes.POINTER(ptr_t)),
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(starts),
        window,
    )
    return out


_IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
_IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def augment_batch(
    x: np.ndarray,
    crop: int,
    *,
    seed: int,
    train: bool,
    mean: np.ndarray = _IMAGENET_MEAN,
    std: np.ndarray = _IMAGENET_STD,
) -> np.ndarray:
    """NHWC random-crop(+flip)+normalize (train) / center-crop (eval)."""
    n, h, w, c = x.shape
    if crop > h or crop > w:
        # Validated here so both code paths fail identically — the native
        # kernel would otherwise read out of bounds where numpy raises.
        raise ValueError(f"crop {crop} exceeds stored image size {h}x{w}")
    mean = np.ascontiguousarray(np.broadcast_to(mean, (c,)), np.float32)
    std = np.ascontiguousarray(np.broadcast_to(std, (c,)), np.float32)
    lib = _load()
    if lib is None:
        return _augment_numpy(x, crop, seed=seed, train=train, mean=mean, std=std)
    x = np.ascontiguousarray(x, np.float32)
    out = np.empty((n, crop, crop, c), np.float32)
    lib.frl_augment_batch(
        _fptr(x), _fptr(out), n, h, w, c, crop,
        ctypes.c_uint64(seed & (2**64 - 1)), int(train), _fptr(mean),
        _fptr(std),
    )
    return out


_M64 = (1 << 64) - 1


def _splitmix64(s: int) -> tuple[int, int]:
    """One splitmix64 step — bit-identical to the C++ kernel's RNG."""
    s = (s + 0x9E3779B97F4A7C15) & _M64
    z = s
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
    return s, (z ^ (z >> 31)) & _M64


def _uniform01(s: int) -> tuple[int, np.float32]:
    s, z = _splitmix64(s)
    return s, np.float32(z >> 40) * np.float32(1.0 / 16777216.0)


def _augment_numpy(x, crop, *, seed, train, mean, std):
    """Numpy fallback with the SAME splitmix64 draws as the C++ kernel.

    Identical RNG streams matter: batches are pure functions of
    (seed, step) per the resume contract, so resuming in an environment
    whose native availability differs must not change the training stream.
    The parity test asserts native == numpy bit-for-bit.
    """
    n, h, w, c = x.shape
    out = np.empty((n, crop, crop, c), np.float32)
    max_y, max_x = h - crop, w - crop
    for i in range(n):
        if train:
            # Same per-sample stream derivation and draw order as C++
            # (draws skipped when the crop has no freedom, as there).
            s = (seed ^ ((0x243F6A8885A308D3 * (i + 1)) & _M64)) & _M64
            y0 = x0 = 0
            if max_y > 0:
                s, u = _uniform01(s)
                y0 = min(int(np.float32(u * np.float32(max_y + 1))), max_y)
            if max_x > 0:
                s, u = _uniform01(s)
                x0 = min(int(np.float32(u * np.float32(max_x + 1))), max_x)
            s, u = _uniform01(s)
            patch = x[i, y0:y0 + crop, x0:x0 + crop]
            if u < np.float32(0.5):
                patch = patch[:, ::-1]
        else:
            y0, x0 = max_y // 2, max_x // 2
            patch = x[i, y0:y0 + crop, x0:x0 + crop]
        out[i] = (np.asarray(patch, np.float32) - mean) / std
    return out


