"""Shared sharded-.npy corpus machinery (ImageNet images, video clips).

One implementation of shard discovery, data/label pairing validation,
memmapping, offset bookkeeping, and the native per-shard gather — so the
per-dataset loaders hold only their format specifics (augmentation, shape
contracts). Validation happens at construction: a missing labels shard or a
shape-divergent data shard fails here with a clear error, never mid-run.
"""

from __future__ import annotations

import glob
import os
import re
import sys

import numpy as np


def derive_label_classes(
    raw_dir: str, split: str, splits_arg: str = "", out_dir: str = ""
) -> tuple[list[str], list[str]]:
    """Class list for label ids, consistent ACROSS splits (producer tier).

    Ids come from the sorted UNION of class directories over the split
    set — a class present in train but absent in val would otherwise
    shift every later id and silently mislabel eval. ``splits_arg``
    (comma-separated) pins the split set; default is every
    conventionally-named split dir under ``raw_dir`` (train/val/test...),
    falling back to all subdirs for unconventional layouts — so a stray
    non-split directory can't inject fake classes when the convention
    holds. When ``out_dir`` holds a ``*_meta.json`` from an earlier
    split run, its class list must match — mismatch raises rather than
    shipping shards whose train/val ids disagree.

    Returns ``(classes, split_names)``; raises ValueError with an
    operator-actionable message on any inconsistency.
    """
    import json as _json

    split_dir = os.path.join(raw_dir, split)
    if not os.path.isdir(split_dir):
        raise ValueError(f"split directory does not exist: {split_dir}")
    if splits_arg:
        split_names = [s for s in splits_arg.split(",") if s]
        if split not in split_names:
            raise ValueError(
                f"--split {split} not in --splits {split_names}"
            )
    else:
        subdirs = sorted(
            d for d in os.listdir(raw_dir)
            if os.path.isdir(os.path.join(raw_dir, d))
        )
        known = {"train", "val", "valid", "validation", "test", "eval"}
        if split in known and any(d in known for d in subdirs):
            split_names = [d for d in subdirs if d in known]
        else:
            split_names = subdirs
        print(
            f"deriving label ids from splits {split_names} "
            f"(pin with --splits if this is wrong)",
            file=sys.stderr,
        )
    union: set[str] = set()
    for sd in split_names:
        sdir = os.path.join(raw_dir, sd)
        if not os.path.isdir(sdir):
            raise ValueError(f"--splits names missing directory: {sdir}")
        union.update(
            d for d in os.listdir(sdir)
            if os.path.isdir(os.path.join(sdir, d))
        )
    classes = sorted(union)
    if not classes:
        raise ValueError(f"no class directories under {raw_dir}")
    if out_dir:
        for mp in sorted(glob.glob(os.path.join(out_dir, "*_meta.json"))):
            try:
                with open(mp) as fh:
                    prev = _json.load(fh).get("class_names")
            except (OSError, ValueError):
                continue
            if prev is not None and prev != classes:
                raise ValueError(
                    f"class list mismatch vs {mp}: existing {prev} != "
                    f"derived {classes}; re-run all splits against one "
                    "raw_dir"
                )
    return classes, split_names


def aligned_pair_paths(
    data_dir: str, split: str, kind: str
) -> list[tuple[str, str]]:
    """Sealed, index-contiguous (data, labels) shard pairs — the streaming
    tier's unit of visibility.

    A pair is eligible only when BOTH halves are sealed (renamed into
    place) AND every lower-indexed pair is too: producers append in index
    order, but an rsync from a decode farm delivers files in arbitrary
    order, so ``images_002`` may land before ``images_001`` — pairing by
    sorted-list position would then mislabel or crash. Indices are parsed
    and the walk stops at the first gap in EITHER half.
    """
    def by_index(tag: str) -> dict[int, str]:
        out = {}
        for p in glob.glob(
            os.path.join(data_dir, f"{split}_{tag}_*.npy")
        ):
            m = re.search(rf"{tag}_(\d+)\.npy$", os.path.basename(p))
            if m:
                out[int(m.group(1))] = p
        return out

    xs, ys = by_index(kind), by_index("labels")
    common = sorted(set(xs) & set(ys))
    pairs = []
    for j, idx in enumerate(common):
        if idx != common[0] + j:
            break  # gap: a lower-indexed shard is still in flight
        pairs.append((xs[idx], ys[idx]))
    return pairs


def sealed_save(path: str, arr: np.ndarray) -> None:
    """Write a shard ATOMICALLY: ``*.tmp`` then ``os.replace``.

    The streaming tier (data/streaming.py) re-scans the directory while
    producers write; a plain ``np.save`` exposes a torn half-written file
    under the final name. The open-file form keeps np.save from appending
    a second ``.npy`` to the tmp name.
    """
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        np.save(fh, arr)
    os.replace(tmp, path)


class ShardedNpyCorpus:
    """``{split}_{kind}_XXX.npy`` data shards + ``{split}_labels_XXX.npy``.

    ``found`` is False when ``data_dir`` holds no complete shard set (the
    caller decides how to fall back); any *inconsistent* shard set raises.
    """

    def __init__(self, data_dir: str, split: str, kind: str,
                 max_shards: int = 0):
        """``max_shards > 0`` caps the view to the first N index-contiguous
        sealed PAIRS (``aligned_pair_paths``) — the streaming tier uses
        this to hold every host to the same agreed shard count while
        producers keep appending in arbitrary file order. The default
        (0, frozen tier) keeps the strict all-shards view whose pairing
        check below RAISES on any inconsistency — a partially-copied
        frozen corpus is an error, not a window."""
        self.found = False
        if max_shards > 0:
            pairs = aligned_pair_paths(data_dir, split, kind)[:max_shards]
            xs = [x for x, _ in pairs]
            ys = [y for _, y in pairs]
        else:
            xs = sorted(
                glob.glob(os.path.join(data_dir, f"{split}_{kind}_*.npy"))
            )
            ys = sorted(
                glob.glob(os.path.join(data_dir, f"{split}_labels_*.npy"))
            )
        if not xs and not ys:
            return
        def _idx(paths, tag):
            out = []
            for p in paths:
                m = re.search(rf"{tag}_(\d+)\.npy$", os.path.basename(p))
                out.append(m.group(1) if m else os.path.basename(p))
            return out

        if _idx(xs, kind) != _idx(ys, "labels"):
            # A partially-copied corpus must not silently misalign labels.
            raise ValueError(
                f"{data_dir}: {kind}/labels shards do not pair up — "
                f"{[os.path.basename(p) for p in xs]} vs "
                f"{[os.path.basename(p) for p in ys]}"
            )
        # Memmap per shard — real corpora dwarf host RAM.
        self.shards = [np.load(p, mmap_mode="r") for p in xs]
        shapes = {s.shape[1:] for s in self.shards}
        if len(shapes) != 1:
            raise ValueError(
                f"{data_dir}: inconsistent {kind} shard shapes {shapes}; "
                "re-shard the corpus"
            )
        self.item_shape = self.shards[0].shape[1:]
        self.y = np.concatenate([np.load(p) for p in ys]).astype(np.int32)
        self.offsets = np.cumsum([0] + [len(s) for s in self.shards])
        self.n = int(self.offsets[-1])
        if len(self.y) != self.n:
            raise ValueError(
                f"{data_dir}: {self.n} {kind} items but {len(self.y)} labels"
            )
        self.found = True

    def gather(self, idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(items, labels) for sorted indices, via the native parallel
        gather (the memmap page faults happen inside the C++ kernel)."""
        from frl_distributed_ml_scaffold_tpu.data import native

        shard_ids = np.searchsorted(self.offsets, idx, side="right") - 1
        x = np.empty((len(idx),) + self.item_shape, np.float32)
        for s in np.unique(shard_ids):
            mask = shard_ids == s
            x[mask] = native.gather_rows(
                self.shards[s], idx[mask] - self.offsets[s]
            )
        return x, self.y[idx]


def warn_missing(data_dir: str, what: str, split: str) -> None:
    """A configured-but-absent corpus must be loud: training silently on
    synthetic data is the classic wasted-run trap."""
    from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

    get_logger().warning(
        "%s: data_dir=%s has no %s shards for split %r — falling back to "
        "SYNTHETIC data; fix data.data_dir if a real corpus was intended",
        what,
        data_dir,
        what,
        split,
    )
