"""Shared sharded-.npy corpus machinery (ImageNet images, video clips).

One implementation of shard discovery, data/label pairing validation,
memmapping, offset bookkeeping, and the native per-shard gather — so the
per-dataset loaders hold only their format specifics (augmentation, shape
contracts). Validation happens at construction: a missing labels shard or a
shape-divergent data shard fails here with a clear error, never mid-run.
"""

from __future__ import annotations

import glob
import os
import re

import numpy as np


class ShardedNpyCorpus:
    """``{split}_{kind}_XXX.npy`` data shards + ``{split}_labels_XXX.npy``.

    ``found`` is False when ``data_dir`` holds no complete shard set (the
    caller decides how to fall back); any *inconsistent* shard set raises.
    """

    def __init__(self, data_dir: str, split: str, kind: str):
        self.found = False
        xs = sorted(glob.glob(os.path.join(data_dir, f"{split}_{kind}_*.npy")))
        ys = sorted(glob.glob(os.path.join(data_dir, f"{split}_labels_*.npy")))
        if not xs and not ys:
            return
        def _idx(paths, tag):
            out = []
            for p in paths:
                m = re.search(rf"{tag}_(\d+)\.npy$", os.path.basename(p))
                out.append(m.group(1) if m else os.path.basename(p))
            return out

        if _idx(xs, kind) != _idx(ys, "labels"):
            # A partially-copied corpus must not silently misalign labels.
            raise ValueError(
                f"{data_dir}: {kind}/labels shards do not pair up — "
                f"{[os.path.basename(p) for p in xs]} vs "
                f"{[os.path.basename(p) for p in ys]}"
            )
        # Memmap per shard — real corpora dwarf host RAM.
        self.shards = [np.load(p, mmap_mode="r") for p in xs]
        shapes = {s.shape[1:] for s in self.shards}
        if len(shapes) != 1:
            raise ValueError(
                f"{data_dir}: inconsistent {kind} shard shapes {shapes}; "
                "re-shard the corpus"
            )
        self.item_shape = self.shards[0].shape[1:]
        self.y = np.concatenate([np.load(p) for p in ys]).astype(np.int32)
        self.offsets = np.cumsum([0] + [len(s) for s in self.shards])
        self.n = int(self.offsets[-1])
        if len(self.y) != self.n:
            raise ValueError(
                f"{data_dir}: {self.n} {kind} items but {len(self.y)} labels"
            )
        self.found = True

    def gather(self, idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(items, labels) for sorted indices, via the native parallel
        gather (the memmap page faults happen inside the C++ kernel)."""
        from frl_distributed_ml_scaffold_tpu.data import native

        shard_ids = np.searchsorted(self.offsets, idx, side="right") - 1
        x = np.empty((len(idx),) + self.item_shape, np.float32)
        for s in np.unique(shard_ids):
            mask = shard_ids == s
            x[mask] = native.gather_rows(
                self.shards[s], idx[mask] - self.offsets[s]
            )
        return x, self.y[idx]


def warn_missing(data_dir: str, what: str, split: str) -> None:
    """A configured-but-absent corpus must be loud: training silently on
    synthetic data is the classic wasted-run trap."""
    from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

    get_logger().warning(
        "%s: data_dir=%s has no %s shards for split %r — falling back to "
        "SYNTHETIC data; fix data.data_dir if a real corpus was intended",
        what,
        data_dir,
        what,
        split,
    )
