"""Pipeline assembly: dataset → per-host batches → global sharded jax.Array.

The step-indexed pull model (``batch(step)``) rather than a push iterator is
deliberate: it makes the stream a pure function of step, so (a) resume after
checkpoint restore is exact — restart at step k reproduces the batch the
failed run would have seen (SURVEY §7 hard part 3), and (b) a topology
change just changes how the same global batch is split across hosts.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator

import jax
import numpy as np

from frl_distributed_ml_scaffold_tpu import faults
from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
from frl_distributed_ml_scaffold_tpu.dist.mesh import MeshEnv
from frl_distributed_ml_scaffold_tpu.faults import RetryPolicy

Batch = dict[str, np.ndarray]

_IMAGE_DATASETS = {"mnist", "imagenet", "synthetic_mnist", "synthetic_imagenet"}


def _build_source(cfg: DataConfig, split: str):
    name = cfg.name
    if name in ("mnist", "synthetic_mnist"):
        from frl_distributed_ml_scaffold_tpu.data.mnist import MNIST

        return MNIST(cfg, split=split)
    if name in ("imagenet", "synthetic_imagenet"):
        from frl_distributed_ml_scaffold_tpu.data.imagenet import ImageNet

        return ImageNet(cfg, split=split)
    if name == "lm":
        from frl_distributed_ml_scaffold_tpu.data.lm import TokenBinLM

        return TokenBinLM(cfg, split=split)
    if name == "lm_synthetic":
        from frl_distributed_ml_scaffold_tpu.data.synthetic import SyntheticLM

        return SyntheticLM(cfg, split=split)
    if name == "video":
        from frl_distributed_ml_scaffold_tpu.data.video import VideoClips

        return VideoClips(cfg, split=split)
    if name == "video_synthetic":
        from frl_distributed_ml_scaffold_tpu.data.synthetic import SyntheticVideo

        return SyntheticVideo(cfg, split=split)
    raise KeyError(f"unknown dataset {name!r}")


class DataPipeline:
    """Per-host sharded, step-indexed data pipeline.

    ``global_batch(step)`` returns the *global* batch as sharded jax.Arrays:
    each process generates only its slice, then
    ``jax.make_array_from_process_local_data`` assembles the logical array
    over the mesh's batch axes without any cross-host copy.
    """

    def __init__(self, cfg: DataConfig, env: MeshEnv, *, split: str = "train"):
        self.cfg = cfg
        self.env = env
        self.split = split
        self.source = _build_source(cfg, split)
        from frl_distributed_ml_scaffold_tpu.dist.mesh import local_batch_size

        self.local_batch_size = local_batch_size(cfg.global_batch_size, env)
        self._proc = jax.process_index()
        # Loader hardening (ISSUE 9): the host-side batch build is a pure
        # function of step, so a transient failure (decode error on a
        # flaky FS read, a shard mid-replacement) is safely retried under
        # the unified policy; the budget's last exception propagates —
        # a permanently bad shard kills the run loudly.
        self._retry = RetryPolicy(
            max_retries=cfg.loader_max_retries,
            backoff_s=cfg.loader_retry_backoff_s,
            max_backoff_s=max(cfg.loader_retry_backoff_s * 8, 1e-9),
        )
        #: Total batch-build retries this pipeline performed (observable
        #: fault ledger; tests + chaos drills read it).
        self.loader_retries = 0

    def _count_retry(self, attempt: int, exc: BaseException) -> None:
        self.loader_retries += 1

    def local_batch(self, step: int) -> Batch:
        def build() -> Batch:
            faults.maybe_raise("data.loader", key=step)
            return self.source.batch(
                step, self.local_batch_size, host_offset=self._proc
            )

        return self._retry.call(
            build,
            describe=f"{self.split} batch(step={step})",
            on_retry=self._count_retry,
        )

    def global_batch(self, step: int) -> dict[str, jax.Array]:
        """Host batch -> device-committed sharded arrays. ``shardings_for``
        is the single source of truth for placement, so the H2D transfer
        lands each slice directly on its home devices — whichever thread
        runs this (the prefetch worker, in the training loop) pays the
        transfer, not the consumer."""
        local = self.local_batch(step)
        shardings = self.shardings_for(local)
        return {
            key: jax.make_array_from_process_local_data(shardings[key], arr)
            for key, arr in local.items()
        }

    def shardings_for(self, batch: Batch) -> dict[str, jax.sharding.NamedSharding]:
        """NamedSharding per batch key — the single source of truth used both
        for array assembly here and for the trainer's jit in_shardings."""
        return {
            key: jax.sharding.NamedSharding(self.env.mesh, self._spec_for(key, arr))
            for key, arr in batch.items()
        }

    def _spec_for(self, key: str, arr) -> "jax.sharding.PartitionSpec":
        from jax.sharding import PartitionSpec as P

        from frl_distributed_ml_scaffold_tpu.dist.mesh import BATCH_AXES

        # Sequence data additionally shards the time dimension over `seq`
        # when sequence parallelism is on (SURVEY C8). Raw LM batches carry
        # seq_len+1 tokens (inputs+shifted targets), which is generally not
        # divisible by the seq axis — those stay unsharded on time; the
        # sequence-parallel path reshards after the inputs/targets split.
        if (
            key == "tokens"
            and self.env.axis_size("seq") > 1
            and arr.ndim >= 2
            and arr.shape[1] % self.env.axis_size("seq") == 0
        ):
            return P(BATCH_AXES, "seq")
        return P(BATCH_AXES, *([None] * (arr.ndim - 1)))

    def __iter__(self) -> Iterator[dict[str, jax.Array]]:
        step = 0
        while True:
            yield self.global_batch(step)
            step += 1


class PrefetchingPipeline:
    """Builds batches ahead of the consumer on a background worker.

    The reference's DataLoader-worker-pool equivalent, adapted to the
    step-indexed pull model: batches stay pure functions of step (exact
    resume is preserved — a prefetched-but-unconsumed batch is simply
    rebuilt after restart), while host-side batch assembly (native gather/
    augment/synthesis + device transfer) overlaps the previous device step.
    One worker is enough: batch assembly need only be faster than the
    compiled step, not parallel with itself, and a single worker keeps
    device-transfer ordering deterministic.

    The worker does NOT stop at host arrays: it runs the full
    ``DataPipeline.global_batch`` (``shardings_for`` + device placement)
    AND waits for the transfers to land, so a consumed prefetched batch is
    already committed and resident on its devices — the consumer thread's
    only work is dispatching the step, never H2D (tested by
    tests/test_native_data.py::test_prefetch_transfers_on_worker_thread).
    """

    def __init__(self, pipeline: DataPipeline, depth: int = 2):
        import concurrent.futures

        self._p = pipeline
        self._depth = max(1, depth)
        self._futures: dict[int, concurrent.futures.Future] = {}
        self._ex: concurrent.futures.ThreadPoolExecutor | None = None

    # DataPipeline surface the trainer uses --------------------------------
    @property
    def cfg(self):
        return self._p.cfg

    @property
    def local_batch_size(self):
        return self._p.local_batch_size

    @property
    def loader_retries(self):
        return self._p.loader_retries

    def shardings_for(self, batch):
        return self._p.shardings_for(batch)

    def global_batch(self, step: int) -> dict[str, jax.Array]:
        import concurrent.futures

        if self._ex is None:  # re-open after close() (Trainer.fit re-entry)
            self._ex = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="frl-data-prefetch"
            )
        # Resume/seek: drop stale prefetches from another step range.
        stale = [s for s in self._futures if s < step or s > step + self._depth]
        for s in stale:
            self._futures.pop(s).cancel()
        fut = self._futures.pop(step, None)
        for s in range(step + 1, step + 1 + self._depth):
            if s not in self._futures:
                self._futures[s] = self._ex.submit(self._build, s)
        # Cache miss (first call, resume jump): build through the same
        # _build path so the committed-and-resident contract holds for
        # every consumed batch, not just prefetched ones.
        return fut.result() if fut is not None else self._build(step)

    def _build(self, step: int) -> dict[str, jax.Array]:
        """Worker-side batch build INCLUDING the H2D wait: device_put is
        async in jax, so without the block the consumer could still inherit
        an in-flight transfer; blocking here pins the whole transfer under
        the previous device step instead."""
        batch = self._p.global_batch(step)
        jax.block_until_ready(list(batch.values()))
        return batch

    def close(self) -> None:
        """Cancel in-flight work and release the worker thread. Trainer.fit
        calls this on exit; the pipeline transparently re-opens if used
        again."""
        for fut in self._futures.values():
            fut.cancel()
        self._futures.clear()
        if self._ex is not None:
            self._ex.shutdown(wait=False, cancel_futures=True)
            self._ex = None

    def __iter__(self) -> Iterator[dict[str, jax.Array]]:
        step = 0
        while True:
            yield self.global_batch(step)
            step += 1


def build_pipeline(cfg: DataConfig, env: MeshEnv, split: str = "train"):
    pipeline = DataPipeline(cfg, env, split=split)
    if split == "train" and cfg.prefetch > 0:
        return PrefetchingPipeline(pipeline, depth=cfg.prefetch)
    return pipeline
