"""MNIST loader: real IDX/NPZ files when present, synthetic fallback.

Zero-egress environment — no download path. If ``data_dir`` holds the
standard ``mnist.npz`` or IDX-gzip files they are used; otherwise the
class-prototype synthetic generator stands in (same shapes/dtypes, and also
trains to >95% accuracy, preserving the BASELINE config-1 acceptance
criterion).
"""

from __future__ import annotations

import gzip
import os

import numpy as np

from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
from frl_distributed_ml_scaffold_tpu.data.synthetic import SyntheticImages


def _load_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    magic = int.from_bytes(data[2:4], "big")
    ndim = data[3]
    dims = [int.from_bytes(data[4 + 4 * i : 8 + 4 * i], "big") for i in range(ndim)]
    offset = 4 + 4 * ndim
    return np.frombuffer(data, dtype=np.uint8, offset=offset).reshape(dims)


def _find_real_mnist(data_dir: str, split: str):
    npz = os.path.join(data_dir, "mnist.npz")
    if os.path.exists(npz):
        with np.load(npz) as z:
            if split == "train":
                return z["x_train"], z["y_train"]
            return z["x_test"], z["y_test"]
    prefix = "train" if split == "train" else "t10k"
    for ext in (".gz", ""):
        xi = os.path.join(data_dir, f"{prefix}-images-idx3-ubyte{ext}")
        yi = os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte{ext}")
        if os.path.exists(xi) and os.path.exists(yi):
            return _load_idx(xi), _load_idx(yi)
    return None


class MNIST:
    """Deterministic shuffled epochs over real MNIST, or synthetic fallback."""

    def __init__(self, cfg: DataConfig, *, split: str):
        self.cfg = cfg
        self._fallback = None
        self._x = self._y = None
        found = _find_real_mnist(cfg.data_dir, split) if cfg.data_dir else None
        if found is not None:
            x, y = found
            self._x = (x.astype(np.float32) / 255.0 - 0.1307) / 0.3081
            self._x = self._x.reshape(len(x), 28, 28, 1)
            self._y = y.astype(np.int32)
            self._seed = cfg.shuffle_seed
        else:
            self._fallback = SyntheticImages(cfg, split=split)

    @property
    def is_synthetic(self) -> bool:
        return self._fallback is not None

    def batch(self, step: int, batch_size: int, host_offset: int = 0) -> dict:
        if self._fallback is not None:
            return self._fallback.batch(step, batch_size, host_offset)
        rng = np.random.default_rng((self._seed, step, host_offset))
        idx = rng.integers(0, len(self._x), size=batch_size)
        return {"image": self._x[idx], "label": self._y[idx]}
