"""Deterministic, *learnable* synthetic datasets.

Random-label data can't show learning; every generator here embeds a real
input→label mapping so integration tests can assert loss decrease
(SURVEY §4):

- images: per-class prototype patterns + Gaussian noise (linearly separable
  at high SNR — an MLP reaches >95% quickly, like real MNIST).
- LM: tokens follow a noisy affine rule ``t+1 = (a*t + b) mod V`` — next-token
  CE drops well below the uniform log(V) once the rule is learned.
- video: per-class spatio-temporal prototypes (the pattern drifts across
  frames so the temporal dimension carries signal).

All generators are stateless functions of (seed, index) — any host can
produce any element, which is what makes per-host sharding and deterministic
resume trivial (SURVEY §7 hard part 3).
"""

from __future__ import annotations

import numpy as np

from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig


class SyntheticImages:
    """Class-prototype images: ``x = prototype[label] + sigma * noise``."""

    def __init__(self, cfg: DataConfig, *, split: str, sigma: float = 0.35):
        self.cfg = cfg
        self.sigma = sigma
        base_seed = cfg.shuffle_seed + (0 if split == "train" else 7919)
        self._seed = base_seed
        proto_rng = np.random.default_rng(1234)  # prototypes shared by splits
        shape = (cfg.num_classes, cfg.image_size, cfg.image_size, cfg.channels)
        self.prototypes = proto_rng.standard_normal(shape, dtype=np.float32)

    def batch(self, step: int, batch_size: int, host_offset: int = 0) -> dict:
        rng = np.random.default_rng((self._seed, step, host_offset))
        labels = rng.integers(0, self.cfg.num_classes, size=batch_size)
        noise = rng.standard_normal(
            (batch_size,) + self.prototypes.shape[1:], dtype=np.float32
        )
        images = self.prototypes[labels] + self.sigma * noise
        return {"image": images, "label": labels.astype(np.int32)}


class SyntheticLM:
    """Noisy affine next-token rule over the vocab."""

    A = 31
    B = 17
    NOISE_P = 0.05

    def __init__(self, cfg: DataConfig, *, split: str):
        self.cfg = cfg
        self._seed = cfg.shuffle_seed + (0 if split == "train" else 7919)

    def batch(self, step: int, batch_size: int, host_offset: int = 0) -> dict:
        cfg = self.cfg
        rng = np.random.default_rng((self._seed, step, host_offset))
        toks = np.empty((batch_size, cfg.seq_len + 1), dtype=np.int64)
        toks[:, 0] = rng.integers(0, cfg.vocab_size, size=batch_size)
        for t in range(cfg.seq_len):
            nxt = (self.A * toks[:, t] + self.B) % cfg.vocab_size
            flip = rng.random(batch_size) < self.NOISE_P
            nxt = np.where(flip, rng.integers(0, cfg.vocab_size, batch_size), nxt)
            toks[:, t + 1] = nxt
        return {"tokens": toks.astype(np.int32)}


class SyntheticVideo:
    """Per-class drifting spatio-temporal prototypes."""

    def __init__(self, cfg: DataConfig, *, split: str, sigma: float = 0.35):
        self.cfg = cfg
        self.sigma = sigma
        self._seed = cfg.shuffle_seed + (0 if split == "train" else 7919)
        proto_rng = np.random.default_rng(4321)
        shape = (
            cfg.num_classes,
            cfg.num_frames,
            cfg.image_size,
            cfg.image_size,
            cfg.channels,
        )
        # Build frame t as a rolled copy of frame 0 so motion encodes class.
        frame0 = proto_rng.standard_normal(
            (cfg.num_classes, 1, cfg.image_size, cfg.image_size, cfg.channels),
            dtype=np.float32,
        )
        frames = [np.roll(frame0, shift=t, axis=2) for t in range(cfg.num_frames)]
        self.prototypes = np.concatenate(frames, axis=1).reshape(shape)

    def batch(self, step: int, batch_size: int, host_offset: int = 0) -> dict:
        rng = np.random.default_rng((self._seed, step, host_offset))
        labels = rng.integers(0, self.cfg.num_classes, size=batch_size)
        noise = rng.standard_normal(
            (batch_size,) + self.prototypes.shape[1:], dtype=np.float32
        )
        clips = self.prototypes[labels] + self.sigma * noise
        return {"video": clips, "label": labels.astype(np.int32)}
