"""LM corpus loader: memmapped token-bin files if present, else synthetic.

SURVEY C16 names an LM corpus loader alongside MNIST/ImageNet. The on-disk
format is the de-facto standard flat token binary (nanoGPT-style): one
``{split}.bin`` file of little-endian uint16 (or uint32 for vocabs > 65535)
token ids, optionally described by a ``{split}.bin.json`` sidecar
(``{"dtype": "uint16", "vocab_size": N}``). ``write_token_bin`` below both
documents and implements the producer side, so any tokenizer script can
materialize a corpus the loader accepts.

Reading is memmapped and step-indexed: batch ``(step, host_offset)`` draws
its window starts from a counter-based RNG, so the stream is a pure function
of ``(seed, step)`` — exact resume after checkpoint restore, identical
batches regardless of host count or restarts (same contract as every other
loader here). Each sample is one contiguous ``seq_len + 1`` slice (input +
shifted target share the window), so a batch costs ``batch_size`` contiguous
page-cached reads, never a full-file materialization.
"""

from __future__ import annotations

import json
import os

import numpy as np

from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
from frl_distributed_ml_scaffold_tpu.data.synthetic import SyntheticLM

_BIN_DTYPES = {"uint16": np.uint16, "uint32": np.uint32}


def _logger():
    from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

    return get_logger()


def write_token_bin(path: str, tokens, *, vocab_size: int | None = None) -> None:
    """Producer-side tooling: write a token stream as ``<path>`` + sidecar.

    Picks uint16 when the ids fit (half the disk/page-cache footprint of
    uint32 — this is why the format exists), uint32 otherwise.
    """
    tokens = np.asarray(tokens)
    if tokens.ndim != 1:
        raise ValueError(f"token stream must be 1-D, got shape {tokens.shape}")
    if tokens.size and int(tokens.min()) < 0:
        raise ValueError("token ids must be non-negative")
    hi = int(tokens.max()) if tokens.size else 0
    if vocab_size is not None and hi >= vocab_size:
        raise ValueError(f"token id {hi} out of range for vocab_size {vocab_size}")
    # Size the dtype from the VOCAB when declared, not the observed max:
    # the sidecar pins the dtype forever (append_token_bin enforces it),
    # and a first chunk that happened to stay under 65536 must not wedge
    # a 100k-vocab stream on uint16.
    limit = vocab_size - 1 if vocab_size is not None else hi
    dtype = np.uint16 if limit < 2**16 else np.uint32
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tokens.astype(dtype).tofile(path)
    sidecar = {"dtype": dtype.__name__}
    if vocab_size is not None:
        sidecar["vocab_size"] = int(vocab_size)
    with open(path + ".json", "w") as fh:
        json.dump(sidecar, fh)


def _read_sidecar(path: str) -> dict:
    sidecar_path = path + ".json"
    if os.path.exists(sidecar_path):
        with open(sidecar_path) as fh:
            return json.load(fh)
    return {}


def append_token_bin(path: str, tokens) -> None:
    """Streaming-producer append: grow an existing token bin in place.

    The dtype is PINNED by the existing sidecar (``write_token_bin`` must
    have created the file) — an appender that re-decided uint16 vs uint32
    per chunk would corrupt the stream the moment a chunk's max id
    crossed 65535. Appends are what the streaming loader
    (data/streaming.py ``StreamingTokenBin``) consumes: it rounds the
    visible window DOWN to a coarse token block, so a half-flushed tail
    here is never sampled.
    """
    sidecar = _read_sidecar(path)
    dtype = _BIN_DTYPES.get(sidecar.get("dtype"))
    if dtype is None:
        raise ValueError(
            f"{path} has no sidecar dtype; create the bin with "
            "write_token_bin first"
        )
    tokens = np.asarray(tokens)
    if tokens.ndim != 1:
        raise ValueError(f"token stream must be 1-D, got shape {tokens.shape}")
    if tokens.size:
        hi, lo = int(tokens.max()), int(tokens.min())
        if lo < 0 or hi >= np.iinfo(dtype).max + 1:
            raise ValueError(
                f"token ids [{lo}, {hi}] do not fit the bin's pinned "
                f"dtype {dtype.__name__}"
            )
        vocab = sidecar.get("vocab_size")
        if vocab is not None and hi >= vocab:
            raise ValueError(
                f"token id {hi} out of range for vocab_size {vocab}"
            )
    with open(path, "ab") as fh:
        tokens.astype(dtype).tofile(fh)


class TokenBinLM:
    """Memmapped token-bin corpus with step-indexed window sampling."""

    def __init__(self, cfg: DataConfig, *, split: str):
        self.cfg = cfg
        self._fallback = None
        self._mm = None
        path = None
        if cfg.data_dir:
            path = os.path.join(cfg.data_dir, f"{split}.bin")
            if not os.path.exists(path) and split != "train":
                # Smoke runs often ship only train.bin; eval reuses it with a
                # split-salted RNG rather than failing — but say so: metrics
                # computed on training data must be recognizable as such.
                train_path = os.path.join(cfg.data_dir, "train.bin")
                if os.path.exists(train_path):
                    _logger().warning(
                        "lm data: no %s in %s; the %r split is sampling from "
                        "train.bin (split-salted RNG) — these metrics are "
                        "computed on TRAINING data",
                        f"{split}.bin",
                        cfg.data_dir,
                        split,
                    )
                path = train_path
            if not os.path.exists(path):
                # data_dir was explicitly configured: falling back to random
                # synthetic tokens without saying so would silently train on
                # noise (same class of trap as the mesh/opt-state fallbacks).
                if cfg.streaming and split == "train":
                    # Streaming's whole point is "start before the
                    # producer finishes" — but a missing bin must REFUSE
                    # like the shard tier, not quietly train on noise
                    # forever (the fallback decision happens once, here).
                    raise ValueError(
                        f"data.streaming=true but {path} does not exist. "
                        "Start the tokenizer/producer first (write_token_"
                        "bin creates the bin + sidecar) — the streaming "
                        "loader refuses to guess."
                    )
                _logger().warning(
                    "lm data: data_dir=%s has no %s.bin — falling back to "
                    "SYNTHETIC random tokens; fix data.data_dir if a real "
                    "corpus was intended",
                    cfg.data_dir,
                    split,
                )
                path = None
        self._stream = None
        if path is not None:
            sidecar = _read_sidecar(path)
            dtype = _BIN_DTYPES.get(sidecar.get("dtype", "uint16"))
            if dtype is None:
                raise ValueError(
                    f"{path}.json names unsupported dtype "
                    f"{sidecar.get('dtype')!r}; expected uint16/uint32"
                )
            if cfg.streaming and split == "train":
                # Online ingestion: the producer keeps APPENDING to the
                # bin (append_token_bin); the visible token window widens
                # every streaming_refresh_every steps, host-agreed. Train
                # split only — eval keeps the frozen view.
                from frl_distributed_ml_scaffold_tpu.data.streaming import (
                    StreamingTokenBin,
                )

                self._stream = StreamingTokenBin(
                    path, dtype,
                    refresh_every=cfg.streaming_refresh_every,
                )
                self._mm = self._stream.tokens
            elif cfg.streaming:
                # Non-train splits under streaming: FROZEN view of a file
                # a producer may still be appending to — always clamp to
                # whole tokens (a torn byte-tail would fail the memmap),
                # and to whole TOKEN_BLOCKs when the file is big enough
                # for that to matter. Small static eval bins keep their
                # full token-aligned length: zeroing a 5k-token val.bin
                # because the TRAIN stream is online would break eval for
                # a file nothing is appending to.
                from frl_distributed_ml_scaffold_tpu.data.streaming import (
                    TOKEN_BLOCK,
                )

                n_tok = os.path.getsize(path) // np.dtype(dtype).itemsize
                if n_tok >= TOKEN_BLOCK:
                    n_tok = (n_tok // TOKEN_BLOCK) * TOKEN_BLOCK
                self._mm = np.memmap(
                    path, dtype=dtype, mode="r", shape=(n_tok,)
                )
            else:
                self._mm = np.memmap(path, dtype=dtype, mode="r")
            vocab = sidecar.get("vocab_size")
            if vocab is not None and vocab > cfg.vocab_size:
                raise ValueError(
                    f"corpus {path} has vocab_size {vocab} but "
                    f"data.vocab_size={cfg.vocab_size}; the model would "
                    "see out-of-range ids"
                )
            if len(self._mm) < cfg.seq_len + 2:
                raise ValueError(
                    f"corpus {path} has {len(self._mm)} tokens, too short "
                    f"for seq_len={cfg.seq_len}"
                )
        if self._mm is None:
            self._fallback = SyntheticLM(cfg, split=split)
        self._seed = cfg.shuffle_seed + (0 if split == "train" else 7919)

    @property
    def is_synthetic(self) -> bool:
        return self._fallback is not None

    def batch(self, step: int, batch_size: int, host_offset: int = 0) -> dict:
        if self._fallback is not None:
            return self._fallback.batch(step, batch_size, host_offset)
        from frl_distributed_ml_scaffold_tpu.data import native

        if self._stream is not None:
            self._stream.maybe_refresh(step)  # see data/streaming.py
            self._mm = self._stream.tokens
        cfg = self.cfg
        window = cfg.seq_len + 1  # input + next-token target share it
        rng = np.random.default_rng((self._seed, step, host_offset))
        starts = rng.integers(0, len(self._mm) - window, size=batch_size)
        return {"tokens": native.gather_windows(self._mm, starts, window)}
