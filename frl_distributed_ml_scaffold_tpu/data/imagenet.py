"""ImageNet-shape loader: real per-class folders if present, else synthetic.

A real ImageNet copy would need JPEG decode throughput beyond what Python
gives (SURVEY §7 hard part 5); in this zero-egress image, no ImageNet exists,
so the synthetic class-prototype generator provides the same shapes/dtypes
at memory speed — benchmark numbers then measure the chip, not the loader.
If ``data_dir`` points at a directory of pre-decoded ``.npy`` shards
(``{split}_images_XXX.npy`` / ``{split}_labels_XXX.npy``), those are used.
"""

from __future__ import annotations

import glob
import os

import numpy as np

from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
from frl_distributed_ml_scaffold_tpu.data.synthetic import SyntheticImages


class ImageNet:
    def __init__(self, cfg: DataConfig, *, split: str):
        self.cfg = cfg
        self._fallback = None
        self._shards = None
        self._train = split == "train"
        if cfg.data_dir:
            xs = sorted(glob.glob(os.path.join(cfg.data_dir, f"{split}_images_*.npy")))
            ys = sorted(glob.glob(os.path.join(cfg.data_dir, f"{split}_labels_*.npy")))
            if xs and ys:
                # Keep per-shard mmaps — concatenating would materialize the
                # whole dataset (hundreds of GB for ImageNet) in host RAM.
                self._shards = [np.load(p, mmap_mode="r") for p in xs]
                self._y = np.concatenate([np.load(p) for p in ys]).astype(np.int32)
                self._offsets = np.cumsum([0] + [len(s) for s in self._shards])
                self._n = int(self._offsets[-1])
                self._seed = cfg.shuffle_seed
        if self._shards is None:
            self._fallback = SyntheticImages(cfg, split=split)

    @property
    def is_synthetic(self) -> bool:
        return self._fallback is not None

    def batch(self, step: int, batch_size: int, host_offset: int = 0) -> dict:
        if self._fallback is not None:
            return self._fallback.batch(step, batch_size, host_offset)
        from frl_distributed_ml_scaffold_tpu.data import native

        rng = np.random.default_rng((self._seed, step, host_offset))
        idx = np.sort(rng.integers(0, self._n, size=batch_size))
        shard_ids = np.searchsorted(self._offsets, idx, side="right") - 1
        # Per-shard native gather: the parallel memcpy is where the mmap
        # page faults happen (SURVEY §7 hard part 5).
        shape = self._shards[0].shape[1:]
        size = self.cfg.image_size
        if min(shape[0], shape[1]) < size:
            raise ValueError(
                f"stored shards are {shape[0]}x{shape[1]} but "
                f"data.image_size={size}; shards must be stored at >= the "
                "model input size"
            )
        x = np.empty((batch_size,) + shape, np.float32)
        for s in np.unique(shard_ids):
            mask = shard_ids == s
            x[mask] = native.gather_rows(
                self._shards[s], idx[mask] - self._offsets[s]
            )
        # Always through the augment kernel: normalize + (train) flip apply
        # even when stored size == input size — storage size must never
        # change training statistics. Larger storage adds the random crop.
        x = native.augment_batch(
            x,
            size,
            seed=hash((self._seed, step, host_offset)) & (2**63 - 1),
            train=self._train,
        )
        return {"image": x, "label": self._y[idx]}
