"""ImageNet-shape loader: pre-decoded ``.npy`` shards if present, else synthetic.

Per-step JPEG decode on the host would starve the chip (SURVEY §7 hard
part 5), so decode happens OFFLINE: ``tools/decode_imagenet.py`` turns a
raw per-class JPEG tree into ``{split}_images_XXX.npy`` (float32 [0,1] or
uint8 0-255) + ``{split}_labels_XXX.npy`` shards, which this loader
memmaps and gathers per batch. In this zero-egress image no ImageNet
exists, so the synthetic class-prototype generator provides the same
shapes/dtypes at memory speed — benchmark numbers then measure the chip,
not the loader.
"""

from __future__ import annotations

import numpy as np

from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
from frl_distributed_ml_scaffold_tpu.data.shards import (
    ShardedNpyCorpus,
    warn_missing,
)
from frl_distributed_ml_scaffold_tpu.data.synthetic import SyntheticImages


class ImageNet:
    def __init__(self, cfg: DataConfig, *, split: str):
        self.cfg = cfg
        self._fallback = None
        self._corpus = None
        self._train = split == "train"
        if cfg.data_dir:
            if cfg.streaming and self._train:
                # Streaming applies to the TRAIN split only: eval keeps
                # the frozen view (synthetic fallback + warning when its
                # shards don't exist) — a producer streaming train_* must
                # not crash the eval pipeline mid-run.
                from frl_distributed_ml_scaffold_tpu.data.streaming import (
                    StreamingShardCorpus,
                )

                corpus = StreamingShardCorpus(
                    cfg.data_dir, split, "images",
                    refresh_every=cfg.streaming_refresh_every,
                )
            else:
                corpus = ShardedNpyCorpus(cfg.data_dir, split, "images")
            if corpus.found:
                shape = corpus.item_shape
                if min(shape[0], shape[1]) < cfg.image_size:
                    raise ValueError(
                        f"stored shards are {shape[0]}x{shape[1]} but "
                        f"data.image_size={cfg.image_size}; shards must be "
                        "stored at >= the model input size"
                    )
                self._corpus = corpus
                self._seed = cfg.shuffle_seed
            else:
                warn_missing(cfg.data_dir, "images", split)
        if self._corpus is None:
            self._fallback = SyntheticImages(cfg, split=split)

    @property
    def is_synthetic(self) -> bool:
        return self._fallback is not None

    def batch(self, step: int, batch_size: int, host_offset: int = 0) -> dict:
        if self._fallback is not None:
            return self._fallback.batch(step, batch_size, host_offset)
        from frl_distributed_ml_scaffold_tpu.data import native

        if hasattr(self._corpus, "maybe_refresh"):
            # Streaming tier: widen the sampling window to newly sealed
            # shards (host-synchronized; see data/streaming.py).
            self._corpus.maybe_refresh(step)
        rng = np.random.default_rng((self._seed, step, host_offset))
        idx = np.sort(rng.integers(0, self._corpus.n, size=batch_size))
        size = self.cfg.image_size
        # uint8 shards (tools/decode_imagenet.py --dtype uint8, 1/4 the
        # disk) are converted + scaled to [0,1] float32 INSIDE the gather
        # (native.gather_rows) — stored dtype never changes training
        # statistics.
        x, labels = self._corpus.gather(idx)
        # Always through the augment kernel: normalize + (train) flip apply
        # even when stored size == input size — storage size must never
        # change training statistics. Larger storage adds the random crop.
        x = native.augment_batch(
            x,
            size,
            seed=hash((self._seed, step, host_offset)) & (2**63 - 1),
            train=self._train,
        )
        return {"image": x, "label": labels}
