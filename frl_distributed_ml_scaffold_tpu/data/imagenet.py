"""ImageNet-shape loader: real per-class folders if present, else synthetic.

A real ImageNet copy would need JPEG decode throughput beyond what Python
gives (SURVEY §7 hard part 5); in this zero-egress image, no ImageNet exists,
so the synthetic class-prototype generator provides the same shapes/dtypes
at memory speed — benchmark numbers then measure the chip, not the loader.
If ``data_dir`` points at a directory of pre-decoded ``.npy`` shards
(``{split}_images_XXX.npy`` / ``{split}_labels_XXX.npy``), those are used.
"""

from __future__ import annotations

import numpy as np

from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
from frl_distributed_ml_scaffold_tpu.data.shards import (
    ShardedNpyCorpus,
    warn_missing,
)
from frl_distributed_ml_scaffold_tpu.data.synthetic import SyntheticImages


class ImageNet:
    def __init__(self, cfg: DataConfig, *, split: str):
        self.cfg = cfg
        self._fallback = None
        self._corpus = None
        self._train = split == "train"
        if cfg.data_dir:
            corpus = ShardedNpyCorpus(cfg.data_dir, split, "images")
            if corpus.found:
                shape = corpus.item_shape
                if min(shape[0], shape[1]) < cfg.image_size:
                    raise ValueError(
                        f"stored shards are {shape[0]}x{shape[1]} but "
                        f"data.image_size={cfg.image_size}; shards must be "
                        "stored at >= the model input size"
                    )
                self._corpus = corpus
                self._seed = cfg.shuffle_seed
            else:
                warn_missing(cfg.data_dir, "images", split)
        if self._corpus is None:
            self._fallback = SyntheticImages(cfg, split=split)

    @property
    def is_synthetic(self) -> bool:
        return self._fallback is not None

    def batch(self, step: int, batch_size: int, host_offset: int = 0) -> dict:
        if self._fallback is not None:
            return self._fallback.batch(step, batch_size, host_offset)
        from frl_distributed_ml_scaffold_tpu.data import native

        rng = np.random.default_rng((self._seed, step, host_offset))
        idx = np.sort(rng.integers(0, self._corpus.n, size=batch_size))
        size = self.cfg.image_size
        x, labels = self._corpus.gather(idx)
        # Always through the augment kernel: normalize + (train) flip apply
        # even when stored size == input size — storage size must never
        # change training statistics. Larger storage adds the random crop.
        x = native.augment_batch(
            x,
            size,
            seed=hash((self._seed, step, host_offset)) & (2**63 - 1),
            train=self._train,
        )
        return {"image": x, "label": labels}
