"""Video-clip loader: pre-decoded .npy clip shards if present, else synthetic.

SURVEY C16 names "Ego4D clip loaders". Raw video containers need a decode
stack (ffmpeg/decord) this zero-egress image doesn't ship — and decoding
per-step would starve the chip anyway (SURVEY §7 hard part 5). The TPU-
idiomatic pipeline decodes OFFLINE into fixed-shape clip tensors, exactly
as the ImageNet path stores pre-decoded frames: ``{split}_clips_XXX.npy``
``(N, T, H, W, C) float32`` + ``{split}_labels_XXX.npy`` ``(N,) int``,
memmapped per shard, gathered per batch with the native C++ kernel.
``write_clip_shards`` below is the producer side (and documents the format
for any external decoder script).

Sampling is step-indexed like every loader here: batch = f(seed, step), so
resume is exact and host count is irrelevant to the stream.
"""

from __future__ import annotations

import os

import numpy as np

from frl_distributed_ml_scaffold_tpu.config.schema import DataConfig
from frl_distributed_ml_scaffold_tpu.data.shards import (
    ShardedNpyCorpus,
    warn_missing,
)
from frl_distributed_ml_scaffold_tpu.data.synthetic import SyntheticVideo


def write_clip_shards(
    out_dir: str,
    clips: np.ndarray,
    labels: np.ndarray,
    *,
    split: str = "train",
    shard_size: int = 256,
) -> int:
    """Write ``(N, T, H, W, C)`` clips + ``(N,)`` labels as memmappable
    shards. Returns the shard count. Float32 clips are stored as-is;
    normalize offline (or here) once, not per step."""
    clips = np.asarray(clips, np.float32)
    labels = np.asarray(labels, np.int32)
    if clips.ndim != 5 or labels.ndim != 1 or len(clips) != len(labels):
        raise ValueError(
            f"clips must be (N,T,H,W,C) with matching (N,) labels; got "
            f"{clips.shape} / {labels.shape}"
        )
    os.makedirs(out_dir, exist_ok=True)
    n_shards = 0
    for i in range(0, len(clips), shard_size):
        np.save(
            os.path.join(out_dir, f"{split}_clips_{n_shards:03d}.npy"),
            clips[i : i + shard_size],
        )
        np.save(
            os.path.join(out_dir, f"{split}_labels_{n_shards:03d}.npy"),
            labels[i : i + shard_size],
        )
        n_shards += 1
    return n_shards


class VideoClips:
    def __init__(self, cfg: DataConfig, *, split: str):
        self.cfg = cfg
        self._fallback = None
        self._corpus = None
        if cfg.data_dir:
            if cfg.streaming and split == "train":
                # Train split only — eval keeps the frozen view (see
                # data/imagenet.py for the rationale).
                from frl_distributed_ml_scaffold_tpu.data.streaming import (
                    StreamingShardCorpus,
                )

                corpus = StreamingShardCorpus(
                    cfg.data_dir, split, "clips",
                    refresh_every=cfg.streaming_refresh_every,
                )
            else:
                corpus = ShardedNpyCorpus(cfg.data_dir, split, "clips")
            if corpus.found:
                want = (cfg.num_frames, cfg.image_size, cfg.image_size, cfg.channels)
                if corpus.item_shape != want:
                    raise ValueError(
                        f"stored clips are {corpus.item_shape} but the config "
                        f"wants {want}; re-shard or fix data.num_frames/"
                        "image_size"
                    )
                self._corpus = corpus
            else:
                warn_missing(cfg.data_dir, "clips", split)
        if self._corpus is None:
            self._fallback = SyntheticVideo(cfg, split=split)
        self._seed = cfg.shuffle_seed + (0 if split == "train" else 7919)

    @property
    def is_synthetic(self) -> bool:
        return self._fallback is not None

    def batch(self, step: int, batch_size: int, host_offset: int = 0) -> dict:
        if self._fallback is not None:
            return self._fallback.batch(step, batch_size, host_offset)
        if hasattr(self._corpus, "maybe_refresh"):
            self._corpus.maybe_refresh(step)  # see data/streaming.py
        rng = np.random.default_rng((self._seed, step, host_offset))
        idx = np.sort(rng.integers(0, self._corpus.n, size=batch_size))
        x, y = self._corpus.gather(idx)
        return {"video": x, "label": y}
