"""Streaming (online-ingestion) views over growing corpora (C16).

Closes the reference gap the offline tier left open (VERDICT r4 missing
#5): the torch DataLoader can iterate a dataset that is still being
produced; the mmap loaders here froze the corpus at construction. This
module makes the corpus APPEND-ONLY GROWABLE instead — two shapes:

- ``StreamingShardCorpus``: a producer (tools/decode_imagenet.py /
  decode_video.py, a concurrent rsync from a decode farm, ...) keeps
  sealing ``{split}_{kind}_XXX.npy`` + ``{split}_labels_XXX.npy`` pairs
  into ``data.data_dir``; the loader widens its sampling window to the
  new pairs.
- ``StreamingTokenBin``: a tokenizer keeps APPENDING to ``{split}.bin``
  (``append_token_bin`` in data/lm.py); the loader widens its token
  window to the grown file.

TPU-native design constraints drive the three decisions here:

1. **Sealing.** Shard producers write ``*.npy.tmp`` and ``os.replace``
   into the final name (the tools do this since round 5), so a scan
   never sees a torn shard; the LABELS shard is the pair's commit
   marker, and visibility is the longest index-contiguous prefix
   (``aligned_pair_paths`` — robust to out-of-order delivery). Token
   bins are append-only flat files: the visible count is the file
   length rounded DOWN to a coarse block, so a half-flushed tail is
   never sampled.

2. **Hosts agree on the view — over the filesystem, never a collective.**
   Each host scans its own filesystem view, which can momentarily differ
   (NFS attribute caches); per-host batch *shapes* would still match, but
   sampling from different windows would silently skew the data
   distribution across the DP axis. The agreement medium is the corpus
   directory itself (the same design as the elastic supervisor's
   membership tier), as a LEADER-PUBLISHED WINDOW with deferred
   activation rather than a symmetric min (which lets two hosts read
   each other's publishes from different moments and adopt different
   windows): every host publishes its visible ``(count, anchor)`` to
   ``.stream_sync/`` (sealed writes); process 0 alone computes the
   target window (min count, anchors required equal) and publishes it
   with ``activate_at_bucket = current_bucket + 1``; every host —
   leader included — adopts a published window at its own refresh of
   that bucket. Refresh buckets are ``step // refresh_every`` and SPMD
   training keeps hosts within a collective's latency of each other, so
   a window published at bucket b is visible to every host's bucket-b+1
   refresh: all hosts widen at the same step, to the same unit SET
   (anchor + count, not count alone). A host that transiently cannot
   serve the window (NFS lag) defers one refresh and logs it. A device
   collective here would be a deadlock instead: ``maybe_refresh`` runs
   on the data-prefetch WORKER thread, unordered against the main
   thread's train-step collectives, and JAX requires identical
   cross-process launch order. When re-pointing an existing data_dir at
   a new corpus, clear ``.stream_sync/`` first.

3. **Determinism is a watermark, not a promise.** The offline tier's
   "batches are a pure function of (seed, step)" cannot survive a corpus
   that grows on wall-clock time; what IS kept: between refreshes the
   view is frozen (same (seed, step) → same batch), every widening is
   logged with its step and unit count, and ``state()`` exposes the
   watermark for metrics. Exact cross-run reproduction requires
   replaying the same directory growth — stated here rather than
   pretended away.

Reference parity note: torch's IterableDataset/DataLoader streaming
(facebookresearch scaffold's data tier) delivers the same capability via
per-worker iterators; the watermark design replaces worker processes
with the idempotent re-scan because the expensive decode work already
happened offline (SURVEY §7 hard part 5).
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Callable, Optional

import numpy as np

from frl_distributed_ml_scaffold_tpu.data.shards import (
    ShardedNpyCorpus,
    aligned_pair_paths,
)
from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger


def _sealed_pair_count(data_dir: str, split: str, kind: str) -> int:
    """Shard pairs eligible for reading: both halves sealed AND every
    lower index sealed too (``aligned_pair_paths`` — robust to producers
    that deliver files out of index order, e.g. rsync)."""
    return len(aligned_pair_paths(data_dir, split, kind))


class _WindowProtocol:
    """The leader-published window agreement (module docstring decision
    2), generic over what a unit is: shard pairs or token blocks.

    ``scan`` returns this host's local ``(count, anchor)``;
    ``self.visible`` is the currently adopted count (the subclass updates
    it when it actually adopts a view).
    """

    def __init__(self, data_dir: str, tag: str,
                 scan: Callable[[], tuple[int, int]]):
        self.data_dir = data_dir
        self.tag = tag
        self.scan = scan
        self.visible = 0

    def _sync_path(self, name: str) -> str:
        sync_dir = os.path.join(self.data_dir, ".stream_sync")
        os.makedirs(sync_dir, exist_ok=True)
        return os.path.join(sync_dir, f"{self.tag}_{name}.json")

    def _publish(self, count: int, anchor: int, pidx: int) -> None:
        path = self._sync_path(f"host_{pidx}")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"count": count, "anchor": anchor}, fh)
        os.replace(tmp, path)

    def _read_json(self, name: str):
        try:
            with open(self._sync_path(name)) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def _leader_propose(self, n_proc: int, bucket: int,
                        my_anchor: int) -> None:
        """Process 0 only: publish a bigger window once every host's
        publish is visible and anchors agree; activation is DEFERRED to
        the next bucket so every host adopts at the same refresh."""
        counts = []
        for p in range(n_proc):
            rec = self._read_json(f"host_{p}")
            if rec is None or rec.get("anchor") != my_anchor:
                return  # unpublished peer / anchor disagreement: wait
            counts.append(int(rec["count"]))
        target = min(counts)
        win = self._read_json("window")
        # A leftover window from an EARLIER corpus in this directory names
        # a different anchor — its count is incomparable with the current
        # unit set, and every live host just published the new anchor, so
        # the leader has full information to repair it. Without this,
        # a stale larger-count window could never be overwritten and the
        # followers' anchor guard would spin to the deadline.
        stale_anchor = win is not None and int(win.get("anchor", -1)) != my_anchor
        current = 0 if (win is None or stale_anchor) else int(win["count"])
        # Also materialize the very first window even at target 0, so a
        # no-data-yet start FAILS FAST with the caller's precise refusal
        # instead of every follower timing out on an absent file.
        if win is None or stale_anchor or target > max(current, self.visible):
            tmp = self._sync_path("window") + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({"count": target, "anchor": my_anchor,
                           "activate_at_bucket": bucket + 1}, fh)
            os.replace(tmp, self._sync_path("window"))

    def initial(self, deadline_s: float = 60.0) -> int:
        """Construction-time agreement: every host publishes, the leader
        publishes the initial window (activate_at_bucket=0), every host
        waits bounded for it. Returns the agreed count (possibly 0 — the
        caller decides whether 0 is a refusal)."""
        deadline = time.monotonic() + deadline_s
        while True:
            count, anchor = self.scan()
            import jax

            n_proc = jax.process_count()
            if n_proc <= 1:
                return count
            pidx = jax.process_index()
            self._publish(count, anchor, pidx)
            if pidx == 0:
                self._leader_propose(n_proc, bucket=-1, my_anchor=anchor)
            win = self._read_json("window")
            if win is not None:
                agreed = int(win["count"])
                if agreed > 0 and int(win.get("anchor", -1)) != anchor:
                    # The published window names a different unit SET than
                    # this host sees — a stale .stream_sync file from an
                    # earlier corpus in the same directory (the docstring's
                    # "clear .stream_sync first" footgun), or this host's
                    # view lagging a prefix rotation. Adopting it would
                    # silently map indices onto the wrong units: keep
                    # waiting for a window matching the local anchor and
                    # fail loudly at the deadline instead.
                    win = None
                elif agreed <= 0 or count >= agreed:
                    # A same-anchor window from an earlier run on the same
                    # dir is fine — the corpus is append-only so it is
                    # servable, and the first refresh converges every host
                    # onto the leader's fresh proposals.
                    return agreed
                # else: NFS hasn't shown this host the full agreed prefix
                # yet — retry within the deadline rather than serve a
                # silently smaller view.
            if time.monotonic() >= deadline:
                raise ValueError(
                    f"data.streaming=true: no agreed initial window for "
                    f"{self.tag} under {self.data_dir}/.stream_sync within "
                    f"{deadline_s:.0f}s — are all hosts pointing at the "
                    "same shared data_dir?"
                )
            time.sleep(1.0)

    def agree(self, bucket: int) -> Optional[tuple[int, int]]:
        """One refresh round: publish, leader proposes, return the active
        window ``(count, anchor)`` when it is bigger than ``visible`` —
        else None (nothing to adopt this bucket)."""
        count, anchor = self.scan()
        import jax

        if jax.process_count() <= 1:
            return (count, anchor) if count > self.visible else None
        self._publish(count, anchor, jax.process_index())
        if jax.process_index() == 0:
            self._leader_propose(jax.process_count(), bucket, anchor)
        win = self._read_json("window")
        if (
            win is not None
            and int(win.get("activate_at_bucket", 0)) <= bucket
            and int(win["count"]) > self.visible
        ):
            return int(win["count"]), int(win["anchor"])
        return None


#: Adoption retries within one refresh bucket before falling back to the
#: bucket boundary: a transient NFS attribute-cache lag clears within a
#: batch or two, but a permanently unservable window (rotated corpus,
#: mid-run anchor mismatch) must not pay a directory scan + sync publish +
#: warning line on EVERY batch for the rest of the run. A deliberate bare
#: budget, not a faults/retry.py RetryPolicy: adoption is step-driven
#: (the next batch IS the backoff), so the policy's sleeping machinery
#: would never run — only its ``max_retries`` semantics apply.
RETRY_BUDGET_PER_BUCKET = 8


def _defer_adoption(view, step: int, bucket: int, why: str, *args) -> None:
    """Shared retry policy for both streaming tiers (shard + token bin).

    An agreed window ``view`` cannot serve yet: RETRY on the very next
    batch (the window is already active on peers, so every deferred step
    trains on a stale skew of the data distribution across the DP axis) —
    but only ``RETRY_BUDGET_PER_BUCKET`` times per bucket, then defer to
    the boundary. ``view`` needs ``refresh_every`` plus the
    ``_skew_deferrals`` / ``_bucket_retries`` / ``_next_refresh``
    attributes; the skew counter rides ``state()`` so lag is observable.
    """
    view._skew_deferrals += 1
    view._bucket_retries += 1
    if view._bucket_retries <= RETRY_BUDGET_PER_BUCKET:
        view._next_refresh = step + 1
        suffix = " — retrying next batch"
    else:
        view._next_refresh = (bucket + 1) * view.refresh_every
        suffix = (" — retry budget exhausted this bucket, deferring to "
                  "the next refresh bucket")
    get_logger().warning("streaming: " + why + suffix, *args)


class StreamingShardCorpus:
    """A ``ShardedNpyCorpus`` whose shard window can widen over time.

    Drop-in for the frozen corpus (``found`` / ``n`` / ``item_shape`` /
    ``gather`` delegate to the current view); the loader calls
    ``maybe_refresh(step)`` once per batch and the view re-scans every
    ``refresh_every`` steps. Shards already in the view are never
    re-opened — append-only means existing mmaps stay valid.
    """

    def __init__(self, data_dir: str, split: str, kind: str,
                 refresh_every: int):
        self.data_dir, self.split, self.kind = data_dir, split, kind
        self.refresh_every = max(1, refresh_every)
        self._proto = _WindowProtocol(
            data_dir, f"{split}_{kind}", self._local_scan
        )
        agreed = self._proto.initial()
        if agreed == 0:
            # No sealed pair visible on SOME host (the agreed count is a
            # host-min, so every host takes this branch together).
            # Refusing beats the two bad alternatives: an uncapped view
            # can crash on a half-sealed pair (data half present, labels
            # in flight), and a synthetic fallback would silently train
            # on fake data forever — the loader's fallback check happens
            # once, at construction.
            raise ValueError(
                f"data.streaming=true but {data_dir} has no sealed "
                f"{split} {kind}+labels shard pair yet (on every host). "
                "Start the producer first, or wait for its first flush — "
                "the streaming loader refuses to guess."
            )
        self._proto.visible = agreed
        self._view = ShardedNpyCorpus(
            data_dir, split, kind, max_shards=agreed
        )
        self._next_refresh = self.refresh_every
        self._skew_deferrals = 0
        self._bucket_retries = 0
        self._bucket = -1

    def _local_scan(self) -> tuple[int, int]:
        """(count, anchor) of this host's sealed contiguous prefix;
        anchor = first pair's index, -1 when empty."""
        pairs = aligned_pair_paths(self.data_dir, self.split, self.kind)
        if not pairs:
            return 0, -1
        m = re.search(r"_(\d+)\.npy$", os.path.basename(pairs[0][0]))
        return len(pairs), int(m.group(1)) if m else -1

    # -- frozen-corpus surface -------------------------------------------
    @property
    def found(self) -> bool:
        return self._view.found

    @property
    def n(self) -> int:
        return self._view.n

    @property
    def item_shape(self):
        return self._view.item_shape

    def gather(self, idx):
        return self._view.gather(idx)

    # -- streaming surface -----------------------------------------------
    def maybe_refresh(self, step: int) -> None:
        if step < self._next_refresh:
            return
        bucket = step // self.refresh_every
        if bucket != self._bucket:
            self._bucket, self._bucket_retries = bucket, 0
        adopt = self._proto.agree(bucket)
        if adopt is None:
            # Nothing newly active this bucket: next check at the boundary.
            self._next_refresh = (bucket + 1) * self.refresh_every
            return
        count, anchor = adopt
        my_count, my_anchor = self._local_scan()
        if my_anchor != anchor or my_count < count:
            _defer_adoption(
                self, step, bucket,
                "cannot serve agreed window (anchor %d/%d, count %d/%d)",
                my_anchor, anchor, my_count, count,
            )
            return
        try:
            new_view = ShardedNpyCorpus(
                self.data_dir, self.split, self.kind, max_shards=count
            )
        except ValueError as e:
            # A transiently inconsistent directory must never kill a
            # training run mid-flight.
            _defer_adoption(
                self, step, bucket, "inconsistent shard view: %s", e
            )
            return
        if not new_view.found:
            # Racing producer wrote garbage; keep the old view.
            _defer_adoption(self, step, bucket, "agreed window not readable")
            return
        get_logger().info(
            "streaming: widened %s/%s view %d -> %d shards "
            "(%d items) at step %d",
            self.split, self.kind, self._proto.visible, count,
            new_view.n, step,
        )
        self._proto.visible = count
        self._view = new_view
        self._next_refresh = (bucket + 1) * self.refresh_every

    def state(self) -> dict:
        """Watermark for metrics/observability (decision 3 above)."""
        return {
            "shards": self._proto.visible,
            "items": self.n,
            "skew_deferrals": self._skew_deferrals,
        }


#: Token-bin visibility granularity: the visible count rounds DOWN to
#: this many tokens, so a producer's half-flushed tail is never sampled
#: and window proposals stay coarse (one proposal per ~8k new tokens,
#: not per write() syscall).
TOKEN_BLOCK = 8192


class StreamingTokenBin:
    """A growing flat token binary (``{split}.bin``, data/lm.py format)
    whose visible token count widens over time.

    The producer APPENDS (``append_token_bin`` — same dtype enforced via
    the sidecar); the visible window is the file length rounded down to
    ``TOKEN_BLOCK`` tokens, agreed across hosts by the same
    leader-window protocol as the shard tier (anchor is always 0: a flat
    file has one possible prefix). ``tokens`` re-memmaps on widen —
    cheap, and the old map stays valid because the file only grows.
    """

    def __init__(self, path: str, dtype, refresh_every: int):
        self.path = path
        self.dtype = np.dtype(dtype)
        self.refresh_every = max(1, refresh_every)
        self._proto = _WindowProtocol(
            os.path.dirname(path) or ".",
            os.path.basename(path).replace(".", "_"),
            self._local_scan,
        )
        agreed = self._proto.initial()
        if agreed == 0:
            raise ValueError(
                f"data.streaming=true but {path} holds fewer than "
                f"{TOKEN_BLOCK} tokens (on every host). Start the "
                "tokenizer/producer first — the streaming loader "
                "refuses to guess."
            )
        self._proto.visible = agreed
        self._mm = np.memmap(path, dtype=self.dtype, mode="r",
                             shape=(agreed,))
        self._next_refresh = self.refresh_every
        self._skew_deferrals = 0
        self._bucket_retries = 0
        self._bucket = -1

    def _local_scan(self) -> tuple[int, int]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return 0, 0
        tokens = size // self.dtype.itemsize
        return (tokens // TOKEN_BLOCK) * TOKEN_BLOCK, 0

    def __len__(self) -> int:
        return int(self._proto.visible)

    @property
    def tokens(self) -> np.ndarray:
        return self._mm

    def maybe_refresh(self, step: int) -> None:
        if step < self._next_refresh:
            return
        bucket = step // self.refresh_every
        if bucket != self._bucket:
            self._bucket, self._bucket_retries = bucket, 0
        adopt = self._proto.agree(bucket)
        if adopt is None:
            self._next_refresh = (bucket + 1) * self.refresh_every
            return
        count, _ = adopt
        my_count, _ = self._local_scan()
        if my_count < count:
            # Same retry-within-bucket contract (and budget) as the shard
            # tier — one shared policy, _defer_adoption.
            _defer_adoption(
                self, step, bucket,
                "cannot serve agreed token window (%d local < %d agreed)",
                my_count, count,
            )
            return
        get_logger().info(
            "streaming: widened %s view %d -> %d tokens at step %d",
            self.path, self._proto.visible, count, step,
        )
        self._proto.visible = count
        self._mm = np.memmap(self.path, dtype=self.dtype, mode="r",
                             shape=(count,))
        self._next_refresh = (bucket + 1) * self.refresh_every

    def state(self) -> dict:
        return {
            "tokens": int(self._proto.visible),
            "skew_deferrals": self._skew_deferrals,
        }
