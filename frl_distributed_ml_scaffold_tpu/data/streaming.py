"""Streaming (online-ingestion) view over a growing shard corpus (C16).

Closes the reference gap the offline tier left open (VERDICT r4 missing
#5): the torch DataLoader can iterate a dataset that is still being
produced; the mmap shard loaders here froze the corpus at construction.
This module makes the shard directory APPEND-ONLY GROWABLE instead: a
producer (tools/decode_imagenet.py / decode_video.py, a concurrent rsync
from a decode farm, ...) keeps sealing new ``{split}_{kind}_XXX.npy`` +
``{split}_labels_XXX.npy`` pairs into ``data.data_dir`` while training
runs, and the loader periodically re-scans and widens its sampling window
to the new data — no restart, no epoch machinery.

TPU-native design constraints drive the three decisions here:

1. **Sealing by rename.** Producers write ``*.npy.tmp`` and
   ``os.replace`` into the final name (the producers in tools/ do this
   since round 5), so a scan never sees a torn shard. The scanner
   additionally requires the LABELS shard of a pair to exist before the
   pair is eligible — data-then-labels ordering makes label presence the
   commit marker, whatever the producer.

2. **Hosts agree on the view — over the filesystem, never a collective.**
   Each host scans its own filesystem view, which can momentarily differ
   (NFS attribute caches); per-host batch *shapes* would still match, but
   sampling from different windows would silently skew the data
   distribution across the DP axis. The agreement medium is the shard
   directory itself (the same design as the elastic supervisor's
   membership tier), as a LEADER-PUBLISHED WINDOW with deferred
   activation rather than a symmetric min (which lets two hosts read
   each other's publishes from different moments and adopt different
   windows): every host publishes its visible ``(count, anchor)`` to
   ``.stream_sync/`` (sealed writes); process 0 alone computes the
   target window (min count, anchors required equal) and publishes it
   with ``activate_at_bucket = current_bucket + 1``; every host —
   leader included — adopts a published window at its own refresh of
   that bucket. Refresh buckets are ``step // refresh_every`` and SPMD
   training keeps hosts within a collective's latency of each other, so
   a window published at bucket b is visible to every host's bucket-b+1
   refresh: all hosts widen at the same step, to the same shard SET
   (anchor + count, not count alone). A host that transiently cannot
   serve the window (NFS lag) defers one refresh and logs it. A device
   collective here would be a deadlock instead: ``maybe_refresh`` runs
   on the data-prefetch WORKER thread, unordered against the main
   thread's train-step collectives, and JAX requires identical
   cross-process launch order. When re-pointing an existing data_dir at
   a new corpus, clear ``.stream_sync/`` first.

3. **Determinism is a watermark, not a promise.** The offline tier's
   "batches are a pure function of (seed, step)" cannot survive a corpus
   that grows on wall-clock time; what IS kept: between refreshes the
   view is frozen (same (seed, step) → same batch), every widening is
   logged with its step and shard count, and ``state["shards"]`` exposes
   the watermark for metrics. Exact cross-run reproduction requires
   replaying the same directory growth — stated here rather than
   pretended away.

Reference parity note: torch's IterableDataset/DataLoader streaming
(facebookresearch scaffold's data tier) delivers the same capability via
per-worker iterators; the shard-watermark design replaces worker
processes with the idempotent re-scan because the expensive decode work
already happened offline (SURVEY §7 hard part 5).
"""

from __future__ import annotations

import json
import os

from frl_distributed_ml_scaffold_tpu.data.shards import (
    ShardedNpyCorpus,
    aligned_pair_paths,
)
from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger


def _sealed_pair_count(data_dir: str, split: str, kind: str) -> int:
    """Shard pairs eligible for reading: both halves sealed AND every
    lower index sealed too (``aligned_pair_paths`` — robust to producers
    that deliver files out of index order, e.g. rsync)."""
    return len(aligned_pair_paths(data_dir, split, kind))


class StreamingShardCorpus:
    """A ``ShardedNpyCorpus`` whose shard window can widen over time.

    Drop-in for the frozen corpus (``found`` / ``n`` / ``item_shape`` /
    ``gather`` delegate to the current view); the loader calls
    ``maybe_refresh(step)`` once per batch and the view re-scans every
    ``refresh_every`` steps. Shards already in the view are never
    re-opened — append-only means existing mmaps stay valid.
    """

    def __init__(self, data_dir: str, split: str, kind: str,
                 refresh_every: int):
        self.data_dir, self.split, self.kind = data_dir, split, kind
        self.refresh_every = max(1, refresh_every)
        # Construction is a one-time synchronization point: every host
        # publishes, the leader computes and publishes the initial
        # window (activate_at_bucket=0), every host waits bounded for it
        # (jax.distributed init blocks the same way).
        import time as _time

        deadline = _time.monotonic() + 60.0
        agreed = self._initial_window()
        while agreed is None and _time.monotonic() < deadline:
            _time.sleep(1.0)
            agreed = self._initial_window()
        if agreed is None:
            raise ValueError(
                f"data.streaming=true: no agreed initial window under "
                f"{data_dir}/.stream_sync within 60s — are all hosts "
                "pointing at the same shared data_dir?"
            )
        self._shards_visible = agreed
        if self._shards_visible == 0:
            # No sealed pair visible on SOME host (the count is the
            # host-min, so every host takes this branch together).
            # Refusing beats the two bad alternatives: an uncapped view
            # can crash on a half-sealed pair (data half present, labels
            # in flight), and a synthetic fallback would silently train
            # on fake data forever — the loader's fallback check happens
            # once, at construction.
            raise ValueError(
                f"data.streaming=true but {data_dir} has no sealed "
                f"{split} {kind}+labels shard pair yet (on every host). "
                "Start the producer first, or wait for its first flush — "
                "the streaming loader refuses to guess."
            )
        self._view = ShardedNpyCorpus(
            data_dir, split, kind, max_shards=self._shards_visible
        )
        self._next_refresh = self.refresh_every

    # -- frozen-corpus surface -------------------------------------------
    @property
    def found(self) -> bool:
        return self._view.found

    @property
    def n(self) -> int:
        return self._view.n

    @property
    def item_shape(self):
        return self._view.item_shape

    def gather(self, idx):
        return self._view.gather(idx)

    # -- window-agreement protocol (decision 2 above) ---------------------
    def _local_scan(self) -> tuple[int, int]:
        """(count, anchor) of this host's sealed contiguous prefix;
        anchor = first pair's index, -1 when empty."""
        pairs = aligned_pair_paths(self.data_dir, self.split, self.kind)
        if not pairs:
            return 0, -1
        import re as _re

        m = _re.search(r"_(\d+)\.npy$", os.path.basename(pairs[0][0]))
        return len(pairs), int(m.group(1)) if m else -1

    def _sync_path(self, name: str) -> str:
        sync_dir = os.path.join(self.data_dir, ".stream_sync")
        os.makedirs(sync_dir, exist_ok=True)
        return os.path.join(
            sync_dir, f"{self.split}_{self.kind}_{name}.json"
        )

    def _publish(self, count: int, anchor: int, pidx: int) -> None:
        path = self._sync_path(f"host_{pidx}")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"count": count, "anchor": anchor}, fh)
        os.replace(tmp, path)

    def _read_json(self, name: str):
        try:
            with open(self._sync_path(name)) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def _leader_propose(self, n_proc: int, bucket: int,
                        my_anchor: int) -> None:
        """Process 0 only: publish a bigger window once every host's
        publish is visible and anchors agree; activation is DEFERRED to
        the next bucket so every host adopts at the same refresh."""
        counts = []
        for p in range(n_proc):
            rec = self._read_json(f"host_{p}")
            if rec is None or rec.get("anchor") != my_anchor:
                return  # unpublished peer / anchor disagreement: wait
            counts.append(int(rec["count"]))
        target = min(counts)
        win = self._read_json("window")
        current = int(win["count"]) if win else 0
        # Also materialize the very first window even at target 0, so a
        # no-shards-yet start FAILS FAST with the precise refusal below
        # instead of every follower timing out on an absent file.
        if (win is None) or target > max(current, self._shards_visible):
            tmp = self._sync_path("window") + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({"count": target, "anchor": my_anchor,
                           "activate_at_bucket": bucket + 1}, fh)
            os.replace(tmp, self._sync_path("window"))

    def _initial_window(self):
        """Construction-time agreement; returns the agreed count or None
        (retry — a peer or the leader hasn't published yet)."""
        count, anchor = self._local_scan()
        import jax

        n_proc = jax.process_count()
        if n_proc <= 1:
            self._shards_visible = 0  # _leader_propose compares against it
            return count
        pidx = jax.process_index()
        self._publish(count, anchor, pidx)
        if pidx == 0:
            self._shards_visible = 0
            self._leader_propose(n_proc, bucket=-1, my_anchor=anchor)
        win = self._read_json("window")
        if win is None:
            return None
        agreed = int(win["count"])
        if agreed > 0 and count < agreed:
            # NFS hasn't shown this host the full agreed prefix yet —
            # retry within the construction deadline rather than build a
            # silently smaller view.
            return None
        # Stale window from an earlier run on the same dir: fine — the
        # corpus is append-only so it is servable, and the first refresh
        # converges every host onto the leader's fresh proposals.
        return agreed

    def _adopt(self, count: int, anchor: int, step: int) -> None:
        my_count, my_anchor = self._local_scan()
        if my_anchor != anchor or my_count < count:
            get_logger().warning(
                "streaming: cannot serve agreed window (anchor %d/%d, "
                "count %d/%d) — NFS lag? deferring one refresh",
                my_anchor, anchor, my_count, count,
            )
            return
        try:
            new_view = ShardedNpyCorpus(
                self.data_dir, self.split, self.kind, max_shards=count
            )
        except ValueError as e:
            # A transiently inconsistent directory must defer one
            # refresh, never kill a training run mid-flight.
            get_logger().warning(
                "streaming: refresh deferred (inconsistent shard view: "
                "%s)", e
            )
            return
        if not new_view.found:
            return  # racing producer wrote garbage; keep the old view
        get_logger().info(
            "streaming: widened %s/%s view %d -> %d shards "
            "(%d items) at step %d",
            self.split, self.kind, self._shards_visible, count,
            new_view.n, step,
        )
        self._shards_visible = count
        self._view = new_view

    def maybe_refresh(self, step: int) -> None:
        if step < self._next_refresh:
            return
        bucket = step // self.refresh_every
        self._next_refresh = (bucket + 1) * self.refresh_every
        count, anchor = self._local_scan()
        import jax

        if jax.process_count() <= 1:
            if count > self._shards_visible:
                self._adopt(count, anchor, step)
            return
        self._publish(count, anchor, jax.process_index())
        if jax.process_index() == 0:
            self._leader_propose(jax.process_count(), bucket, anchor)
        win = self._read_json("window")
        if (
            win is not None
            and int(win.get("activate_at_bucket", 0)) <= bucket
            and int(win["count"]) > self._shards_visible
        ):
            self._adopt(int(win["count"]), int(win["anchor"]), step)

    def state(self) -> dict:
        """Watermark for metrics/observability (decision 3 above)."""
        return {"shards": self._shards_visible, "items": self.n}
