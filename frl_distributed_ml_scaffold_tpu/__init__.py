"""frl_distributed_ml_scaffold_tpu — a TPU-native distributed-ML scaffold.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
facebookresearch/FRL-Distributed-ML-Scaffold (see SURVEY.md — the reference
mount is empty in this environment, so parity targets are the reconstructed
component inventory SURVEY.md §2, C1–C20, and the five BASELINE.json configs).

Architecture (TPU-first, not a torch translation):

- ``dist/``      — device mesh topology + a thin collective façade over XLA
                   collectives (ICI/DCN), replacing the reference's
                   NCCL/Gloo process groups (SURVEY C1, C2).
- ``trainer/``   — a single jit-compiled train step (grad-accum via
                   ``lax.scan``, remat via ``jax.checkpoint``, bf16 precision
                   policy) replacing the DDP/FSDP wrapper + autocast step
                   loop (SURVEY C3, C10–C12).
- ``parallel/``  — parallelism as sharding annotations: DP/FSDP/ZeRO/TP/PP/
                   SP(ring+Ulysses)/EP as PartitionSpec rules over one mesh
                   (SURVEY C4–C9).
- ``models/``    — MLP, ResNet-50, ViT-B/16, GPT-2-medium, video classifier
                   (SURVEY C15).
- ``data/``      — per-host sharded input pipelines (SURVEY C16).
- ``checkpoint/``— Orbax sharded save/restore with topology-changed resume
                   (SURVEY C13).
- ``launcher/``  — single-entrypoint CLI + elastic checkpoint-restart
                   supervisor (SURVEY C1, C14).
- ``ops/``       — Pallas TPU kernels (ring/flash attention) and fused ops.
- ``utils/``     — pytree paths, logging, timers, profiling (SURVEY C18, C19).
"""

__version__ = "0.1.0"
