"""Step timing + throughput measurement (SURVEY C19, BASELINE.md protocol).

The contract: timings exclude compile (warmup window), force true device
completion via ``device_get`` of the step's scalar outputs (see ``_force``
for why not ``block_until_ready``), and report median + p90 e2e step time
plus samples/sec/chip — the benchmark harness and the trainer both use this
one implementation so numbers agree.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax
import numpy as np


def _force(out) -> None:
    """Force true device completion of ``out`` (per-step scalars, e.g. loss).

    ``jax.block_until_ready`` is doubly wrong on the experimental axon TPU
    relay: it reports donated/aliased buffers ready immediately, silently
    turning step timing into dispatch timing (observed: "1.5ms" RN50 steps
    that are really 207ms) — and on live buffers it issues a slow
    stream-sync RPC (~75 ms/call measured 2026-07-30, +2.5 ms/step charged
    to 30-step windows) on top of the fetch. ``device_get`` of each leaf
    both forces the real data dependency and is the exact operation the
    training loop's metric fetch performs at log boundaries, so timed
    windows measure what production steps cost — no more, no less.
    """
    if out is None:
        return
    jax.device_get(out)  # one fetch for the whole (scalar-leaved) pytree


@dataclass
class StepTimer:
    """Collects per-step wall times after a warmup window.

    Usage::

        timer = StepTimer(warmup=3)
        for batch in data:
            state, metrics = train_step(state, batch)
            timer.tick(metrics["loss"])  # force a per-step SCALAR + record
                                         # (never the state: _force fetches
                                         # everything it is handed)
    """

    warmup: int = 3
    _times: list[float] = field(default_factory=list)
    _seen: int = 0
    _last: float | None = None

    def tick(self, out=None) -> float | None:
        """Mark the end of a step; returns this step's time (or None in warmup)."""
        _force(out)
        now = time.perf_counter()
        dt = None
        if self._last is not None:
            self._seen += 1
            if self._seen > self.warmup:
                dt = now - self._last
                self._times.append(dt)
        self._last = now
        return dt

    def tick_window(self, out, steps: int) -> float | None:
        """Record a window of ``steps`` steps ending now; appends the
        *per-step average* for the window. Used by the training loop, which
        only blocks on device output at log boundaries (blocking every step
        would serialize the async dispatch pipeline). The first window is
        dropped (contains compile)."""
        _force(out)
        now = time.perf_counter()
        dt = None
        if self._last is not None:
            self._seen += 1
            if self._seen > self.warmup:
                dt = (now - self._last) / max(steps, 1)
                self._times.extend([dt] * steps)
        self._last = now
        return dt

    def reset(self) -> None:
        self._times.clear()
        self._seen = 0
        self._last = None

    @property
    def count(self) -> int:
        return len(self._times)

    def summary(self, samples_per_step: int | None = None) -> dict:
        """Step-time percentiles (p50/p90/p95/p99) plus mean and
        samples/sec/chip if batch size given. Granularity follows the
        feed mode: ``tick()`` every step (benchmark harness) gives true
        per-step tails; ``tick_window()`` (training loop) records one
        averaged value per log window, so the tail is across *windows* —
        a straggler step inside a window is folded into that window's
        mean and only shows up if it moves the whole window."""
        if not self._times:
            return {"steps_timed": 0}
        arr = np.asarray(self._times)
        out = {
            "steps_timed": int(arr.size),
            "step_time_median_s": float(np.median(arr)),
            "step_time_p50_s": float(np.median(arr)),
            "step_time_p90_s": float(np.percentile(arr, 90)),
            "step_time_p95_s": float(np.percentile(arr, 95)),
            "step_time_p99_s": float(np.percentile(arr, 99)),
            "step_time_mean_s": float(arr.mean()),
            "steps_per_sec": float(1.0 / np.median(arr)),
        }
        if samples_per_step is not None:
            n_chips = jax.device_count()
            out["samples_per_sec"] = float(samples_per_step / np.median(arr))
            out["samples_per_sec_per_chip"] = out["samples_per_sec"] / n_chips
        return out
