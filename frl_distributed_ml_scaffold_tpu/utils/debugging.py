"""Sanitizers / race detection (SURVEY §5 "Race detection/sanitizers").

The reference's sanitizer tier (TSAN / compute-sanitizer over its native
deps) has no direct TPU equivalent because the failure class it hunts —
data races on shared mutable device memory — is removed by construction
here: JAX programs are pure functions over immutable arrays, and all
mutation (donation, double-buffering) is mediated by XLA with aliasing
checked at compile time. What remains detectable at runtime, this module
turns on:

- **NaN/Inf detection** (``jax_debug_nans`` / ``jax_debug_infs``): every
  primitive re-checked, failing with the offending op's traceback — the
  numerics analog of a sanitizer trap. Large overhead; debug runs only.
- **Tracer leak detection** (``jax_check_tracer_leaks``): catches escaped
  tracers from side-effecting closures — the JAX-specific "race" of
  captured stale state.
- **Donation/aliasing hygiene**: using a donated buffer raises by default;
  ``strict_donation()`` upgrades the *warning* on non-donatable layouts to
  an error so silent copies don't mask aliasing assumptions.
- **Deterministic replay**: disabling XLA autotuning-dependent fusion
  reordering isn't needed on TPU (deterministic by default — document this
  as the determinism story vs. CUDA's atomics nondeterminism).

Usage: ``with sanitize():`` around a suspect run, or ``sanitize_from_env()``
at process start honoring ``FRL_TPU_SANITIZE=nans,leaks``.
"""

from __future__ import annotations

import contextlib
import os
import warnings
from typing import Iterator

import jax

from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

_FLAG_MAP = {
    "nans": "jax_debug_nans",
    "infs": "jax_debug_infs",
    "leaks": "jax_check_tracer_leaks",
}


@contextlib.contextmanager
def sanitize(*checks: str) -> Iterator[None]:
    """Enable runtime sanitizers for the scope. Default: all of them.

    ``checks`` ⊆ {"nans", "infs", "leaks"}.
    """
    names = checks or tuple(_FLAG_MAP)
    saved = {}
    for name in names:
        flag = _FLAG_MAP[name]  # KeyError = typo'd sanitizer name, surface it
        saved[flag] = getattr(jax.config, flag)
        jax.config.update(flag, True)
    try:
        yield
    finally:
        for flag, old in saved.items():
            jax.config.update(flag, old)


def sanitize_from_env(var: str = "FRL_TPU_SANITIZE") -> bool:
    """Process-wide sanitizer enable from the environment (no scope exit).

    ``FRL_TPU_SANITIZE=1`` or ``=all`` turns everything on;
    ``FRL_TPU_SANITIZE=nans,leaks`` selects. Returns True if anything was
    enabled.
    """
    val = os.environ.get(var, "").strip().lower()
    if not val or val in ("0", "false"):
        return False
    names = tuple(_FLAG_MAP) if val in ("1", "true", "all") else tuple(
        n.strip() for n in val.split(",") if n.strip()
    )
    enabled = []
    for name in names:
        flag = _FLAG_MAP.get(name)
        if flag is None:
            # Env typos must not kill a multi-host launch — warn and skip.
            get_logger().warning(
                "%s: unknown sanitizer %r (valid: %s) — skipped",
                var, name, ", ".join(_FLAG_MAP),
            )
            continue
        jax.config.update(flag, True)
        enabled.append(name)
    if enabled:
        get_logger().info("sanitizers enabled: %s", ", ".join(enabled))
    return bool(enabled)


@contextlib.contextmanager
def strict_donation() -> Iterator[None]:
    """Escalate 'donated buffer could not be aliased' warnings to errors.

    A donation that silently falls back to a copy doubles the train state's
    HBM footprint — exactly the class of silent perf/memory hazard the
    sanitizer tier exists to surface.
    """
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "error", message=".*[Dd]onat.*", category=UserWarning
        )
        yield
