"""Logging + metric emission (SURVEY C18).

Design: metrics are accumulated *on device* inside the compiled step (the
trainer returns a small metrics pytree); the host only periodically
``device_get``s and writes them. Process-0 gating replaces the reference's
rank-0 gating. Output is both human stdout and machine JSONL — samples/sec/
chip and step time are first-class because they ARE the baseline metric
(BASELINE.md measurement protocol).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any, IO, Mapping

import jax

_LOGGERS: dict[str, logging.Logger] = {}


def is_primary_process() -> bool:
    """True on the process that should write logs (reference: rank 0).

    Deliberately never *initializes* a backend: a host-side code path that
    merely wants to log (the native data core loader, offline tools) would
    block forever on an unreachable TPU relay if this called
    ``jax.process_index()`` cold. Resolution order:

    1. the distributed runtime's process id (backend-free; set whenever
       ``jax.distributed.initialize`` ran — the launcher's multi-process
       path);
    2. ``jax.process_index()`` — but only when a backend already exists,
       so the call cannot trigger bring-up (covers multi-host stacks that
       know their rank from topology without explicit distributed init);
    3. primary — no distributed runtime and no backend means there is
       nobody else to defer to.

    Residual caveat: on path-3 hosts that later become non-primary, early
    log lines (before backend init) may appear on every host — cosmetic,
    and strictly better than the hang.
    """
    try:
        from jax._src import distributed

        pid = getattr(distributed.global_state, "process_id", None)
        if pid is not None:
            return pid == 0
    except Exception as e:  # private-API drift: fall through
        logging.getLogger(__name__).debug(
            "distributed-runtime process-id probe failed (%s)", e
        )
    try:
        from jax._src import xla_bridge

        if getattr(xla_bridge, "_backends", None):
            return jax.process_index() == 0
    except Exception as e:  # private-API drift: fall through
        logging.getLogger(__name__).debug(
            "backend process-index probe failed (%s)", e
        )
    return True


def get_logger(name: str = "frl_tpu") -> logging.Logger:
    """Process-0-gated stdout logger; non-primary processes log at ERROR."""
    if name in _LOGGERS:
        return _LOGGERS[name]
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s] %(message)s")
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO if is_primary_process() else logging.ERROR)
        logger.propagate = False
    _LOGGERS[name] = logger
    return logger


def _truncate_partial_line(path: str) -> None:
    """Crash-safety on reopen: a process killed mid-``write`` (OOM,
    SIGKILL, preemption without grace) leaves a torn final line, which
    poisons every later line-by-line reader of the file. Drop everything
    after the last newline BEFORE appending resumes — the torn record is
    unrecoverable either way; the file staying parseable is what
    matters."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return  # no file yet: nothing to repair
    if size == 0:
        return
    with open(path, "rb+") as fh:
        fh.seek(size - 1)
        if fh.read(1) == b"\n":
            return  # clean shutdown last time
        pos = size
        while pos > 0:
            step = min(65536, pos)
            fh.seek(pos - step)
            idx = fh.read(step).rfind(b"\n")
            if idx >= 0:
                fh.truncate(pos - step + idx + 1)
                return
            pos -= step
        fh.truncate(0)  # single torn line: the whole file is the tear


class JsonlWriter:
    """Append-only JSONL metric sink, primary-process only. Reopening an
    existing file first truncates any torn final line (crash-safety —
    see ``_truncate_partial_line``)."""

    def __init__(self, path: str | None):
        self._fh: IO[str] | None = None
        if path and is_primary_process():
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            _truncate_partial_line(path)
            self._fh = open(path, "a", buffering=1)

    def write(self, record: Mapping[str, Any]) -> None:
        if self._fh is not None:
            self._fh.write(json.dumps(record, default=_json_default) + "\n")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def _json_default(x: Any) -> Any:
    if hasattr(x, "item"):
        return x.item()
    if hasattr(x, "tolist"):
        return x.tolist()
    return str(x)


class TensorBoardWriter:
    """Optional TensorBoard scalar sink (``tf.summary``), primary-only.

    TensorFlow is imported lazily and failures downgrade to a warning —
    the sink is observability sugar on top of the JSONL record of truth,
    never a dependency of the training path. (jax.profiler traces already
    land in TensorBoard; this adds the scalar curves next to them.)
    """

    def __init__(self, logdir: str | None):
        self._writer = None
        self._tf = None
        if logdir and is_primary_process():
            try:
                import tensorflow as tf

                self._tf = tf
                self._writer = tf.summary.create_file_writer(logdir)
            except Exception as e:  # missing/broken TF: sink off, run on
                get_logger().warning("tensorboard sink disabled: %s", e)

    def write(self, step: int, record: Mapping[str, Any]) -> None:
        if self._writer is None:
            return
        with self._writer.as_default(step=int(step)):
            for k, v in record.items():
                if k != "step" and isinstance(v, (int, float)):
                    self._tf.summary.scalar(k, float(v))
        self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class MetricLogger:
    """Periodic metric emitter: stdout line + JSONL record.

    ``log(step, metrics, extra)`` converts device scalars to Python floats
    (one ``device_get`` for the whole dict) and writes both sinks.
    """

    def __init__(
        self,
        jsonl_path: str | None = None,
        name: str = "frl_tpu",
        tb_dir: str | None = None,
    ):
        self._logger = get_logger(name)
        self._jsonl = JsonlWriter(jsonl_path)
        self._tb = TensorBoardWriter(tb_dir)
        self._start = time.monotonic()

    def log(
        self,
        step: int,
        metrics: Mapping[str, Any],
        extra: Mapping[str, Any] | None = None,
    ) -> dict[str, Any]:
        host_metrics = jax.device_get(dict(metrics))
        record: dict[str, Any] = {
            "step": int(step),
            "wall_time_s": round(time.monotonic() - self._start, 3),
        }
        for k, v in host_metrics.items():
            try:
                record[k] = float(v)
            except (TypeError, ValueError):
                record[k] = v
        if extra:
            record.update(extra)
        parts = [f"step={record['step']}"]
        parts += [
            f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in record.items()
            if k not in ("step",)
        ]
        self._logger.info(" ".join(parts))
        self._jsonl.write(record)
        self._tb.write(record["step"], record)
        return record

    def close(self) -> None:
        self._jsonl.close()
        self._tb.close()
