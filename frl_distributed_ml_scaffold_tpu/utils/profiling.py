"""Tracing / profiling (SURVEY C19, §5 "Tracing/profiling").

The reference's torch.profiler+NVTX tier maps to three TPU-native tools:

- **Step-window traces**: ``WindowProfiler`` wraps a window of training
  steps in ``jax.profiler.start_trace``/``stop_trace``, producing a
  TensorBoard-loadable trace (XLA ops, fusion boundaries, ICI collectives,
  host dispatch) under ``<workdir>/<name>/trace/``. Configured via
  ``trainer.profile_start_step`` / ``trainer.profile_steps`` — zero-cost
  when disabled, no code changes to profile a run.
- **Host-loop annotations**: ``annotate("load_batch")`` wraps host-side
  phases in ``jax.profiler.TraceAnnotation`` so loader stalls are visible
  between device steps in the same trace.
- **HLO dumps**: ``launcher.launch.hlo_dump_flags(dir)`` (jax-free module —
  must be set in the environment before the backend initializes) makes XLA
  write optimized HLO per compilation for compile-time inspection (fusion
  decisions, layout choices).

Process-0 gating matches the logging tier: traces are only captured on the
primary process (each host profiles its own devices; one trace is what the
TensorBoard workflow wants).
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator

import jax

from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger, is_primary_process


class WindowProfiler:
    """Capture a ``jax.profiler`` trace for steps [start, start+steps).

    Call ``step_start(step)`` at the top of each loop iteration and
    ``stop()`` after the loop (covers runs shorter than the window). The
    window boundaries are host-side; the trace still contains the full
    async device timeline for those steps because dispatch happens inside
    the window.
    """

    def __init__(self, trace_dir: str, start_step: int, num_steps: int):
        self.trace_dir = trace_dir
        self.start_step = start_step
        self.num_steps = num_steps
        self._active = False
        self._done = num_steps <= 0 or not is_primary_process()

    @property
    def enabled(self) -> bool:
        return self.num_steps > 0

    def step_start(self, step: int) -> None:
        if self._done:
            return
        if not self._active and step >= self.start_step:
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
            self._active = True
            get_logger().info(
                "profiler: tracing steps %d..%d -> %s",
                step, step + self.num_steps - 1, self.trace_dir,
            )
        elif self._active and step >= self.start_step + self.num_steps:
            self.stop()

    def stop(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            get_logger().info("profiler: trace written to %s", self.trace_dir)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Host-loop phase annotation visible in the profiler timeline."""
    with jax.profiler.TraceAnnotation(name):
        yield


def annotate_step(step: int):
    """Named per-step annotation — groups a step's dispatch in the trace."""
    return jax.profiler.StepTraceAnnotation("train", step_num=step)


def device_memory_stats() -> dict[str, float]:
    """Per-device HBM usage in GiB (the ``torch.cuda.memory_summary``
    equivalent — SURVEY §5 observability). Empty when the backend exposes
    no stats (CPU simulation); never raises — observability must not be
    able to kill a run."""
    try:
        dev = jax.local_devices()[0]
        stats = dev.memory_stats() or {}
    except Exception:
        return {}
    gib = 1024**3
    out = {}
    if "bytes_in_use" in stats:
        out["hbm_in_use_gib"] = round(stats["bytes_in_use"] / gib, 3)
    if "peak_bytes_in_use" in stats:
        out["hbm_peak_gib"] = round(stats["peak_bytes_in_use"] / gib, 3)
    if "bytes_limit" in stats:
        out["hbm_limit_gib"] = round(stats["bytes_limit"] / gib, 3)
    return out
