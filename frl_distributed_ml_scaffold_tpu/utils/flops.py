"""Backend-free FLOP counting by walking a jaxpr (BASELINE.md protocol).

``jax.stages.Lowered.cost_analysis`` needs backend support the experimental
axon TPU plugin doesn't provide, so the bench harness would report no MFU on
the one platform where MFU matters. This counter needs no backend at all:
trace the train step to a jaxpr (abstract shapes only) and sum matmul/conv
FLOPs directly — the count covers everything the jaxpr actually contains,
forward AND backward AND optimizer, with no 3x-forward heuristics.

Convention: one multiply-add = 2 FLOPs (the MFU convention used by chip
peak numbers). Only ``dot_general`` and ``conv_general_dilated`` are
counted — elementwise/reduction FLOPs are noise next to them on any model
this framework benchmarks (they are also the ops the MXU peak refers to).

Control flow: ``scan``/``pjit``/``cond``/``remat`` bodies are descended
into (scan multiplied by trip count, cond by its worst branch);
``while_loop`` bodies are counted ONCE — trip counts are not static. The
ring-attention hop loop is the only hot while in this codebase, and ring
configs aren't single-chip bench candidates.
"""

from __future__ import annotations

from functools import reduce
from operator import mul

import jax
import numpy as np


def _prod(xs) -> int:
    return int(reduce(mul, xs, 1))


def _dot_flops(eqn) -> int:
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    dims = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dims
    batch = _prod(a.shape[i] for i in lb)
    contract = _prod(a.shape[i] for i in lc)
    m = _prod(a.shape[i] for i in range(a.ndim) if i not in set(lc) | set(lb))
    n = _prod(b.shape[i] for i in range(b.ndim) if i not in set(rc) | set(rb))
    return 2 * batch * m * n * contract


def _conv_flops(eqn) -> int:
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    out = eqn.outvars[0].aval
    dn = eqn.params["dimension_numbers"]
    # Output spatial positions x output channels x batch ...
    out_elems = _prod(out.shape)
    # ... each costs kernel_spatial x in_channels/groups MACs.
    k_spatial = _prod(rhs.shape[i] for i in dn.rhs_spec[2:])
    cin_per_group = rhs.shape[dn.rhs_spec[1]]
    return 2 * out_elems * k_spatial * cin_per_group


def jaxpr_flops(jaxpr) -> int:
    """Total matmul+conv FLOPs of a (closed) jaxpr, recursively."""
    if hasattr(jaxpr, "jaxpr"):  # ClosedJaxpr
        jaxpr = jaxpr.jaxpr
    total = 0
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "dot_general":
            total += _dot_flops(eqn)
        elif prim == "conv_general_dilated":
            total += _conv_flops(eqn)
        elif prim == "scan":
            total += eqn.params["length"] * jaxpr_flops(eqn.params["jaxpr"])
        elif prim == "while":
            # Trip count unknown statically; count one iteration of body
            # (+ cond) so the figure is a lower bound, not zero.
            total += jaxpr_flops(eqn.params["body_jaxpr"])
            total += jaxpr_flops(eqn.params["cond_jaxpr"])
        elif prim == "cond":
            total += max(
                (jaxpr_flops(b) for b in eqn.params["branches"]), default=0
            )
        elif prim == "pallas_call":
            # The jaxpr param is the PER-GRID-CELL kernel body: multiply by
            # the grid size or flash-attention FLOPs undercount by the whole
            # grid (B*H*Tq_blocks*Tk_blocks).
            grid = tuple(getattr(eqn.params["grid_mapping"], "grid", ()) or ())
            mult = (
                _prod(grid)
                if grid and all(isinstance(g, int) for g in grid)
                else 1  # dynamic grid dims: count one cell (lower bound)
            )
            total += mult * jaxpr_flops(eqn.params["jaxpr"])
        else:
            # pjit / remat / custom_vjp / shard_map wrappers all carry their
            # body under one of these params.
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                sub = eqn.params.get(key) if eqn.params else None
                if sub is not None:
                    total += jaxpr_flops(sub)
                    break
    return int(total)


def peak_flops_per_chip() -> float:
    """Per-chip peak FLOP/s for MFU denominators — v5e bf16 (197 TFLOP/s)
    by default, overridable via ``FRL_PEAK_FLOPS_PER_CHIP`` when the run
    lands on other silicon. On CPU sim the resulting MFU is a nominal
    tiny-but-positive placeholder (the serve_bench convention)."""
    import os

    return float(os.environ.get("FRL_PEAK_FLOPS_PER_CHIP", 197e12))


def fn_flops(fn, *example_args) -> int:
    """FLOPs of ``fn(*example_args)`` — traced abstractly, nothing runs."""
    shapes = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype)
        if hasattr(x, "dtype")
        else x,
        example_args,
    )
    return jaxpr_flops(jax.make_jaxpr(fn)(*shapes))
