"""Utility layer: pytree paths, logging, timing, profiling (SURVEY C18, C19)."""

from frl_distributed_ml_scaffold_tpu.utils.trees import (
    named_tree_map,
    tree_path_names,
    tree_size_bytes,
)
from frl_distributed_ml_scaffold_tpu.utils.logging import (
    JsonlWriter,
    MetricLogger,
    get_logger,
)
from frl_distributed_ml_scaffold_tpu.utils.timing import StepTimer
