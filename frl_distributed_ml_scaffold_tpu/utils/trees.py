"""Pytree path utilities.

The parallelism layer assigns shardings to parameters by *name* (regex rules
over ``"path/to/leaf"`` strings — SURVEY C4–C9), so a canonical flat naming of
any pytree is load-bearing infrastructure. Built on ``jax.tree_util`` key
paths so it works for dicts, dataclasses, optax states, and flax param trees
alike.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import numpy as np


def _key_entry_to_str(entry: Any) -> str:
    """Render one tree_util key entry as a path segment."""
    if isinstance(entry, jax.tree_util.DictKey):
        return str(entry.key)
    if isinstance(entry, jax.tree_util.SequenceKey):
        return str(entry.idx)
    if isinstance(entry, jax.tree_util.GetAttrKey):
        return str(entry.name)
    if isinstance(entry, jax.tree_util.FlattenedIndexKey):
        return str(entry.key)
    # Fallback: strip tree_util's decoration (e.g. "['a']" -> "a").
    return str(entry).strip("[]'\".")


def path_str(path: tuple, sep: str = "/") -> str:
    """Join a tree_util key path into a ``"a/b/c"`` string."""
    return sep.join(_key_entry_to_str(p) for p in path)


def named_tree_map(
    fn: Callable[[str, Any], Any], tree: Any, *rest: Any, sep: str = "/"
) -> Any:
    """``tree_map`` where ``fn`` receives ``(name, leaf, *rest_leaves)``.

    ``name`` is the slash-joined key path of the leaf. This is the primitive
    under regex-based partition-rule matching.
    """
    return jax.tree_util.tree_map_with_path(
        lambda p, x, *r: fn(path_str(p, sep), x, *r), tree, *rest
    )


def tree_path_names(tree: Any, sep: str = "/") -> list[str]:
    """Flat list of leaf path names, in tree_flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [path_str(p, sep) for p, _ in flat]


def tree_size_bytes(tree: Any) -> int:
    """Total bytes of all array leaves (params + opt state accounting)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
        elif hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            total += int(leaf.size) * np.dtype(leaf.dtype).itemsize
    return total


def tree_param_count(tree: Any) -> int:
    """Total element count of all array leaves."""
    return sum(
        int(np.prod(leaf.shape))
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "shape")
    )
