"""Elastic checkpoint-restart supervisor (SURVEY C14, call stack (d)).

The reference's torchrun elastic agent detects worker death, re-rendezvouses
the surviving/replacement nodes, and workers reload the last checkpoint. JAX
has no in-band elasticity — membership is fixed at
``jax.distributed.initialize`` — so the TPU-native design is deliberate
**checkpoint-restart elasticity** (SURVEY C14): a per-host supervisor runs
the training as a child process; when the child dies, the supervisor
restarts it (fresh ``initialize``, possibly over a different topology) and
the run resumes from the last Orbax checkpoint via the resharding restore
path (checkpoint/manager.py). On a multi-host pod each host runs its own
supervisor; the coordinator's supervisor restarting re-forms the cluster.

Fault injection (SURVEY §5) lives here too: ``FRL_FAULT_AT_STEP=N`` makes
the child hard-exit (``os._exit`` — no checkpoint flush, no atexit, the
moral equivalent of SIGKILL) after completing step N, exactly once per
workdir. The kill-and-resume test tier drives the supervisor through a real
crash → restart → resume cycle.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from frl_distributed_ml_scaffold_tpu.config.schema import ExperimentConfig
from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

#: Exit code the fault-injection hook dies with (distinguishable from real
#: python tracebacks' rc=1 in supervisor logs).
FAULT_EXIT_CODE = 43

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


# --------------------------------------------------------------------------
# Supervisor (parent side)
# --------------------------------------------------------------------------


def _child_command(args, topo: Optional[dict] = None) -> list[str]:
    """Re-exec the launcher without --elastic, checkpointing forced on.

    The forced overrides come last so they beat anything the user passed:
    a supervised run without checkpoint+resume would restart from step 0
    forever. ``topo`` (coordinator / num_processes / process_id) overrides
    the CLI topology after a shrink.
    """
    topo = topo or {}
    coordinator = topo.get("coordinator", args.coordinator)
    num_processes = topo.get("num_processes", args.num_processes)
    process_id = topo.get("process_id", args.process_id)
    cmd = [
        sys.executable,
        "-m",
        "frl_distributed_ml_scaffold_tpu.launcher.launch",
        "--config",
        args.config,
        "--device",
        args.device,
    ]
    if args.device == "cpu" and args.sim_devices:
        cmd += ["--sim-devices", str(args.sim_devices)]
    if coordinator:
        cmd += ["--coordinator", coordinator]
    if num_processes is not None:
        cmd += ["--num-processes", str(num_processes)]
    if process_id is not None:
        cmd += ["--process-id", str(process_id)]
    cmd += list(args.overrides)
    cmd += ["checkpoint.enabled=true", "checkpoint.resume=true"]
    return cmd


class _Membership:
    """Shared-workdir host membership for the shrink policy.

    The run's workdir is already the cross-host shared medium (Orbax
    checkpoints live there), so liveness rides the same channel: each
    host's supervisor heartbeats ``members/host_<uid>.json`` ({uid,
    endpoint, ts}) from a daemon thread; any supervisor can read the
    directory and declare peers whose heartbeat is older than
    ``peer_timeout_s`` dead. ``uid`` is the host's ORIGINAL process id —
    stable across shrinks (ranks are remapped per-topology, uids never).
    ``endpoint`` is the coordinator address this host would serve if it
    became rank 0 after a shrink (pre-allocated port, published so
    survivors re-elect deterministically: lowest surviving uid wins).
    No consensus protocol: every survivor computes the same answer from
    the same files, which is exactly the torchrun-agent re-rendezvous
    contract expressed over a shared filesystem instead of a TCP store.
    """

    def __init__(self, run_dir: str, uid: int, endpoint: str):
        self.dir = os.path.join(run_dir, "members")
        self.uid = uid
        self.endpoint = endpoint
        self.path = os.path.join(self.dir, f"host_{uid}.json")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(
                {"uid": self.uid, "endpoint": self.endpoint, "ts": time.time()},
                fh,
            )
        os.replace(tmp, self.path)  # atomic: readers never see a torn write

    def start(self, interval_s: float) -> None:
        self.beat()

        def loop() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.beat()
                except OSError as e:
                    # A transient shared-FS blip (NFS hiccup, ENOSPC) must
                    # not kill the thread for good: a silently dead
                    # heartbeat gets this healthy host shrunk OUT of the
                    # world by its peers. Log and retry next interval.
                    get_logger().warning(
                        "elastic: heartbeat write failed (%s); retrying", e
                    )

        self._thread = threading.Thread(
            target=loop, name="elastic-heartbeat", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def retire(self) -> None:
        """Clean-exit path: withdraw from membership so peers don't wait
        out the staleness window on a host that finished its work."""
        self.stop()
        try:
            os.remove(self.path)
        except OSError:
            pass

    def survivors(self, peer_timeout_s: float) -> list[dict]:
        """Hosts with a fresh heartbeat, sorted by uid (self always
        qualifies — the daemon thread is beating)."""
        now = time.time()
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            names = []
        for name in names:
            if not (name.startswith("host_") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.dir, name)) as fh:
                    rec = json.load(fh)
            except (OSError, ValueError):
                continue  # torn/just-deleted file: treat as absent this poll
            if now - rec.get("ts", 0) <= peer_timeout_s:
                out.append(rec)
        return sorted(out, key=lambda r: r["uid"])


def _own_endpoint(args) -> str:
    """The coordinator address this host would serve after taking rank 0.

    Host reachable-address resolution: ``FRL_TPU_HOST_ADDRESS`` env (tests
    and multi-NIC deployments), else the current coordinator's host when we
    already are rank 0, else this host's name. The port is freshly bound
    then released — standard pre-allocation racy-but-practical pattern.
    """
    host = os.environ.get("FRL_TPU_HOST_ADDRESS")
    if host is None:
        if args.process_id in (0, None) and args.coordinator:
            host = args.coordinator.rsplit(":", 1)[0]
        else:
            host = socket.gethostname()
    if args.process_id in (0, None) and args.coordinator:
        # Already the coordinator: keep serving the address peers know.
        return args.coordinator
    with socket.socket() as s:
        s.bind((host, 0))
        port = s.getsockname()[1]
    return f"{host}:{port}"


def supervise(args, cfg: ExperimentConfig) -> int:
    """Run the training child under restart supervision; returns final rc.

    Restart policy: up to ``cfg.elastic.max_restarts`` restarts with
    exponential backoff starting at ``cfg.elastic.backoff_s``. A clean child
    exit (rc 0) ends supervision; exhausting the budget returns the child's
    last rc.

    Shrink policy (``elastic.shrink_after > 0``, SURVEY C14 / call stack
    (d)): after that many consecutive failed restarts, read the membership
    heartbeats; if peers are dead (stale beyond ``elastic.peer_timeout_s``)
    and this host survives, re-launch the child over the surviving hosts —
    ranks remapped by surviving uid order, coordinator re-elected to the
    lowest surviving uid's published endpoint, restart budget refreshed for
    the new topology. The child's fresh ``initialize`` + Orbax resharding
    restore (checkpoint/manager.py) do the actual continuation; data
    sharding re-splits because per-host slicing keys off the new
    process_count. A host that comes back after a shrink fails its stale
    rendezvous and must be re-admitted by operator action — same contract
    as a torchrun agent that missed the re-rendezvous round.
    """
    logger = get_logger()
    env = os.environ.copy()
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")

    world = args.num_processes if args.num_processes is not None else 1
    uid = args.process_id
    topo: dict = {}
    membership: Optional[_Membership] = None
    if cfg.elastic.shrink_after > 0 and world > 1:
        if uid is None:
            # JAX-autodetected process ids (Cloud TPU metadata) are not
            # visible to the supervisor: every host would heartbeat the
            # same members/host_0.json and a cluster-wide child failure
            # would split-brain into N concurrent rank-0 worlds writing
            # one checkpoint dir. Shrink needs an explicit --process-id.
            logger.warning(
                "elastic: shrink_after=%d requires an explicit "
                "--process-id (autodetected ids are not visible to the "
                "supervisor); shrink policy DISABLED for this run",
                cfg.elastic.shrink_after,
            )
        else:
            membership = _Membership(
                os.path.join(cfg.workdir, cfg.name), uid, _own_endpoint(args)
            )
            membership.start(
                interval_s=max(0.5, cfg.elastic.peer_timeout_s / 4)
            )

    restarts = 0
    consecutive_failures = 0
    try:
        cmd = _child_command(args)
        logger.info("elastic: supervising %s", " ".join(cmd))
        while True:
            t0 = time.monotonic()
            rc = subprocess.call(cmd, cwd=_REPO_ROOT, env=env)
            elapsed = time.monotonic() - t0
            if rc == 0:
                logger.info(
                    "elastic: run completed after %d restart(s)", restarts
                )
                return 0
            if elapsed >= cfg.elastic.reset_after_s:
                restarts = 0  # the child made real progress; fresh budget
                consecutive_failures = 0
            consecutive_failures += 1

            if (
                membership is not None
                and world > 1
                and consecutive_failures >= cfg.elastic.shrink_after
            ):
                surv = membership.survivors(cfg.elastic.peer_timeout_s)
                uids = [r["uid"] for r in surv]
                if uid in uids and len(surv) < world:
                    new_world = len(surv)
                    new_rank = uids.index(uid)
                    new_coord = surv[0]["endpoint"] if new_world > 1 else None
                    logger.warning(
                        "elastic: shrinking from %d to %d processes "
                        "(dead peers stale > %.0fs); new rank=%d "
                        "coordinator=%s — resuming from last checkpoint "
                        "with resharding restore",
                        world,
                        new_world,
                        cfg.elastic.peer_timeout_s,
                        new_rank,
                        new_coord,
                    )
                    world = new_world
                    topo = {
                        "num_processes": new_world,
                        "process_id": new_rank,
                        "coordinator": new_coord,
                    }
                    cmd = _child_command(args, topo)
                    restarts = 0  # fresh budget for the new topology
                    consecutive_failures = 0
                    continue  # relaunch immediately — peers already waited

            if restarts >= cfg.elastic.max_restarts:
                logger.error(
                    "elastic: child rc=%d; restart budget (%d) exhausted — "
                    "giving up",
                    rc,
                    cfg.elastic.max_restarts,
                )
                return rc
            restarts += 1
            delay = cfg.elastic.backoff_s * (2 ** (restarts - 1))
            logger.warning(
                "elastic: child died rc=%d after %.1fs; restart %d/%d in "
                "%.1fs (resume from last checkpoint)",
                rc,
                elapsed,
                restarts,
                cfg.elastic.max_restarts,
                delay,
            )
            time.sleep(delay)
    finally:
        if membership is not None:
            membership.retire()


# --------------------------------------------------------------------------
# Fault injection (child side)
# --------------------------------------------------------------------------


def fault_hook_from_env(
    cfg: ExperimentConfig,
) -> Optional[Callable[[int, dict], None]]:
    """``on_step`` hook that hard-kills the process after a designated step.

    ``FRL_FAULT_AT_STEP=N`` → die after completing step N (0-indexed step
    N-1 in the loop, i.e. when ``step + 1 == N``). A marker file in the
    workdir makes the fault one-shot so the restarted child survives even
    when it resumes from a checkpoint before the fault step.
    """
    spec = os.environ.get("FRL_FAULT_AT_STEP")
    if not spec:
        return None
    fault_step = int(spec)
    marker = os.path.join(cfg.workdir, cfg.name, "fault_injected")
    if os.path.exists(marker):
        return None
    logger = get_logger()

    def hook(step: int, metrics: dict) -> None:
        if step + 1 == fault_step:
            os.makedirs(os.path.dirname(marker), exist_ok=True)
            with open(marker, "w") as fh:
                fh.write(str(fault_step))
            logger.warning(
                "fault injection: hard-exit(%d) after step %d",
                FAULT_EXIT_CODE,
                fault_step,
            )
            sys.stdout.flush()
            os._exit(FAULT_EXIT_CODE)

    return hook
