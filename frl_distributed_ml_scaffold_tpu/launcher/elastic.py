"""Elastic checkpoint-restart supervisor (SURVEY C14, call stack (d)).

The reference's torchrun elastic agent detects worker death, re-rendezvouses
the surviving/replacement nodes, and workers reload the last checkpoint. JAX
has no in-band elasticity — membership is fixed at
``jax.distributed.initialize`` — so the TPU-native design is deliberate
**checkpoint-restart elasticity** (SURVEY C14): a per-host supervisor runs
the training as a child process; when the child dies, the supervisor
restarts it (fresh ``initialize``, possibly over a different topology) and
the run resumes from the last Orbax checkpoint via the resharding restore
path (checkpoint/manager.py). On a multi-host pod each host runs its own
supervisor; the coordinator's supervisor restarting re-forms the cluster.

Fault injection (SURVEY §5) lives here too: ``FRL_FAULT_AT_STEP=N`` makes
the child hard-exit (``os._exit`` — no checkpoint flush, no atexit, the
moral equivalent of SIGKILL) after completing step N, exactly once per
workdir. The kill-and-resume test tier drives the supervisor through a real
crash → restart → resume cycle.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, Optional

from frl_distributed_ml_scaffold_tpu.config.schema import ExperimentConfig
from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

#: Exit code the fault-injection hook dies with (distinguishable from real
#: python tracebacks' rc=1 in supervisor logs).
FAULT_EXIT_CODE = 43

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


# --------------------------------------------------------------------------
# Supervisor (parent side)
# --------------------------------------------------------------------------


def _child_command(args) -> list[str]:
    """Re-exec the launcher without --elastic, checkpointing forced on.

    The forced overrides come last so they beat anything the user passed:
    a supervised run without checkpoint+resume would restart from step 0
    forever.
    """
    cmd = [
        sys.executable,
        "-m",
        "frl_distributed_ml_scaffold_tpu.launcher.launch",
        "--config",
        args.config,
        "--device",
        args.device,
    ]
    if args.device == "cpu" and args.sim_devices:
        cmd += ["--sim-devices", str(args.sim_devices)]
    if args.coordinator:
        cmd += ["--coordinator", args.coordinator]
    if args.num_processes is not None:
        cmd += ["--num-processes", str(args.num_processes)]
    if args.process_id is not None:
        cmd += ["--process-id", str(args.process_id)]
    cmd += list(args.overrides)
    cmd += ["checkpoint.enabled=true", "checkpoint.resume=true"]
    return cmd


def supervise(args, cfg: ExperimentConfig) -> int:
    """Run the training child under restart supervision; returns final rc.

    Restart policy: up to ``cfg.elastic.max_restarts`` restarts with
    exponential backoff starting at ``cfg.elastic.backoff_s``. A clean child
    exit (rc 0) ends supervision; exhausting the budget returns the child's
    last rc.
    """
    logger = get_logger()
    cmd = _child_command(args)
    env = os.environ.copy()
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    restarts = 0
    logger.info("elastic: supervising %s", " ".join(cmd))
    while True:
        t0 = time.monotonic()
        rc = subprocess.call(cmd, cwd=_REPO_ROOT, env=env)
        elapsed = time.monotonic() - t0
        if rc == 0:
            logger.info("elastic: run completed after %d restart(s)", restarts)
            return 0
        if elapsed >= cfg.elastic.reset_after_s:
            restarts = 0  # the child made real progress; fresh fault budget
        if restarts >= cfg.elastic.max_restarts:
            logger.error(
                "elastic: child rc=%d; restart budget (%d) exhausted — giving up",
                rc,
                cfg.elastic.max_restarts,
            )
            return rc
        restarts += 1
        delay = cfg.elastic.backoff_s * (2 ** (restarts - 1))
        logger.warning(
            "elastic: child died rc=%d after %.1fs; restart %d/%d in %.1fs "
            "(resume from last checkpoint)",
            rc,
            elapsed,
            restarts,
            cfg.elastic.max_restarts,
            delay,
        )
        time.sleep(delay)


# --------------------------------------------------------------------------
# Fault injection (child side)
# --------------------------------------------------------------------------


def fault_hook_from_env(
    cfg: ExperimentConfig,
) -> Optional[Callable[[int, dict], None]]:
    """``on_step`` hook that hard-kills the process after a designated step.

    ``FRL_FAULT_AT_STEP=N`` → die after completing step N (0-indexed step
    N-1 in the loop, i.e. when ``step + 1 == N``). A marker file in the
    workdir makes the fault one-shot so the restarted child survives even
    when it resumes from a checkpoint before the fault step.
    """
    spec = os.environ.get("FRL_FAULT_AT_STEP")
    if not spec:
        return None
    fault_step = int(spec)
    marker = os.path.join(cfg.workdir, cfg.name, "fault_injected")
    if os.path.exists(marker):
        return None
    logger = get_logger()

    def hook(step: int, metrics: dict) -> None:
        if step + 1 == fault_step:
            os.makedirs(os.path.dirname(marker), exist_ok=True)
            with open(marker, "w") as fh:
                fh.write(str(fault_step))
            logger.warning(
                "fault injection: hard-exit(%d) after step %d",
                FAULT_EXIT_CODE,
                fault_step,
            )
            sys.stdout.flush()
            os._exit(FAULT_EXIT_CODE)

    return hook
