"""Elastic checkpoint-restart supervisor (SURVEY C14, call stack (d)).

The reference's torchrun elastic agent detects worker death, re-rendezvouses
the surviving/replacement nodes, and workers reload the last checkpoint. JAX
has no in-band elasticity — membership is fixed at
``jax.distributed.initialize`` — so the TPU-native design is deliberate
**checkpoint-restart elasticity** (SURVEY C14): a per-host supervisor runs
the training as a child process; when the child dies, the supervisor
restarts it (fresh ``initialize``, possibly over a different topology) and
the run resumes from the last Orbax checkpoint via the resharding restore
path (checkpoint/manager.py). On a multi-host pod each host runs its own
supervisor; the coordinator's supervisor restarting re-forms the cluster.

Fault injection (SURVEY §5) lives here too: ``FRL_FAULT_AT_STEP=N`` makes
the child hard-exit (``os._exit`` — no checkpoint flush, no atexit, the
moral equivalent of SIGKILL) after completing step N, exactly once per
workdir. The kill-and-resume test tier drives the supervisor through a real
crash → restart → resume cycle.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from frl_distributed_ml_scaffold_tpu import faults
from frl_distributed_ml_scaffold_tpu.config.schema import ExperimentConfig
from frl_distributed_ml_scaffold_tpu.faults import RetryPolicy
from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

#: Exit code the fault-injection hook dies with (distinguishable from real
#: python tracebacks' rc=1 in supervisor logs).
FAULT_EXIT_CODE = 43

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


# --------------------------------------------------------------------------
# Supervisor (parent side)
# --------------------------------------------------------------------------


def _child_command(args, topo: Optional[dict] = None) -> list[str]:
    """Re-exec the launcher without --elastic, checkpointing forced on.

    The forced overrides come last so they beat anything the user passed:
    a supervised run without checkpoint+resume would restart from step 0
    forever. ``topo`` (coordinator / num_processes / process_id) overrides
    the CLI topology after a shrink.
    """
    topo = topo or {}
    coordinator = topo.get("coordinator", args.coordinator)
    num_processes = topo.get("num_processes", args.num_processes)
    process_id = topo.get("process_id", args.process_id)
    cmd = [
        sys.executable,
        "-m",
        "frl_distributed_ml_scaffold_tpu.launcher.launch",
        "--config",
        args.config,
        "--device",
        args.device,
    ]
    if args.device == "cpu" and args.sim_devices:
        cmd += ["--sim-devices", str(args.sim_devices)]
    if coordinator:
        cmd += ["--coordinator", coordinator]
    if num_processes is not None:
        cmd += ["--num-processes", str(num_processes)]
    if process_id is not None:
        cmd += ["--process-id", str(process_id)]
    cmd += list(args.overrides)
    cmd += ["checkpoint.enabled=true", "checkpoint.resume=true"]
    return cmd


class _Membership:
    """Shared-workdir host membership for the shrink policy.

    The run's workdir is already the cross-host shared medium (Orbax
    checkpoints live there), so liveness rides the same channel: each
    host's supervisor heartbeats ``members/host_<uid>.json`` ({uid,
    endpoint, ts}) from a daemon thread; any supervisor can read the
    directory and declare peers whose heartbeat is older than
    ``peer_timeout_s`` dead. ``uid`` is the host's ORIGINAL process id —
    stable across shrinks (ranks are remapped per-topology, uids never).
    ``endpoint`` is the coordinator address this host would serve if it
    became rank 0 after a shrink (pre-allocated port, published so
    survivors re-elect deterministically: lowest surviving uid wins).
    No consensus protocol — but also no synchronized decision: each
    survivor polls independently, so two supervisors straddling the
    staleness boundary can transiently compute different survivor sets.
    ``supervise`` therefore commits a shrink only after two consistent
    reads separated by a heartbeat interval (see the settle logic there);
    that narrows, not closes, the window — same contract as a torchrun
    agent round that a slow host can still miss.

    Staleness is judged in the SHARED FILESYSTEM's clock domain, not the
    hosts': a peer is stale when our own heartbeat file's ``st_mtime``
    (freshly beaten) exceeds the peer's by ``peer_timeout_s``. Both
    mtimes are stamped by the same FS server at ``os.replace`` time, so
    cross-host wall-clock skew — which could otherwise make a skewed
    supervisor declare every live peer dead and split-brain the
    checkpoint dir — cancels out. The embedded ``ts`` stays in the JSON
    for humans/debugging only.
    """

    def __init__(self, run_dir: str, uid: int, endpoint: str, registry=None):
        self.dir = os.path.join(run_dir, "members")
        self.uid = uid
        self.endpoint = endpoint
        self.path = os.path.join(self.dir, f"host_{uid}.json")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Both the daemon heartbeat thread and survivors() (supervise
        # thread) call beat(); serialise them so the shared tmp file can't
        # interleave two writers and publish torn JSON.
        self._beat_lock = threading.Lock()
        # Telemetry (ISSUE 7): the oldest heartbeat age observed at the
        # last liveness read — the scrape-able early warning that a peer
        # is drifting toward the peer_timeout_s eviction line.
        self._m_hb_age = (
            registry.gauge(
                "elastic_heartbeat_age_s",
                help="oldest live member heartbeat age at the last "
                     "liveness read (evicted peers excluded)",
            )
            if registry is not None
            else None
        )
        # ISSUE 9: failed heartbeat writes used to log-and-retry silently
        # forever; now they are counted, and after N consecutive failures
        # the record is retired (see start()) so peers evict this host
        # deterministically instead of racing the staleness window.
        self._m_hb_failures = (
            registry.counter(
                "heartbeat_write_failures_total",
                help="membership heartbeat writes that raised (shared-FS "
                     "outage); consecutive failures retire the record",
            )
            if registry is not None
            else None
        )

    def beat(self) -> None:
        with self._beat_lock:
            if self._stop.is_set():
                # retire() may have already unlinked the file; a straggler
                # beat (e.g. a grow watcher blocked in survivors() past
                # its join timeout) must not resurrect a heartbeat for a
                # departed host — peers would count it alive for a full
                # peer_timeout_s and could preempt healthy children over
                # it.
                return
            faults.maybe_raise("elastic.heartbeat_write", OSError)
            os.makedirs(self.dir, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(
                    {
                        "uid": self.uid,
                        "endpoint": self.endpoint,
                        "ts": time.time(),
                    },
                    fh,
                )
            os.replace(tmp, self.path)  # atomic: no torn reads

    def start(self, interval_s: float, retire_after: int = 10) -> None:
        """Start the heartbeat thread. A transient shared-FS blip (NFS
        hiccup, ENOSPC) must not kill the thread for good — a silently
        dead heartbeat gets this healthy host shrunk OUT of the world by
        its peers — so failures are logged, COUNTED
        (``heartbeat_write_failures_total``), and retried next interval.
        But ``retire_after`` (``elastic.heartbeat_retire_after``)
        CONSECUTIVE failures mean the FS is gone for this host, not
        blinking: the record is retired (unlinked, best-effort) so peers
        evict it deterministically — absent reads as departed, exactly
        like the clean ``retire()`` path — instead of every peer racing
        the mtime staleness window at a slightly different moment
        (ISSUE 9). The INITIAL beat still raises to the caller: at
        startup there is no healthy history to protect, so an unwritable
        membership dir is a misconfiguration that must crash the
        supervisor loudly, not degrade into a silent peer-side
        eviction."""
        self.beat()

        def loop() -> None:
            failures = 0
            while not self._stop.wait(interval_s):
                try:
                    self.beat()
                    failures = 0
                except OSError as e:
                    failures += 1
                    if self._m_hb_failures is not None:
                        self._m_hb_failures.inc()
                    get_logger().warning(
                        "elastic: heartbeat write failed (%s); %d/%s "
                        "consecutive", e, failures,
                        retire_after if retire_after else "inf",
                    )
                    if retire_after and failures >= retire_after:
                        get_logger().error(
                            "elastic: %d consecutive heartbeat-write "
                            "failures — retiring membership record for "
                            "uid %d so peers evict deterministically",
                            failures, self.uid,
                        )
                        self._stop.set()
                        with self._beat_lock:
                            try:
                                os.remove(self.path)
                            except OSError as rm_err:
                                get_logger().warning(
                                    "elastic: could not unlink retired "
                                    "heartbeat (%s); peers will fall back "
                                    "to the staleness window", rm_err,
                                )
                        return

        self._thread = threading.Thread(
            target=loop, name="elastic-heartbeat", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def retire(self) -> None:
        """Clean-exit path: withdraw from membership so peers don't wait
        out the staleness window on a host that finished its work."""
        self.stop()
        # Unlink under the beat lock: any in-flight beat() finishes first,
        # and every later one no-ops on the _stop check — the removal is
        # final.
        with self._beat_lock:
            try:
                os.remove(self.path)
            except OSError:
                pass

    def survivors(self, peer_timeout_s: float) -> Optional[list[dict]]:
        """Hosts with a fresh heartbeat, sorted by uid (self always
        qualifies — we beat right here before judging anyone).

        Returns ``None`` when liveness CANNOT be judged this poll (no
        FS-clock reference, or a peer's heartbeat file errored on read):
        a partial shared-FS outage must defer the shrink decision
        entirely, not silently drop live peers into the "dead" set and
        split-brain the checkpoint dir.
        """
        # Re-beat so our own file's st_mtime is "now" in the FS clock
        # domain; every peer mtime is then compared against it (see class
        # docstring — never against local time.time()).
        try:
            self.beat()
            now = os.stat(self.path).st_mtime
        except OSError as e:
            get_logger().warning(
                "elastic: cannot stat own heartbeat (%s); "
                "deferring liveness judgment this poll", e
            )
            return None
        out = []
        max_age = 0.0
        try:
            names = os.listdir(self.dir)
        except OSError as e:
            get_logger().warning(
                "elastic: cannot list members dir (%s); deferring", e
            )
            return None
        for name in names:
            if not (name.startswith("host_") and name.endswith(".json")):
                continue
            path = os.path.join(self.dir, name)
            try:
                mtime = os.stat(path).st_mtime
            except FileNotFoundError:
                continue  # just-deleted (clean retire): absent is correct
            except OSError as e:
                # EIO and friends: WE can't read, which says nothing
                # about the peer — defer, same policy as the unreadable-
                # fresh-heartbeat branch below.
                get_logger().warning(
                    "elastic: cannot stat %s (%s); deferring liveness "
                    "judgment this poll", name, e
                )
                return None
            if now - mtime > peer_timeout_s:
                continue  # genuinely stale: dead
            # Gauge folds LIVE members only: a hard-crashed peer's file is
            # never unlinked (only clean retire() does that), and its
            # ever-growing age would saturate the gauge forever, masking
            # the live-member lag this metric exists to warn about.
            max_age = max(max_age, now - mtime)
            try:
                with open(path) as fh:
                    rec = json.load(fh)
            except (OSError, ValueError) as e:
                # A FRESH heartbeat we cannot read is an US problem
                # (EIO, torn write), not evidence of a dead peer — refuse
                # to judge rather than shrink a live host out.
                get_logger().warning(
                    "elastic: fresh heartbeat %s unreadable (%s); "
                    "deferring liveness judgment this poll", name, e
                )
                return None
            out.append(rec)
        if self._m_hb_age is not None:
            self._m_hb_age.set(max_age)
        return sorted(out, key=lambda r: r["uid"])


def _own_endpoint(args) -> tuple[str, Optional[socket.socket]]:
    """The coordinator address this host would serve after taking rank 0.

    Host reachable-address resolution: ``FRL_TPU_HOST_ADDRESS`` env (tests
    and multi-NIC deployments), else the current coordinator's host when we
    already are rank 0, else this host's name. Returns ``(endpoint,
    held_socket)``: the pre-allocated port's socket stays OPEN (bound, not
    listening) so nothing else on this host can take it during the
    possibly-hours between startup and a shrink electing us rank 0;
    ``supervise`` closes it immediately before launching the child that
    will actually serve the coordinator there. The race window is thus the
    few ms of child exec, not the supervisor's whole lifetime.
    """
    host = os.environ.get("FRL_TPU_HOST_ADDRESS")
    if host is None:
        if args.process_id in (0, None) and args.coordinator:
            host = args.coordinator.rsplit(":", 1)[0]
        else:
            host = socket.gethostname()
    if args.process_id in (0, None) and args.coordinator:
        # Already the coordinator: keep serving the address peers know
        # (that port is the live child's to bind, not ours to hold).
        return args.coordinator, None
    s = socket.socket()
    try:
        s.bind((host, 0))
    except OSError:
        s.close()  # don't leak the fd on unresolvable host / bind failure
        raise
    port = s.getsockname()[1]
    return f"{host}:{port}", s


def supervise(args, cfg: ExperimentConfig) -> int:
    """Run the training child under restart supervision; returns final rc.

    Restart policy: up to ``cfg.elastic.max_restarts`` restarts with
    exponential backoff starting at ``cfg.elastic.backoff_s``. A clean child
    exit (rc 0) ends supervision; exhausting the budget returns the child's
    last rc.

    Shrink policy (``elastic.shrink_after > 0``, SURVEY C14 / call stack
    (d)): after that many consecutive failed restarts, read the membership
    heartbeats; if peers are dead (stale beyond ``elastic.peer_timeout_s``)
    and this host survives, re-launch the child over the surviving hosts —
    ranks remapped by surviving uid order, coordinator re-elected to the
    lowest surviving uid's published endpoint, restart budget refreshed for
    the new topology. The child's fresh ``initialize`` + Orbax resharding
    restore (checkpoint/manager.py) do the actual continuation; data
    sharding re-splits because per-host slicing keys off the new
    process_count.

    Grow-back (``elastic.grow``, on by default): after a shrink, a watcher
    thread keeps reading the membership heartbeats while the child runs;
    when an evicted host resumes beating (repaired, or a false-positive
    eviction) for two consecutive polls, the watcher SIGTERMs the child —
    which checkpoints and exits cleanly via the preemption path
    (trainer/loop.py) — and the supervisor re-forms at the larger world
    over the settled survivor set. The revived host needs no special
    action: its own supervisor keeps relaunching the original topology,
    whose rendezvous starts succeeding the moment the re-formed world
    includes it.
    """
    logger = get_logger()
    env = os.environ.copy()
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")

    # Supervisor telemetry (ISSUE 7): restart/shrink/grow counters + the
    # membership heartbeat-age gauge, published as a Prometheus sidecar
    # next to the child's run artifacts on every supervision event — the
    # fleet-level "is this host crash-looping / shrunk" signal.
    from frl_distributed_ml_scaffold_tpu.telemetry import (
        MetricsRegistry,
        Tracer,
        write_prometheus_file,
    )

    telem = MetricsRegistry()
    # Supervisor tracing (ISSUE 8): one lane per supervision session —
    # child_run / restart_wait / reform spans, exported as Chrome-trace
    # JSON next to the .prom sidecar, so an incident (crash → backoff →
    # shrink → grow-back) reads as ONE trace instead of interleaved log
    # lines. No profiler annotations: this process owns no devices.
    tracer = Tracer(enabled=True)
    sup_trace = tracer.new_trace(f"supervisor {args.process_id or 0}")
    sup_span = tracer.begin(
        "supervise", trace=sup_trace, cat="elastic",
        uid=args.process_id, config=args.config,
    )
    m_restarts = telem.counter(
        "elastic_restarts_total", help="child restarts under supervision"
    )
    m_reforms = telem.counter(
        "elastic_membership_changes_total",
        help="committed topology re-formations (shrinks + grows)",
    )
    m_shrinks = telem.counter("elastic_shrinks_total")
    m_grows = telem.counter("elastic_grows_total")
    m_world = telem.gauge("elastic_world_size")
    run_dir_t = os.path.join(cfg.workdir, cfg.name)

    def export_telemetry() -> None:
        try:
            os.makedirs(run_dir_t, exist_ok=True)
            write_prometheus_file(
                telem, os.path.join(run_dir_t, f"supervisor_{uid or 0}.prom")
            )
            tracer.write_chrome_trace(
                os.path.join(run_dir_t, f"supervisor_{uid or 0}_trace.json")
            )
        except OSError as e:  # shared-FS blip: telemetry never kills a run
            logger.warning("elastic: telemetry export failed (%s)", e)

    world = args.num_processes if args.num_processes is not None else 1
    uid = args.process_id
    topo: dict = {}
    membership: Optional[_Membership] = None
    held_port: Optional[socket.socket] = None
    # One formula, used by the daemon beat rate AND the settle/watch
    # windows — the "two reads one heartbeat interval apart" argument
    # depends on them staying equal.
    heartbeat_interval = max(0.5, cfg.elastic.peer_timeout_s / 4)
    if cfg.elastic.shrink_after > 0 and world > 1:
        if uid is None:
            # JAX-autodetected process ids (Cloud TPU metadata) are not
            # visible to the supervisor: every host would heartbeat the
            # same members/host_0.json and a cluster-wide child failure
            # would split-brain into N concurrent rank-0 worlds writing
            # one checkpoint dir. Shrink needs an explicit --process-id.
            logger.warning(
                "elastic: shrink_after=%d requires an explicit "
                "--process-id (autodetected ids are not visible to the "
                "supervisor); shrink policy DISABLED for this run",
                cfg.elastic.shrink_after,
            )
        else:
            endpoint, held_port = _own_endpoint(args)
            membership = _Membership(
                os.path.join(cfg.workdir, cfg.name), uid, endpoint,
                registry=telem,
            )
            membership.start(
                interval_s=heartbeat_interval,
                retire_after=cfg.elastic.heartbeat_retire_after,
            )

    initial_world = world
    m_world.set(world)
    restart_retry = RetryPolicy(
        max_retries=cfg.elastic.max_restarts,
        backoff_s=cfg.elastic.backoff_s,
        max_backoff_s=cfg.elastic.max_backoff_s,
    )
    restarts = 0
    consecutive_failures = 0
    #: Budget-free restarts granted after a grow commit: a partially
    #: repaired cluster (some of the dead hosts back) re-forms in stages —
    #: the revived host must shrink its ORIGINAL topology down to the
    #: committed one via its own shrink logic, which costs it
    #: shrink_after failed rendezvous first. The survivors' rendezvous
    #: failures during that window are self-inflicted by the grow, not
    #: child faults, and must not burn the real restart budget (else a
    #: healthy shrunken run can die because a repair showed up).
    grow_grace = 0

    def settled_survivors() -> Optional[list[dict]]:
        """Two identical survivor reads one heartbeat interval apart, or
        None: supervisors poll unsynchronized, so one read taken at the
        staleness boundary can disagree with a peer's — never commit a
        topology change off a single poll."""
        surv = membership.survivors(cfg.elastic.peer_timeout_s)
        time.sleep(heartbeat_interval)
        surv2 = membership.survivors(cfg.elastic.peer_timeout_s)
        if surv is None or surv2 is None:
            logger.warning(
                "elastic: liveness unjudgeable (shared-FS error); "
                "deferring topology decision"
            )
            return None
        if [r["uid"] for r in surv] != [r["uid"] for r in surv2]:
            logger.warning(
                "elastic: survivor set unsettled (%s vs %s); deferring "
                "topology decision",
                [r["uid"] for r in surv],
                [r["uid"] for r in surv2],
            )
            return None
        return surv

    def commit_reform(surv: list[dict], reason: str) -> None:
        """Adopt the settled survivor set as the new world (shrink or
        grow): ranks remapped by uid order, coordinator re-elected to the
        lowest surviving uid's published endpoint, budgets refreshed."""
        nonlocal world, topo, cmd, restarts, consecutive_failures, \
            held_port, grow_grace
        uids = [r["uid"] for r in surv]
        new_world = len(surv)
        new_rank = uids.index(uid)
        new_coord = surv[0]["endpoint"] if new_world > 1 else None
        logger.warning(
            "elastic: %s from %d to %d processes; new rank=%d "
            "coordinator=%s — resuming from last checkpoint with "
            "resharding restore",
            reason, world, new_world, new_rank, new_coord,
        )
        # Membership change as a span: the committed re-formation moment,
        # in the same trace as the child runs it separates.
        tracer.emit(
            "reform", t0=time.perf_counter(), dur_s=0.0,
            trace=sup_trace, parent=sup_span, cat="elastic",
            reason=reason, frm=world, to=new_world, rank=new_rank,
        )
        world = new_world
        m_reforms.inc()
        (m_grows if reason == "growing" else m_shrinks).inc()
        m_world.set(new_world)
        export_telemetry()
        topo = {
            "num_processes": new_world,
            "process_id": new_rank,
            "coordinator": new_coord,
        }
        if new_rank == 0 and new_world > 1 and held_port is not None:
            # The child will bind the coordinator port we've been holding
            # since startup; release it only now (race window = child
            # exec, not supervisor life).
            held_port.close()
            held_port = None
        cmd = _child_command(args, topo)
        # A reformed world restores a checkpoint written on a DIFFERENT
        # topology: switch the child onto the redistribution restore
        # path (ISSUE 15 — even-layout read + on-device plan execution,
        # no replicated staging) instead of the fixed-layout Orbax read.
        # Appended after _child_command's forced overrides so it wins.
        cmd += ["checkpoint.restore_redistribute=true"]
        restarts = 0
        consecutive_failures = 0
        grow_grace = 3 if reason == "growing" else 0

    def grow_watch(proc: subprocess.Popen, stop: threading.Event,
                   grow_req: threading.Event) -> None:
        """Post-shrink watcher: when the settled survivor set outgrows the
        current world (an evicted host resumed beating), preempt the child
        (SIGTERM -> checkpoint -> clean exit) so the main loop can re-form
        at the larger world."""
        consecutive = 0
        while not stop.wait(heartbeat_interval):
            surv = membership.survivors(cfg.elastic.peer_timeout_s)
            if (
                surv is not None
                and uid in [r["uid"] for r in surv]
                and world < len(surv) <= initial_world
            ):
                consecutive += 1
                if consecutive >= 2:
                    logger.warning(
                        "elastic: evicted peer(s) heartbeating again "
                        "(%d survivors > world %d); preempting child to "
                        "re-form at the larger world",
                        len(surv), world,
                    )
                    grow_req.set()
                    proc.terminate()
                    return
            else:
                consecutive = 0

    try:
        cmd = _child_command(args)
        logger.info("elastic: supervising %s", " ".join(cmd))
        while True:
            t0 = time.monotonic()
            t0_span = time.perf_counter()
            proc = subprocess.Popen(cmd, cwd=_REPO_ROOT, env=env)
            grow_req = threading.Event()
            stop_watch = threading.Event()
            watcher: Optional[threading.Thread] = None
            if (
                membership is not None
                and cfg.elastic.grow
                and world < initial_world
            ):
                watcher = threading.Thread(
                    target=grow_watch,
                    args=(proc, stop_watch, grow_req),
                    name="elastic-grow-watch",
                    daemon=True,
                )
                watcher.start()
            rc = proc.wait()
            stop_watch.set()
            if watcher is not None:
                watcher.join(timeout=5)
            elapsed = time.monotonic() - t0
            tracer.emit(
                "child_run", t0=t0_span, dur_s=elapsed,
                trace=sup_trace, parent=sup_span, cat="elastic",
                rc=rc, world=world, restarts=restarts,
            )

            if grow_req.is_set():
                surv = settled_survivors()
                if (
                    surv is not None
                    and uid in [r["uid"] for r in surv]
                    and world < len(surv) <= initial_world
                ):
                    commit_reform(surv, "growing")
                    continue
                # Fizzled grow. Budget-free relaunch ONLY when the exit
                # really was our preemption — clean exit via the
                # preemption path (rc 0) or killed by our SIGTERM
                # mid-init (rc -15). A child that died of a genuine
                # fault (rc 1, OOM, ...) in the same interval the
                # watcher fired must fall through to normal failure
                # accounting, or a crash-looping child + flapping peer
                # relaunches forever with no backoff and no budget.
                if rc == 0 or rc == -signal.SIGTERM:
                    logger.warning(
                        "elastic: grow fizzled (peer gone again?); "
                        "continuing at world=%d", world
                    )
                    continue
                logger.warning(
                    "elastic: grow fizzled AND child died on its own "
                    "(rc=%d); counting the failure", rc
                )

            if rc == 0:
                logger.info(
                    "elastic: run completed after %d restart(s)", restarts
                )
                return 0
            if elapsed >= cfg.elastic.reset_after_s:
                restarts = 0  # the child made real progress; fresh budget
                consecutive_failures = 0
            consecutive_failures += 1

            if (
                membership is not None
                and world > 1
                and consecutive_failures >= cfg.elastic.shrink_after
            ):
                surv = settled_survivors()
                if (
                    surv is not None
                    and uid in [r["uid"] for r in surv]
                    and len(surv) < world
                ):
                    logger.warning(
                        "elastic: dead peers stale > %.0fs",
                        cfg.elastic.peer_timeout_s,
                    )
                    commit_reform(surv, "shrinking")
                    continue  # relaunch immediately — peers already waited

            if grow_grace > 0:
                grow_grace -= 1
                logger.warning(
                    "elastic: child rc=%d during grow re-formation; "
                    "budget-free retry (%d grace left)", rc, grow_grace
                )
                time.sleep(cfg.elastic.backoff_s)
                continue

            if restarts >= cfg.elastic.max_restarts:
                logger.error(
                    "elastic: child rc=%d; restart budget (%d) exhausted — "
                    "giving up",
                    rc,
                    cfg.elastic.max_restarts,
                )
                return rc
            restarts += 1
            m_restarts.inc()
            export_telemetry()
            # The unified retry policy (faults/retry.py, ISSUE 9):
            # exponential from elastic.backoff_s, capped at
            # elastic.max_backoff_s, budgeted by elastic.max_restarts.
            delay = restart_retry.delay(restarts)
            logger.warning(
                "elastic: child died rc=%d after %.1fs; restart %d/%d in "
                "%.1fs (resume from last checkpoint)",
                rc,
                elapsed,
                restarts,
                cfg.elastic.max_restarts,
                delay,
            )
            t_wait = time.perf_counter()
            time.sleep(delay)
            tracer.emit(
                "restart_wait", t0=t_wait,
                dur_s=time.perf_counter() - t_wait,
                trace=sup_trace, parent=sup_span, cat="elastic",
                restart=restarts, rc=rc,
            )
    finally:
        if held_port is not None:
            held_port.close()
        if membership is not None:
            membership.retire()
        sup_span.end(world=world)
        export_telemetry()


# --------------------------------------------------------------------------
# Fault injection (child side)
# --------------------------------------------------------------------------


def fault_hook_from_env(
    cfg: ExperimentConfig,
) -> Optional[Callable[[int, dict], None]]:
    """``on_step`` hook that kills the process after a designated step.

    ``FRL_FAULT_AT_STEP=N`` → die after completing step N (0-indexed step
    N-1 in the loop, i.e. when ``step + 1 == N``). The kill shape is
    ``FRL_FAULT_SIGNAL``: unset/``KILL`` → ``os._exit`` (no checkpoint
    flush, no atexit — the SIGKILL moral equivalent, driving the
    supervisor's restart-from-last-checkpoint path); ``TERM`` → SIGTERM
    to ourselves (a TPU maintenance preemption — the trainer's graceful
    handler finishes the step, checkpoints, exits rc 0). A marker file in
    the workdir makes the fault one-shot so the restarted child survives
    even when it resumes from a checkpoint before the fault step.

    The in-process fault sites (``faults/plan.py`` ``trainer.*``/
    ``serve.*``/... sites) are the test/chaos-bench surface; this env
    hook is the CROSS-PROCESS one the supervised-child drills need —
    occurrence counters reset per process, the workdir marker does not.
    """
    delay_s = float(os.environ.get("FRL_STEP_DELAY_S", "0") or 0)
    spec = os.environ.get("FRL_FAULT_AT_STEP")
    fault_step = int(spec) if spec else 0
    fault_signal = (os.environ.get("FRL_FAULT_SIGNAL") or "KILL").upper()
    if fault_signal not in ("KILL", "TERM"):
        raise ValueError(
            f"FRL_FAULT_SIGNAL={fault_signal!r}: want KILL (hard exit) "
            "or TERM (graceful preemption)"
        )
    marker = os.path.join(cfg.workdir, cfg.name, "fault_injected")
    if fault_step and os.path.exists(marker):
        fault_step = 0
    if not fault_step and not delay_s:
        return None
    logger = get_logger()

    def hook(step: int, metrics: dict) -> None:
        if delay_s:
            # Chaos/elasticity drills: stretch wall-clock per step so
            # supervisor-side events (peer revival, preemption) can land
            # while the child is mid-run. Synthetic steps are sub-ms;
            # without this the run is over before any drill fires.
            time.sleep(delay_s)
        if fault_step and step + 1 == fault_step:
            os.makedirs(os.path.dirname(marker), exist_ok=True)
            with open(marker, "w") as fh:
                fh.write(str(fault_step))
            if fault_signal == "TERM":
                logger.warning(
                    "fault injection: SIGTERM self-preemption after "
                    "step %d (graceful checkpoint-and-exit path)",
                    fault_step,
                )
                sys.stdout.flush()
                os.kill(os.getpid(), signal.SIGTERM)
                return
            logger.warning(
                "fault injection: hard-exit(%d) after step %d",
                FAULT_EXIT_CODE,
                fault_step,
            )
            sys.stdout.flush()
            os._exit(FAULT_EXIT_CODE)

    return hook
