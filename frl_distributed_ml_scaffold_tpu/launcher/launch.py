"""``launch.py`` — the torchrun-equivalent entrypoint (SURVEY C1).

Usage::

    python launch.py --config=mnist_mlp [--device=tpu|cpu] [--sim-devices=N]
                     [--list-configs] [--elastic] [path.to.field=value ...]

Reference stack (a): torchrun forks N workers, each joins an NCCL process
group. Here: one process per host; ``--device=tpu`` brings up the pod slice
via ``initialize_distributed`` (autodetected on Cloud TPU, FRL_TPU_* env
overrides for manual clusters); ``--device=cpu --sim-devices=8`` gives the
simulated multi-chip CPU mesh used by the test tier (SURVEY C20).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _parse_args(argv=None):
    p = argparse.ArgumentParser(description="FRL-TPU scaffold launcher")
    p.add_argument("--config", help="registered config name (see --list-configs)")
    p.add_argument("--device", default="tpu", choices=["tpu", "cpu"])
    p.add_argument(
        "--sim-devices",
        type=int,
        default=0,
        help="with --device=cpu: number of simulated devices",
    )
    p.add_argument("--list-configs", action="store_true")
    p.add_argument(
        "--elastic",
        action="store_true",
        help="run under the elastic checkpoint-restart supervisor (SURVEY C14)",
    )
    p.add_argument(
        "--eval-only",
        action="store_true",
        help="restore the latest checkpoint and run the eval loop only",
    )
    p.add_argument(
        "--describe",
        action="store_true",
        help="print resolved config, mesh, parameter shardings, FLOPs and "
        "pipeline bubble, then exit without training (dry run)",
    )
    p.add_argument(
        "--coordinator", default=None, help="host:port for multi-host bring-up"
    )
    p.add_argument(
        "--hlo-dump",
        default=None,
        metavar="DIR",
        help="dump optimized HLO per compilation to DIR (SURVEY C19)",
    )
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument(
        "overrides", nargs="*", help="config overrides: path.to.field=value"
    )
    return p.parse_args(argv)


def hlo_dump_flags(dump_dir: str) -> str:
    """XLA_FLAGS value for optimized-HLO dumps (SURVEY C19).

    Lives here (jax-free module), NOT in utils.profiling: that module
    imports jax at top level, which would freeze JAX_PLATFORMS before
    ``_configure_platform``'s CPU forcing below could run.
    """
    return f"--xla_dump_to={dump_dir} --xla_dump_hlo_as_text"


def _configure_platform(args) -> None:
    """Must run before jax initializes a backend."""
    if args.hlo_dump:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + hlo_dump_flags(args.hlo_dump)
        ).strip()
    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        if args.sim_devices > 1:
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count={args.sim_devices}"
                ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    enable_compile_cache()


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Persistent XLA compilation cache (shared by launcher, bench, tests).

    First TPU compiles run ~20-40s; repeat runs of the same config hit the
    cache instead. Off only when FRL_TPU_NO_COMPILE_CACHE is set; cache
    write failures are non-fatal inside jax.
    """
    if os.environ.get("FRL_TPU_NO_COMPILE_CACHE"):
        return
    import jax

    if cache_dir is None:
        cache_dir = os.environ.get(
            "FRL_TPU_COMPILE_CACHE",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))), ".jax_cache"),
        )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def run_experiment(cfg, *, check_imports: bool = True):
    """Train one config to completion; returns (state, last_metrics)."""
    if check_imports:
        _assert_no_cuda_imports()
    from frl_distributed_ml_scaffold_tpu.launcher.elastic import fault_hook_from_env
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    trainer = Trainer(cfg)
    return trainer.fit(on_step=fault_hook_from_env(cfg))


_BANNED_IMPORT_PREFIXES = ("torch", "cupy", "nccl")


def _imported_names(tree) -> "list[str]":
    """Every module name a parsed source imports: Import/ImportFrom plus
    the dynamic forms ``importlib.import_module("x")`` / ``__import__("x")``
    with literal arguments. Module-level so tests can pin the semantics."""
    import ast

    names: list[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            names.extend(a.name for a in node.names)
        elif isinstance(node, ast.ImportFrom) and node.module:
            names.append(node.module)
        elif (
            isinstance(node, ast.Call)
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and (
                (isinstance(node.func, ast.Attribute)
                 and node.func.attr == "import_module")
                or (isinstance(node.func, ast.Name)
                    and node.func.id == "__import__")
            )
        ):
            names.append(node.args[0].value)
    return names


def _assert_no_cuda_imports() -> None:
    """The north-star constraint: zero CUDA/NCCL imports in the TPU path.

    Two complementary tiers (neither alone is sufficient):

    - **Static** AST scan over the framework's own sources — proves *this
      framework's code* declares no CUDA-stack dependency, including
      dynamic ``importlib.import_module("...")`` forms with literal
      arguments. Blind to what third parties import at runtime.
    - **Runtime** ``sys.modules`` check — catches a banned module pulled
      in transitively (a dependency importing torch behind our back) or
      via a non-literal dynamic import the AST scan cannot see. An
      embedding process that legitimately holds host torch (e.g.
      tools/import_hf_gpt2.py converts HF checkpoints on the host) opts
      out explicitly with ``FRL_ALLOW_HOST_TORCH=1`` — the escape hatch
      is deliberate and narrow: it waives only the runtime tier, never
      the source scan.
    """
    import ast

    if os.environ.get("FRL_ALLOW_HOST_TORCH", "") in ("", "0"):
        loaded = [
            m for m in _BANNED_IMPORT_PREFIXES
            if m in sys.modules
            or any(n.startswith(m + ".") for n in sys.modules)
        ]
        if loaded:
            raise RuntimeError(
                f"CUDA-path modules loaded in the launch process: {loaded} "
                "(set FRL_ALLOW_HOST_TORCH=1 if this embedding process "
                "holds host torch deliberately)"
            )

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    offenders = []
    unparseable = []
    for dirpath, _, files in os.walk(pkg_root):
        for f in files:
            if not f.endswith(".py"):
                continue
            path = os.path.join(dirpath, f)
            rel = os.path.relpath(path, pkg_root)
            try:
                with open(path, encoding="utf-8") as fh:
                    tree = ast.parse(fh.read(), filename=path)
            except (SyntaxError, UnicodeDecodeError) as e:
                # A .py the interpreter could never import can't be
                # cleared by the scan — report it as what it is (a broken
                # source file), not as a CUDA dependency.
                unparseable.append(f"{rel}: {e}")
                continue
            if any(
                n == b or n.startswith(b + ".")
                for n in _imported_names(tree)
                for b in _BANNED_IMPORT_PREFIXES
            ):
                offenders.append(rel)
    if unparseable:
        raise RuntimeError(
            "unparseable .py files in the scaffold package (the no-CUDA "
            f"scan cannot clear them): {unparseable}"
        )
    if offenders:
        raise RuntimeError(
            f"CUDA-path imports in TPU scaffold sources: {offenders}"
        )


def main(argv=None) -> int:
    args = _parse_args(argv)
    from frl_distributed_ml_scaffold_tpu.config import (
        apply_overrides,
        get_config,
        list_configs,
        pretty_config,
    )

    if args.list_configs:
        print("\n".join(list_configs()))
        return 0
    if not args.config:
        print("--config is required (see --list-configs)", file=sys.stderr)
        return 2

    _configure_platform(args)

    cfg = apply_overrides(get_config(args.config), args.overrides)

    if args.elastic:
        from frl_distributed_ml_scaffold_tpu.launcher.elastic import supervise

        return supervise(args, cfg)

    from frl_distributed_ml_scaffold_tpu.dist.initialize import initialize_distributed
    from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

    initialize_distributed(args.coordinator, args.num_processes, args.process_id)
    from frl_distributed_ml_scaffold_tpu.utils.debugging import sanitize_from_env

    sanitize_from_env()  # FRL_TPU_SANITIZE=nans,infs,leaks (SURVEY §5)
    logger = get_logger()
    if args.describe:
        return describe(cfg)  # prints the resolved config itself
    logger.info("launching %s\n%s", cfg.name, pretty_config(cfg))
    if args.eval_only:
        last = run_eval(cfg)
    else:
        _, last = run_experiment(cfg)
    logger.info("done: %s", json.dumps(last, default=str))
    return 0


def describe(cfg) -> int:
    """Dry run: resolve everything a training run would — mesh, sharding
    specs, per-step FLOPs, pipeline bubble — and print it. Nothing trains;
    nothing is written (checkpointing and prefetch are forced off so no
    directory is created and no worker thread started)."""
    import numpy as np

    from frl_distributed_ml_scaffold_tpu.config import apply_overrides, pretty_config
    from frl_distributed_ml_scaffold_tpu.parallel.pipeline import pipeline_summary
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer
    from frl_distributed_ml_scaffold_tpu.trainer.tasks import example_input
    from frl_distributed_ml_scaffold_tpu.utils.flops import fn_flops
    from frl_distributed_ml_scaffold_tpu.utils.trees import tree_path_names

    _assert_no_cuda_imports()
    print(pretty_config(cfg))
    cfg = apply_overrides(cfg, ["checkpoint.enabled=false", "data.prefetch=0"])
    trainer = Trainer(cfg)
    print(f"\nmesh: {dict(trainer.env.mesh.shape)} "
          f"({trainer.env.num_devices} devices)")
    summary = pipeline_summary(cfg.model)
    if summary:
        print(summary)

    shapes = trainer.state_shapes.params
    specs = trainer.state_specs.params
    names = tree_path_names(shapes)
    import jax

    total = 0
    print(f"\n{'parameter':58s} {'shape':20s} sharding")
    for name, shape_leaf, spec in zip(
        names, jax.tree.leaves(shapes), jax.tree.leaves(
            specs, is_leaf=lambda x: hasattr(x, "index") and not hasattr(x, "shape")
        )
    ):
        total += int(np.prod(shape_leaf.shape))
        print(f"{name:58s} {str(tuple(shape_leaf.shape)):20s} {spec}")
    print(f"\ntotal params: {total / 1e6:.2f}M")

    # The real global batch size — divisible by every axis/accum factor by
    # construction (only shapes are traced; nothing is materialized on
    # device).
    x = example_input(
        cfg.data, cfg.model, batch_size=cfg.data.global_batch_size
    )
    batch = {k: np.asarray(v) for k, v in x.items()}
    try:
        if getattr(trainer, "_mpmd", None) is not None:
            # MPMD pipeline: no single train-step program — sum the
            # per-stage fwd+bwd jaxpr FLOPs over all microbatches.
            cost = trainer._mpmd.step_cost_analysis()
            if cost is None:
                raise RuntimeError("per-stage FLOPs unavailable")
            flops = float(cost["flops"])
        else:
            flops = trainer._mesh_scoped(fn_flops)(
                trainer._train_step_fn, trainer.state_shapes, batch
            )
        per_sample = flops / batch[next(iter(batch))].shape[0]
        print(f"train-step FLOPs (example batch): {flops / 1e9:.2f} G "
              f"({per_sample / 1e9:.2f} G/sample)")
    except Exception as e:  # describe must never fail a dry run
        print(f"train-step FLOPs: unavailable ({type(e).__name__}: {e})")
    return 0


def run_eval(cfg) -> dict:
    """Reference call stack (e): restore → eval loop, no training."""
    from frl_distributed_ml_scaffold_tpu.trainer.loop import Trainer

    _assert_no_cuda_imports()
    trainer = Trainer(cfg)
    if trainer.checkpointer is None or trainer.checkpointer.latest_step() is None:
        raise RuntimeError(
            "--eval-only needs checkpoint.enabled=true and an existing "
            f"checkpoint under {cfg.workdir}/{cfg.name}/ckpt"
        )
    state = trainer.checkpointer.restore_or_init(trainer)
    return trainer.evaluate(state)


if __name__ == "__main__":
    raise SystemExit(main())
