"""Launcher (SURVEY C1, C14): single entrypoint + elastic supervision.

Replaces torchrun: no rank spawning — JAX is multi-controller SPMD, so the
launcher's job is platform selection (``--device=tpu|cpu``), optional
multi-host bring-up (``jax.distributed.initialize``), config resolution with
CLI overrides, and (optionally) supervising the run for checkpoint-restart
elasticity.
"""

from frl_distributed_ml_scaffold_tpu.launcher.launch import main, run_experiment
