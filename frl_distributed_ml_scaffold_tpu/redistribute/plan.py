"""Mesh-to-mesh redistribution PLAN compiler (ISSUE 15, ROADMAP item 4).

Portable, memory-efficient collective array redistribution (arXiv
2112.01075) as a compiled object: ``compile_leaf_plan`` takes a leaf's
(shape, dtype, source sharding, destination sharding) and emits the
minimal chunked transfer program — shard-local slicing plus exchange
rounds with a bounded scratch budget, never staging a replicated copy of
the logical array (unless the DESTINATION is replication, in which case
a full copy per device is the requirement, not staging).

The plan is three things at once:

- an **executable program** (redistribute/executor.py runs it,
  donated-in-place);
- a **cost model**: ``bytes_moved`` (chunks that actually change
  device), ``bytes_lower_bound`` (the shard-delta: bytes each
  destination device does not already hold — the information-theoretic
  floor any redistribution must move), and ``peak_scratch_bytes`` (the
  largest transient the executor may materialize) — the columns the
  perf ledger's ``redistribute:*`` rows price;
- a **lintable artifact**: the same-mesh ``collective`` kind lowers to
  one shard_map program per leaf class whose jaxpr graft-lint's
  ``reshard:*`` family pins (materialization <= the scratch budget,
  source donated — a naive gather-then-scatter trips both).

Plan kinds, chosen per leaf:

- ``identity``    — same devices, same per-device index map: no-op.
- ``collective``  — same mesh, "atom-clean" spec transition (each mesh
  axis either stays on its dim, moves whole to another dim, appears
  only in the source, or only in the destination — with every dim
  touched by at most one change): ONE shard_map program of
  slice / all_to_all / all_gather steps, peak memory ~= one source
  shard + one destination shard per device.
- ``chunked``     — everything else (cross-mesh, device-subset growth/
  shrink, unclean transitions): host-orchestrated per-destination-shard
  assembly from source-shard slices, each chunk bounded by the scratch
  budget. Single-process only (every shard must be addressable).
- ``host``        — the source is a host (numpy) array: a shard-wise
  ``device_put`` (each device receives only its slice; no staging).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import numpy as np


# --------------------------------------------------------------- indexing


def _resolve_index(
    idx: Sequence[slice], shape: Sequence[int]
) -> tuple[tuple[int, int], ...]:
    """Normalize a devices_indices_map entry to ((start, stop), ...) —
    one pair per dim, trailing unsliced dims filled in."""
    out = []
    for d, dim in enumerate(shape):
        if d < len(idx):
            s = idx[d]
            start = 0 if s.start is None else int(s.start)
            stop = dim if s.stop is None else int(s.stop)
        else:
            start, stop = 0, dim
        out.append((start, stop))
    return tuple(out)


def _region_size(region: tuple[tuple[int, int], ...]) -> int:
    n = 1
    for a, b in region:
        n *= max(0, b - a)
    return n


def _intersect(r1, r2):
    out = []
    for (a1, b1), (a2, b2) in zip(r1, r2):
        a, b = max(a1, a2), min(b1, b2)
        if a >= b:
            return None
        out.append((a, b))
    return tuple(out)


def _split_region(region, limit_elems: int):
    """Split a region into pieces of at most ``limit_elems`` elements,
    cutting along the largest extent first (the chunking that bounds the
    executor's in-flight transfer buffers)."""
    if _region_size(region) <= limit_elems or limit_elems <= 0:
        return [region]
    ext = [b - a for a, b in region]
    dim = int(np.argmax(ext))
    a, b = region[dim]
    mid = a + (b - a) // 2
    if mid == a:  # single row of a huge inner extent: cut the next dim
        order = np.argsort(ext)[::-1]
        for d in order[1:]:
            if ext[d] > 1:
                dim = int(d)
                a, b = region[dim]
                mid = a + (b - a) // 2
                break
        else:
            return [region]  # one element over budget: irreducible
    left = region[:dim] + ((a, mid),) + region[dim + 1:]
    right = region[:dim] + ((mid, b),) + region[dim + 1:]
    return _split_region(left, limit_elems) + _split_region(right, limit_elems)


# ----------------------------------------------------------- plan objects


@dataclasses.dataclass(frozen=True)
class Chunk:
    """One bounded transfer: the ``index`` region of the global array,
    read from ``src_device`` and delivered to ``dst_device`` (equal ids
    = a local copy, free on the wire)."""

    src_device: int
    dst_device: int
    index: tuple[tuple[int, int], ...]
    nbytes: int

    @property
    def moves(self) -> bool:
        return self.src_device != self.dst_device


@dataclasses.dataclass
class Transition:
    """An atom-clean same-mesh spec transition (the ``collective`` plan
    kind's program description). Atoms are mesh-axis tuples treated
    wholesale; each entry carries (atom names, axis sizes product,
    dims). Built by ``analyze_transition``; lowered to a shard_map body
    by redistribute/executor.py."""

    #: every src-spec atom as (names, dim) — the naive reference gathers
    #: all of these (that is exactly the replicated staging the real
    #: program exists to avoid).
    src_atoms: list[tuple[tuple[str, ...], int]]
    #: every dst-spec atom as (names, dim).
    dst_atoms: list[tuple[tuple[str, ...], int]]
    #: atoms present only in dst: local slice, zero comm.
    adds: list[tuple[tuple[str, ...], int]]
    #: atoms moving dim: one all_to_all each.
    moves: list[tuple[tuple[str, ...], int, int]]
    #: atoms present only in src: one tiled all_gather each.
    drops: list[tuple[tuple[str, ...], int]]
    axis_sizes: dict[str, int]

    def atom_size(self, names: tuple[str, ...]) -> int:
        return int(np.prod([self.axis_sizes[n] for n in names], dtype=np.int64))


@dataclasses.dataclass
class LeafPlan:
    """The compiled redistribution program for ONE pytree leaf."""

    path: str
    shape: tuple[int, ...]
    dtype: str
    src_sharding: Any
    dst_sharding: Any
    kind: str  # identity | collective | chunked | host
    chunks: list[Chunk]
    transition: Transition | None
    bytes_moved: int
    bytes_lower_bound: int
    peak_scratch_bytes: int

    @property
    def leaf_bytes(self) -> int:
        return int(
            np.prod(self.shape, dtype=np.int64) * np.dtype(self.dtype).itemsize
        )

    def to_dict(self) -> dict:
        def _spec(sh):
            spec = getattr(sh, "spec", None)
            return str(spec) if spec is not None else type(sh).__name__

        return {
            "path": self.path,
            "shape": list(self.shape),
            "dtype": self.dtype,
            "src": _spec(self.src_sharding),
            "dst": _spec(self.dst_sharding),
            "kind": self.kind,
            "leaf_bytes": self.leaf_bytes,
            "bytes_moved": self.bytes_moved,
            "bytes_lower_bound": self.bytes_lower_bound,
            "peak_scratch_bytes": self.peak_scratch_bytes,
            "n_chunks": len(self.chunks),
        }


@dataclasses.dataclass
class RedistributionPlan:
    """A whole pytree's redistribution: per-leaf programs + the
    aggregate cost model the perf ledger prices. ``executed_scratch_bytes``
    is stamped by the executor — the MEASURED peak host/device transient,
    pinned <= ``peak_scratch_bytes`` in tests."""

    leaves: list[LeafPlan]
    scratch_limit_bytes: int | None = None
    executed_scratch_bytes: int = 0

    @property
    def bytes_moved(self) -> int:
        return sum(l.bytes_moved for l in self.leaves)

    @property
    def bytes_lower_bound(self) -> int:
        return sum(l.bytes_lower_bound for l in self.leaves)

    @property
    def peak_scratch_bytes(self) -> int:
        return max((l.peak_scratch_bytes for l in self.leaves), default=0)

    @property
    def total_bytes(self) -> int:
        return sum(l.leaf_bytes for l in self.leaves)

    def to_dict(self) -> dict:
        return {
            "leaves": [l.to_dict() for l in self.leaves],
            "bytes_moved": self.bytes_moved,
            "bytes_lower_bound": self.bytes_lower_bound,
            "peak_scratch_bytes": self.peak_scratch_bytes,
            "total_bytes": self.total_bytes,
            "scratch_limit_bytes": self.scratch_limit_bytes,
        }

    def summary_lines(self) -> list[str]:
        kinds: dict[str, int] = {}
        for l in self.leaves:
            kinds[l.kind] = kinds.get(l.kind, 0) + 1
        return [
            f"redistribution plan: {len(self.leaves)} leaves "
            + ", ".join(f"{k}={v}" for k, v in sorted(kinds.items())),
            f"  bytes_moved={self.bytes_moved} "
            f"(lower bound {self.bytes_lower_bound}) "
            f"peak_scratch={self.peak_scratch_bytes} "
            f"of {self.total_bytes} total",
        ]


# ------------------------------------------------------- spec transitions


def _spec_atoms(spec, ndim: int) -> list[tuple[tuple[str, ...], int]] | None:
    """PartitionSpec -> [(atom names, dim)]; None when a dim entry is
    malformed. An entry tuple is ONE atom (its names shard the dim
    jointly, major-to-minor)."""
    entries = list(spec) + [None] * (ndim - len(spec))
    if len(entries) > ndim:
        return None
    out = []
    for dim, e in enumerate(entries):
        if e is None:
            continue
        names = (e,) if isinstance(e, str) else tuple(e)
        if names:
            out.append((names, dim))
    return out


def analyze_transition(
    src_spec, dst_spec, mesh, shape: Sequence[int]
) -> Transition | None:
    """Classify a same-mesh spec change into the atom-clean Transition
    the collective executor lowers, or None when the change is not
    cleanly expressible (the plan then falls back to ``chunked``):

    - every src/dst atom pair is either identical or name-disjoint;
    - each dim is touched by at most one add/move/drop (interacting
      transformations on one dim would interleave blocks);
    - every sharded extent divides evenly.
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    src_atoms = _spec_atoms(src_spec, len(shape))
    dst_atoms = _spec_atoms(dst_spec, len(shape))
    if src_atoms is None or dst_atoms is None:
        return None
    # Atom cleanliness: identical or disjoint.
    for a, _ in src_atoms:
        for b, _ in dst_atoms:
            if a != b and set(a) & set(b):
                return None
    # At most one atom per dim on each side (multi-atom dims interleave).
    for atoms in (src_atoms, dst_atoms):
        dims = [d for _, d in atoms]
        if len(dims) != len(set(dims)):
            return None
    src_by_atom = {a: d for a, d in src_atoms}
    dst_by_atom = {a: d for a, d in dst_atoms}
    adds, moves, drops = [], [], []
    for a, d in dst_atoms:
        if a not in src_by_atom:
            adds.append((a, d))
        elif src_by_atom[a] != d:
            moves.append((a, src_by_atom[a], d))
    for a, d in src_atoms:
        if a not in dst_by_atom:
            drops.append((a, d))
    touched: list[int] = [d for _, d in adds] + [d for _, d in drops]
    for _, sd, dd in moves:
        touched += [sd, dd]
    if len(touched) != len(set(touched)):
        return None
    # An unchanged atom's dim must not also host a transformation.
    unchanged_dims = {
        d for a, d in src_atoms if dst_by_atom.get(a) == d
    }
    if unchanged_dims & set(touched):
        return None
    # Divisibility: every sharded dim divides by the product of its
    # atom's sizes, at the LOCAL extent the op sees.
    tr = Transition(
        src_atoms=src_atoms, dst_atoms=dst_atoms,
        adds=adds, moves=moves, drops=drops, axis_sizes=sizes,
    )
    for a, d in src_atoms:
        if shape[d] % tr.atom_size(a) != 0:
            return None
    for a, d in dst_atoms:
        if shape[d] % tr.atom_size(a) != 0:
            return None
    for a, sd, dd in moves:
        # all_to_all splits the (locally whole) dst dim by the group.
        if shape[dd] % tr.atom_size(a) != 0:
            return None
    return tr


def _same_mesh(src_sharding, dst_sharding) -> bool:
    from jax.sharding import NamedSharding

    if not isinstance(src_sharding, NamedSharding) or not isinstance(
        dst_sharding, NamedSharding
    ):
        return False
    ms, md = src_sharding.mesh, dst_sharding.mesh
    if ms.axis_names != md.axis_names:
        return False
    if ms.devices.shape != md.devices.shape:
        return False
    return [d.id for d in ms.devices.flat] == [d.id for d in md.devices.flat]


# ------------------------------------------------------------ compilation


def _index_maps(sharding, shape):
    """{device_id: region} plus {region: [holder ids]} for the unique
    (disjoint) shard regions of a sharding."""
    dev_map = {}
    holders: dict[tuple, list[int]] = {}
    for dev, idx in sharding.devices_indices_map(tuple(shape)).items():
        region = _resolve_index(idx, shape)
        dev_map[dev.id] = region
        holders.setdefault(region, []).append(dev.id)
    for ids in holders.values():
        ids.sort()
    return dev_map, holders


def compile_leaf_plan(
    shape: Sequence[int],
    dtype: Any,
    src_sharding: Any,
    dst_sharding: Any,
    *,
    scratch_limit_bytes: int | None = None,
    path: str = "",
) -> LeafPlan:
    """Compile ONE leaf's redistribution (see module docstring). Works
    purely on shardings + abstract shape/dtype — nothing touches device
    memory, so the perf ledger and the ``--dry-run`` CLI can price a
    migration that never runs."""
    shape = tuple(int(s) for s in shape)
    dtype = np.dtype(dtype)
    itemsize = dtype.itemsize
    host_src = not hasattr(src_sharding, "devices_indices_map")
    dst_map, _dst_holders = _index_maps(dst_sharding, shape)
    max_dst_shard = max(
        (_region_size(r) * itemsize for r in dst_map.values()), default=0
    )

    if host_src:
        # Host -> device: device_put slices per shard; nothing staged
        # beyond one destination shard.
        return LeafPlan(
            path=path, shape=shape, dtype=dtype.name,
            src_sharding=src_sharding, dst_sharding=dst_sharding,
            kind="host", chunks=[], transition=None,
            bytes_moved=sum(
                _region_size(r) * itemsize for r in dst_map.values()
            ),
            bytes_lower_bound=sum(
                _region_size(r) * itemsize for r in dst_map.values()
            ),
            peak_scratch_bytes=max_dst_shard,
        )

    src_map, src_holders = _index_maps(src_sharding, shape)

    # Identity first — an unchanged-topology restore (the reform path
    # forces restore_redistribute on) would otherwise pay the full
    # chunk decomposition per leaf just to discard it.
    if sorted(src_map) == sorted(dst_map) and all(
        src_map[d] == dst_map[d] for d in dst_map
    ):
        return LeafPlan(
            path=path, shape=shape, dtype=dtype.name,
            src_sharding=src_sharding, dst_sharding=dst_sharding,
            kind="identity", chunks=[], transition=None,
            bytes_moved=0, bytes_lower_bound=0, peak_scratch_bytes=0,
        )

    # ---- chunk decomposition (the cost model for every kind) ----------
    # Unique src regions tile the array; each dst shard's cover is its
    # intersection with those tiles. Holder choice prefers the dst
    # device itself (a free local copy), then balances by assigned
    # bytes (deterministic: ties break on lowest device id).
    assigned: dict[int, int] = {}
    chunks: list[Chunk] = []
    lower = 0
    limit_elems = (
        max(1, scratch_limit_bytes // itemsize)
        if scratch_limit_bytes
        else 0
    )
    for dst_id in sorted(dst_map):
        region = dst_map[dst_id]
        for src_region, holder_ids in sorted(src_holders.items()):
            inter = _intersect(region, src_region)
            if inter is None:
                continue
            nbytes = _region_size(inter) * itemsize
            if dst_id in holder_ids:
                holder = dst_id
            else:
                holder = min(
                    holder_ids, key=lambda h: (assigned.get(h, 0), h)
                )
                lower += nbytes
            assigned[holder] = assigned.get(holder, 0) + nbytes
            pieces = (
                _split_region(inter, limit_elems) if limit_elems else [inter]
            )
            for piece in pieces:
                chunks.append(
                    Chunk(
                        src_device=holder, dst_device=dst_id, index=piece,
                        nbytes=_region_size(piece) * itemsize,
                    )
                )
    bytes_moved = sum(c.nbytes for c in chunks if c.moves)

    # ---- kind selection ----------------------------------------------
    transition = None
    if _same_mesh(src_sharding, dst_sharding):
        transition = analyze_transition(
            src_sharding.spec, dst_sharding.spec,
            dst_sharding.mesh, shape,
        )
    if transition is not None:
        src_local = max(
            (_region_size(r) * itemsize for r in src_map.values()), default=0
        )
        return LeafPlan(
            path=path, shape=shape, dtype=dtype.name,
            src_sharding=src_sharding, dst_sharding=dst_sharding,
            kind="collective", chunks=chunks, transition=transition,
            bytes_moved=bytes_moved, bytes_lower_bound=lower,
            # The program holds one source shard and one destination
            # shard live per device (all_to_all is in-place-sized; an
            # all_gather's output IS the destination shard).
            peak_scratch_bytes=src_local + max_dst_shard,
        )

    # Chunked host-windowed fallback: per destination shard, an assembly
    # buffer (only when more than one chunk feeds it) plus one bounded
    # chunk in flight.
    per_dst: dict[int, list[Chunk]] = {}
    for c in chunks:
        per_dst.setdefault(c.dst_device, []).append(c)
    peak = 0
    for dst_id, cs in per_dst.items():
        shard_bytes = _region_size(dst_map[dst_id]) * itemsize
        buf = shard_bytes if len(cs) > 1 else 0
        peak = max(peak, buf + max(c.nbytes for c in cs))
    return LeafPlan(
        path=path, shape=shape, dtype=dtype.name,
        src_sharding=src_sharding, dst_sharding=dst_sharding,
        kind="chunked", chunks=chunks, transition=None,
        bytes_moved=bytes_moved, bytes_lower_bound=lower,
        peak_scratch_bytes=peak,
    )


def compile_tree_plan(
    tree: Any,
    dst_shardings: Any,
    *,
    scratch_limit_bytes: int | None = None,
) -> RedistributionPlan:
    """Compile a whole pytree's redistribution. ``tree`` leaves may be
    live ``jax.Array``s, numpy arrays, or ``ShapeDtypeStruct``s carrying
    a ``.sharding`` (the analytic path); ``dst_shardings`` is a matching
    tree of Shardings."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    dst_leaves = jax.tree_util.tree_leaves(
        dst_shardings, is_leaf=lambda x: hasattr(x, "devices_indices_map")
    )
    if len(flat) != len(dst_leaves):
        raise ValueError(
            f"tree has {len(flat)} leaves but dst_shardings has "
            f"{len(dst_leaves)} — the trees must match"
        )
    leaves = []
    for (kp, leaf), dst in zip(flat, dst_leaves):
        src = getattr(leaf, "sharding", None)
        leaves.append(
            compile_leaf_plan(
                leaf.shape, leaf.dtype, src, dst,
                scratch_limit_bytes=scratch_limit_bytes,
                path=jax.tree_util.keystr(kp),
            )
        )
    return RedistributionPlan(
        leaves=leaves, scratch_limit_bytes=scratch_limit_bytes
    )


# ------------------------------------------------- restore (even) layouts


def restore_layout_spec(shape: Sequence[int], target_spec, mesh):
    """The memory-efficient RESTORE layout for a checkpoint leaf (the
    elastic-restore seam): the target spec with every mesh axis the
    target does not use overlaid onto the largest unsharded divisible
    dim — each device then reads ~1/N of the leaf from disk, never a
    replicated staging copy, and the redistribution to the target layout
    is a pure atom-DROP program (tiled all_gathers on their own dims —
    the clean ``collective`` kind by construction)."""
    from jax.sharding import PartitionSpec as P

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    used: set[str] = set()
    entries = list(target_spec) + [None] * (len(shape) - len(target_spec))
    for e in entries:
        if e is None:
            continue
        for n in (e,) if isinstance(e, str) else e:
            used.add(n)
    remaining = [
        a for a in mesh.axis_names if sizes[a] > 1 and a not in used
    ]
    while remaining:
        size = int(np.prod([sizes[a] for a in remaining], dtype=np.int64))
        cands = [
            i for i, (dim, e) in enumerate(zip(shape, entries))
            if e is None and dim % size == 0 and dim >= size
        ]
        if cands:
            best = max(cands, key=lambda i: shape[i])
            entries[best] = (
                remaining[0] if len(remaining) == 1 else tuple(remaining)
            )
            return P(*entries)
        remaining = remaining[:-1]  # shed minor axes until something fits
    return P(*entries)
