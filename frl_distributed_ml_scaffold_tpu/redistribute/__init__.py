"""Mesh-to-mesh state redistribution service (ISSUE 15, ROADMAP item 4).

One subsystem for every "move state between layouts" seam the scaffold
has grown: a plan compiler (redistribute/plan.py — minimal chunked
collective programs with a bounded scratch budget, the arXiv 2112.01075
contract), an executor that runs plans donated-in-place
(redistribute/executor.py), and a cost model the perf ledger prices
(``RedistributionPlan.bytes_moved`` / ``bytes_lower_bound`` /
``peak_scratch_bytes``). The named seams:

- **elastic restore** — ``checkpoint.restore_or_init`` with
  ``checkpoint.restore_redistribute=true`` (the elastic supervisor's
  reform path forces it): restore a checkpoint saved on ANY mesh at a
  memory-efficient even layout (each device reads ~1/N), then
  redistribute on-device to the trainer's target shardings;
- **train→serve handoff** — ``train_to_serve(params, serve_env,
  rules)``: reshard fsdp×model training params onto a serving TP
  layout on-device (adopted by ``shard_params_for_serving`` /
  ``build_engine(rules=...)`` / the disaggregated PrefillWorker);
- **serving re-spread** — ``ServingEngine.respread_pool(new_env)``:
  re-spread the paged KV pool (+ scale leaves + block tables) when the
  model axis grows or shrinks, composing with park/resume so in-flight
  requests survive token-identically.

Graft-lint's ``reshard:*`` program family pins the executor's
same-mesh collective programs (materialization <= the scratch budget,
source donated); docs/operations.md "State redistribution" is the
operator face.
"""

from __future__ import annotations

from typing import Any, Callable

from frl_distributed_ml_scaffold_tpu.redistribute.executor import (
    collective_callable,
    collective_program,
    execute,
    execute_leaf,
)
from frl_distributed_ml_scaffold_tpu.redistribute.plan import (
    Chunk,
    LeafPlan,
    RedistributionPlan,
    Transition,
    analyze_transition,
    compile_leaf_plan,
    compile_tree_plan,
    restore_layout_spec,
)

__all__ = [
    "Chunk",
    "LeafPlan",
    "RedistributionPlan",
    "Transition",
    "analyze_transition",
    "collective_callable",
    "collective_program",
    "compile_leaf_plan",
    "compile_tree_plan",
    "execute",
    "execute_leaf",
    "mesh_shardings",
    "redistribute_tree",
    "restore_layout_spec",
    "serve_shardings",
    "spec_on",
    "to_mesh",
    "train_to_serve",
    "train_to_serve_plan",
]


def redistribute_tree(
    tree: Any,
    dst_shardings: Any,
    *,
    donate: bool = False,
    scratch_limit_bytes: int | None = None,
) -> tuple[Any, RedistributionPlan]:
    """Compile + execute in one call; returns ``(new_tree, plan)``."""
    plan = compile_tree_plan(
        tree, dst_shardings, scratch_limit_bytes=scratch_limit_bytes
    )
    return execute(plan, tree, donate=donate), plan


def spec_on(mesh, leaf, spec):
    """Carry a PartitionSpec onto another mesh, degrading per-axis: any
    spec entry whose axis no longer divides the dim (or no longer
    exists) is dropped to replication for THAT dim — the honest
    cross-topology transfer rule (a model axis of 2 re-spread to 4
    keeps P(...'model'...) as long as heads still divide)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    entries = list(spec) + [None] * (len(leaf.shape) - len(spec))
    out = []
    for dim, e in zip(leaf.shape, entries):
        if e is None:
            out.append(None)
            continue
        names = (e,) if isinstance(e, str) else tuple(e)
        prod = 1
        ok = True
        for n in names:
            if n not in sizes:
                ok = False
                break
            prod *= sizes[n]
        out.append(e if ok and prod and dim % prod == 0 else None)
    return NamedSharding(mesh, P(*out))


def mesh_shardings(
    tree: Any,
    env_or_mesh: Any,
    *,
    spec_of: Callable[[str, Any], Any] | None = None,
) -> Any:
    """Destination shardings for moving ``tree`` onto another mesh with
    leaf specs carried over (``spec_on`` degradation rules).
    ``spec_of(path, leaf)`` overrides the destination PartitionSpec per
    leaf (None = keep the source's); leaves without a NamedSharding
    (host arrays, single-device) default to replication unless
    ``spec_of`` says otherwise. Split out of ``to_mesh`` so callers can
    COMPILE plans before mutating any state (the respread_pool
    compile-before-park discipline)."""
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = getattr(env_or_mesh, "mesh", env_or_mesh)
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    dst = []
    for kp, leaf in flat:
        path = jax.tree_util.keystr(kp)
        spec = spec_of(path, leaf) if spec_of is not None else None
        if spec is None:
            src = getattr(leaf, "sharding", None)
            spec = getattr(src, "spec", None)
        if spec is None:
            spec = P()
        dst.append(spec_on(mesh, leaf, spec))
    return jax.tree_util.tree_unflatten(treedef, dst)


def to_mesh(
    tree: Any,
    env_or_mesh: Any,
    *,
    spec_of: Callable[[str, Any], Any] | None = None,
    donate: bool = False,
    scratch_limit_bytes: int | None = None,
) -> tuple[Any, RedistributionPlan]:
    """Move a device tree onto another mesh (``mesh_shardings`` +
    compile + execute in one call)."""
    return redistribute_tree(
        tree,
        mesh_shardings(tree, env_or_mesh, spec_of=spec_of),
        donate=donate,
        scratch_limit_bytes=scratch_limit_bytes,
    )


def serve_shardings(params: Any, serve_env: Any, rules: Any = None) -> Any:
    """Destination shardings for the train→serve handoff: the model's
    TP ``rules`` over a replicated base on ``serve_env``'s mesh (no
    FSDP overlay — serving has no optimizer), exactly the derivation
    ``parallel.partition.shard_params_for_serving`` uses."""
    import jax
    from jax.sharding import PartitionSpec as P

    from frl_distributed_ml_scaffold_tpu.config.schema import ParallelConfig
    from frl_distributed_ml_scaffold_tpu.parallel.partition import (
        PartitionRules,
        param_specs,
    )

    rules = rules if rules is not None else PartitionRules()
    specs = param_specs(params, ParallelConfig(), serve_env.mesh, rules)
    spec_leaves = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    flat, treedef = jax.tree_util.tree_flatten(params)
    return jax.tree_util.tree_unflatten(
        treedef,
        [spec_on(serve_env.mesh, l, s) for l, s in zip(flat, spec_leaves)],
    )


def train_to_serve_plan(
    params: Any,
    serve_env: Any,
    rules: Any = None,
    *,
    scratch_limit_bytes: int | None = None,
) -> RedistributionPlan:
    """Compile (only) the train→serve handoff plan — works on abstract
    trees (ShapeDtypeStructs carrying shardings), which is how the
    perf-ledger ``redistribute:train_to_serve`` row and the
    ``reshard_plan.py --dry-run`` CLI price a migration that never
    runs."""
    return compile_tree_plan(
        params, serve_shardings(params, serve_env, rules),
        scratch_limit_bytes=scratch_limit_bytes,
    )


def train_to_serve(
    params: Any,
    serve_env: Any,
    rules: Any = None,
    *,
    donate: bool = False,
    scratch_limit_bytes: int | None = None,
) -> tuple[Any, RedistributionPlan]:
    """The train→serve param handoff (seam 2): reshard a (typically
    fsdp×model-sharded) training params tree onto ``serve_env``'s
    serving TP layout on-device — destination specs from
    ``serve_shardings``, moved by the plan executor instead of a
    replicated host round-trip. Returns ``(placed_params, plan)``; the
    plan's ``bytes_moved``/``peak_scratch_bytes`` are what the
    perf-ledger ``redistribute:train_to_serve`` row prices."""
    plan = train_to_serve_plan(
        params, serve_env, rules, scratch_limit_bytes=scratch_limit_bytes
    )
    return execute(plan, params, donate=donate), plan
