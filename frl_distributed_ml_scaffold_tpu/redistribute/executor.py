"""Redistribution plan EXECUTOR (ISSUE 15): run LeafPlans donated-in-place.

Two lowerings behind one ``execute`` entry:

- ``collective`` plans become ONE jitted shard_map program per (mesh,
  specs, shape, dtype) class — slice / all_to_all / all_gather steps in
  add→move→drop order (shrink first, grow last), input donated. These
  are the programs graft-lint's ``reshard:*`` family pins: every
  intermediate fits the plan's scratch budget (one source shard + one
  destination shard per device), and a naive gather-then-scatter —
  materialize the full logical array on every device, re-slice — trips
  the materialization pin. ``_NAIVE_GATHER_SCATTER`` switches the body
  to exactly that naive reference: the mutation gate's mutant AND the
  bit-exactness oracle the tests compare the real program against.

- ``chunked`` plans run host-orchestrated: per destination shard,
  assemble from bounded source-shard slices (device-to-device when one
  chunk covers the shard; a host window otherwise) and build the
  destination array from its per-device shards. Peak transient = one
  destination shard + one chunk — measured and stamped back onto the
  plan (``executed_scratch_bytes``) so tests pin measured <= planned.

Donation: ``donate=True`` deletes each source leaf's buffers as soon as
its destination array is materialized, so peak tree memory is ONE leaf's
(src + dst), not two full trees.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from frl_distributed_ml_scaffold_tpu.redistribute.plan import (
    LeafPlan,
    RedistributionPlan,
    Transition,
    _region_size,
)

#: Mutation switch for the graft-lint gate (tests/test_graft_lint.py)
#: and the reference oracle for the equivalence tests: True lowers every
#: collective plan to gather-everything-then-slice — the replicated
#: staging the real program is pinned never to do.
_NAIVE_GATHER_SCATTER = False

#: (mesh ids, src spec, dst spec, shape, dtype, naive) -> jitted program.
_PROGRAM_CACHE: dict[tuple, Any] = {}


def _flat_axis_index(names: tuple[str, ...], sizes: dict[str, int]):
    """Flattened (major-to-minor) index of this device within a
    multi-name atom's group — the P(('a','b')) nesting order."""
    from jax import lax

    idx = None
    for n in names:
        i = lax.axis_index(n)
        idx = i if idx is None else idx * sizes[n] + i
    return idx


def _axis_arg(names: tuple[str, ...]):
    return names[0] if len(names) == 1 else names


def _collective_body(tr: Transition):
    """The minimal redistribution body for an atom-clean transition:
    adds (local slice — shrink) first, moves (all_to_all — constant
    size), drops (tiled all_gather — grow) last, each on its own dim."""
    from jax import lax

    def body(x):
        for names, dim in tr.adds:
            size = tr.atom_size(names)
            idx = _flat_axis_index(names, tr.axis_sizes)
            piece = x.shape[dim] // size
            x = lax.dynamic_slice_in_dim(x, idx * piece, piece, axis=dim)
        for names, src_dim, dst_dim in tr.moves:
            x = lax.all_to_all(
                x, _axis_arg(names), split_axis=dst_dim,
                concat_axis=src_dim, tiled=True,
            )
        for names, dim in tr.drops:
            x = lax.all_gather(x, _axis_arg(names), axis=dim, tiled=True)
        return x

    return body


def _naive_body(tr: Transition):
    """The replicated-staging reference: gather EVERY source atom (the
    full logical array lands on every device), then slice every
    destination atom back out. Correct, and exactly what the
    materialization pin exists to forbid."""
    from jax import lax

    def body(x):
        for names, dim in tr.src_atoms:
            x = lax.all_gather(x, _axis_arg(names), axis=dim, tiled=True)
        for names, dim in tr.dst_atoms:
            size = tr.atom_size(names)
            idx = _flat_axis_index(names, tr.axis_sizes)
            piece = x.shape[dim] // size
            x = lax.dynamic_slice_in_dim(x, idx * piece, piece, axis=dim)
        return x

    return body


def collective_callable(plan: LeafPlan):
    """The UN-jitted same-mesh reshard program for a collective
    LeafPlan: shard_map(in=src spec, out=dst spec) around the
    transition body. One artifact for the executor (jitted below) and
    for graft-lint's ``reshard:*`` family (traced via make_jaxpr) —
    they cannot drift. ``_NAIVE_GATHER_SCATTER`` swaps in the
    replicated-staging reference, which is both the mutation gate's
    mutant and the tests' equivalence oracle."""
    from frl_distributed_ml_scaffold_tpu.dist.mesh import shard_map_compat

    body = (
        _naive_body(plan.transition)
        if _NAIVE_GATHER_SCATTER
        else _collective_body(plan.transition)
    )
    return shard_map_compat(
        body, mesh=plan.dst_sharding.mesh,
        in_specs=(plan.src_sharding.spec,),
        out_specs=plan.dst_sharding.spec,
    )


def collective_program(plan: LeafPlan, *, donate: bool = True):
    """THE jitted same-mesh reshard program for a collective LeafPlan —
    ``collective_callable`` under jit, source donated when ``donate``
    (the executor default — graft-lint audits the donated form). Cached
    per program class."""
    import jax

    mesh = plan.dst_sharding.mesh
    # The mesh SHAPE is part of the program identity: the same device
    # ids under mesh(data=2, model=4) vs mesh(data=4, model=2) lower
    # the same spec strings to different placements.
    key = (
        tuple(d.id for d in mesh.devices.flat),
        mesh.axis_names, mesh.devices.shape,
        str(plan.src_sharding.spec), str(plan.dst_sharding.spec),
        plan.shape, plan.dtype, donate, _NAIVE_GATHER_SCATTER,
    )
    if key not in _PROGRAM_CACHE:
        _PROGRAM_CACHE[key] = jax.jit(
            collective_callable(plan),
            donate_argnums=(0,) if donate else (),
        )
    return _PROGRAM_CACHE[key]


def _devices_by_id(*shardings) -> dict[int, Any]:
    out = {}
    for sh in shardings:
        for d in getattr(sh, "device_set", ()) or ():
            out[d.id] = d
    return out


def _rel(index, base):
    """Global region -> slices relative to ``base``'s origin."""
    return tuple(
        slice(a - b0, b - b0) for (a, b), (b0, _) in zip(index, base)
    )


def _execute_chunked(plan: LeafPlan, x, track) -> Any:
    """Host-orchestrated chunk assembly (cross-mesh / unclean
    transitions): per destination shard, either one device-to-device
    slice transfer or a host window filled chunk-by-chunk. Never holds
    more than one destination shard + one chunk."""
    import jax

    devs = _devices_by_id(plan.src_sharding, plan.dst_sharding)
    shards = {s.device.id: s for s in x.addressable_shards}
    missing = [
        c.src_device for c in plan.chunks if c.src_device not in shards
    ]
    if missing:
        raise RuntimeError(
            "chunked redistribution needs every source shard addressable "
            f"(single-process); missing device ids {sorted(set(missing))}. "
            "Multi-host cross-mesh moves must route through a same-mesh "
            "collective plan or a checkpoint round-trip."
        )
    per_dst: dict[int, list] = {}
    for c in plan.chunks:
        per_dst.setdefault(c.dst_device, []).append(c)
    dst_map = {
        d.id: idx
        for d, idx in plan.dst_sharding.devices_indices_map(
            plan.shape
        ).items()
    }
    from frl_distributed_ml_scaffold_tpu.redistribute.plan import (
        _resolve_index,
    )

    out_shards = []
    itemsize = np.dtype(plan.dtype).itemsize
    # Replicated (or partially replicated) destinations repeat regions
    # across devices: assemble each unique region's host window ONCE
    # and device_put per consumer, instead of re-pulling the same
    # source slices R times. The window is dropped after its LAST
    # consumer (refcounted below) — distinct regions are never live
    # together, so the host transient stays at one shard + one chunk,
    # which is what the plan's peak_scratch_bytes promises and
    # track() reports.
    regions = {
        dst_id: _resolve_index(dst_map[dst_id], plan.shape)
        for dst_id in per_dst
    }
    consumers: dict[tuple, int] = {}
    for r in regions.values():
        consumers[r] = consumers.get(r, 0) + 1
    buf_cache: dict[tuple, np.ndarray] = {}
    for dst_id in sorted(per_dst):
        region = regions[dst_id]
        cs = per_dst[dst_id]
        if len(cs) == 1 and cs[0].index == region:
            c = cs[0]
            src = shards[c.src_device]
            src_region = _resolve_index(
                src.index if src.index else (), plan.shape
            )
            piece = src.data[_rel(c.index, src_region)]
            track(c.nbytes)
            piece = jax.device_put(piece, devs[dst_id])
        else:
            buf = buf_cache.get(region)
            if buf is None:
                buf = np.empty(
                    tuple(b - a for a, b in region), np.dtype(plan.dtype)
                )
                shard_bytes = buf.size * itemsize
                for c in cs:
                    src = shards[c.src_device]
                    src_region = _resolve_index(
                        src.index if src.index else (), plan.shape
                    )
                    track(shard_bytes + c.nbytes)
                    buf[_rel(c.index, region)] = np.asarray(
                        src.data[_rel(c.index, src_region)]
                    )
                buf_cache[region] = buf
            piece = jax.device_put(buf, devs[dst_id])
            # device_put copies host->device synchronously enough to
            # release the window once its last consumer has a piece.
            consumers[region] -= 1
            if consumers[region] == 0:
                buf_cache.pop(region, None)
        out_shards.append(piece)
    return jax.make_array_from_single_device_arrays(
        plan.shape, plan.dst_sharding, out_shards
    )


def execute_leaf(plan: LeafPlan, x, *, donate: bool = True, track=None):
    """Run one LeafPlan. ``track(nbytes)`` observes transient peaks."""
    import jax

    track = track or (lambda _n: None)
    if plan.kind == "identity":
        return x
    if plan.kind == "host":
        track(plan.peak_scratch_bytes)
        return jax.device_put(np.asarray(x), plan.dst_sharding)
    if plan.kind == "collective":
        track(plan.peak_scratch_bytes)
        # Donation rides the program (donate_argnums): in-place at the
        # buffer level, which is what keeps an N-device reshard at
        # ~2 shards/device instead of 2 full arrays.
        return collective_program(plan, donate=donate)(x)
    out = _execute_chunked(plan, x, track)
    if donate and isinstance(x, jax.Array) and not x.is_deleted():
        # The chunk transfers above are enqueued; make sure they landed
        # before the source buffers go away.
        jax.block_until_ready(out)
        if not _shares_buffers(x, out):
            x.delete()
    return out


def _shares_buffers(x, out) -> bool:
    """True when any output shard aliases a source buffer — a full-cover
    same-device chunk is a zero-copy re-own (slicing a whole shard
    returns the shard and ``device_put`` onto its own device is a
    no-op), and deleting the source would tear the output. Nothing to
    free in that case anyway: the memory IS shared."""
    try:
        src = {s.data.unsafe_buffer_pointer() for s in x.addressable_shards}
        dst = {
            s.data.unsafe_buffer_pointer() for s in out.addressable_shards
        }
    except Exception:  # backends without the pointer API: be safe
        return True
    return bool(src & dst)


def execute(
    plan: RedistributionPlan, tree: Any, *, donate: bool = True
) -> Any:
    """Run a tree plan leaf-by-leaf (donated: each source leaf is freed
    as soon as its destination exists). Stamps the MEASURED transient
    peak back onto ``plan.executed_scratch_bytes``."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten(tree)
    if len(flat) != len(plan.leaves):
        raise ValueError(
            f"plan has {len(plan.leaves)} leaves but tree has {len(flat)}"
        )
    peak = 0

    def track(n: int) -> None:
        nonlocal peak
        peak = max(peak, int(n))

    out = [
        execute_leaf(lp, leaf, donate=donate, track=track)
        for lp, leaf in zip(plan.leaves, flat)
    ]
    plan.executed_scratch_bytes = peak
    return jax.tree_util.tree_unflatten(treedef, out)
