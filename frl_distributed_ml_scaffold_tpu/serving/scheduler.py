"""Disaggregated prefill/decode serving + the multi-tenant SLO scheduler
(ISSUE 12).

Prefill is compute-bound (one big ragged forward over the prompt), decode
is bandwidth-bound (one tiny forward per token against the whole KV
pool); co-scheduling them in one engine lets a prefill burst blow up
decode TPOT tails — the colocated engine admits into EVERY free slot
inline before each decode tick, so a burst of prompt-heavy arrivals runs
several full prefills ahead of the next token. This module splits the
workload per the MPMD per-worker-program shape (arXiv 2412.14374):

- **PrefillWorker**: owns the prefill programs (bucketed ragged prefill,
  shared-prefix seeded suffix prefill) and, optionally, its own mesh
  partition — on the CPU sim a submesh of the device set (``prefill_env``
  built over a device subset), on hardware a separate slice. With a
  separate partition the worker holds its own params replica and prefill
  dispatches are ASYNC (jax async dispatch + ``Array.is_ready`` polling):
  the decode partition never waits on prefill wall time.
- **DecodeWorker**: a paged ``ServingEngine`` (serving/engine.py) driven
  with an empty queue — it only ever runs the ONE compiled decode /
  verify shape plus the handoff splice. Speculative decoding (ISSUE 11)
  rides the decode worker unchanged.
- **The handoff is a block-table SPLICE**, never a cache copy
  (``generation.splice_pool_blocks``, the same program colocated
  admission jits): the prefilled private blocks scatter into their pool
  homes and ownership moves as one host-side table-row write. When the
  partitions share the pool (the CPU-sim default) the blocks merely
  RE-OWN — zero bytes move; with a separate prefill partition exactly
  the suffix blocks transfer (``jax.device_put``, counted), the targeted
  instance of portable array redistribution (arXiv 2112.01075).
  graft-lint's ``serving:handoff`` program pins the splice clone-free
  and the perf ledger prices it at table bytes, not cache bytes.

On top sits the multi-tenant **SLO scheduler** — PR 9's deadline/shed
machinery promoted to real SLO classes:

- **Per-tenant priority queues** (``TenantSpec``): strict class priority
  ``latency > standard > best_effort``, weighted round-robin within a
  class, per-tenant and global queue bounds. A full GLOBAL queue sheds
  the newest request of the LOWEST queued class, not the arriving
  high-class request (shed ordering follows the SLO, not arrival order).
- **Decoupled prefill/decode admission**: at most
  ``prefill_max_per_tick`` prefills start per decode tick, so a prefill
  burst DEFERS in the queue while running decodes keep their cadence —
  the tail-isolation mechanism ``serve_bench``'s ``*_disagg`` arm
  measures.
- **Decode-slot preemption**: a latency-class handoff with no free slot
  PARKS a best-effort slot (``ServingEngine.park_slot`` — free, because
  the paged pool keeps the parked request's blocks owned) and takes it;
  the parked request resumes later (``resume_parked`` — a table re-own
  plus one cursor pointer-move) and completes token-identically.

Failure semantics extend PR 9's never-hangs contract across the worker
boundary: a prefill-worker death or handoff failure (fault sites
``serve.prefill_worker`` / ``serve.handoff``) releases the pool
reservation and RE-QUEUES the request at the head of its tenant queue,
bounded by ``handoff_retries`` before a typed ``"error"`` completion.
"""

from __future__ import annotations

import collections
import dataclasses
import re
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from frl_distributed_ml_scaffold_tpu import faults
from frl_distributed_ml_scaffold_tpu.config.schema import ServingConfig
from frl_distributed_ml_scaffold_tpu.models.generation import (
    blocks_for_tokens,
    cache_capacity_axis,
    next_cache_bucket,
)
from frl_distributed_ml_scaffold_tpu.serving.engine import (
    Completion,
    ServeRequest,
    ServingEngine,
)
from frl_distributed_ml_scaffold_tpu.telemetry import MetricsRegistry, Tracer

#: SLO classes in strict priority order: a class admits (and, for
#: ``latency``, preempts) ahead of every class to its right.
SLO_CLASSES = ("latency", "standard", "best_effort")
_RANK = {c: i for i, c in enumerate(SLO_CLASSES)}


def _sanitize(name: str) -> str:
    """Tenant name -> metric-name-safe suffix."""
    return re.sub(r"[^A-Za-z0-9_]", "_", name)


def _capacity_slice(tree, start_tok: int, stop_tok: int, cap: int):
    """Slice a slot-cache tree's capacity-bearing leaves
    (``generation.cache_capacity_axis`` — K/V and scale stacks at
    capacity ``cap``) to positions ``[start_tok, stop_tok)``;
    bookkeeping leaves pass through. The cross-partition handoff moves
    ONLY this window — the blocks that change owner — never the whole
    bucketed cache."""

    def leaf(e):
        ax = cache_capacity_axis(e, cap)
        if ax is None:
            return e
        return jax.lax.slice_in_dim(e, start_tok, stop_tok, axis=ax)

    return jax.tree.map(leaf, tree)


def _capacity_pad(tree, cap_from: int, cap_to: int):
    """Inverse of ``_capacity_slice`` for the receiving partition: pad
    capacity-bearing leaves from ``cap_from`` back to ``cap_to`` (the
    padded region is exactly the zeros the un-sliced tree carried, so
    the downstream program sees an identical cache)."""

    def leaf(e):
        ax = cache_capacity_axis(e, cap_from)
        if ax is None:
            return e
        pad = [(0, 0)] * e.ndim
        pad[ax] = (0, cap_to - cap_from)
        return jnp.pad(e, pad)

    return jax.tree.map(leaf, tree)


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's SLO contract.

    ``slo_class`` orders admission (and preemption rights: only
    ``latency`` tenants preempt, and only ``best_effort`` slots are
    preemptible); ``weight`` is the weighted-round-robin share WITHIN a
    class; ``max_queue_depth`` bounds this tenant's own queue (0 = only
    the scheduler's global bound applies); ``default_deadline_s`` stamps
    requests that pass no explicit deadline."""

    name: str
    slo_class: str = "standard"
    weight: int = 1
    max_queue_depth: int = 0
    default_deadline_s: float = 0.0

    def __post_init__(self):
        if self.slo_class not in SLO_CLASSES:
            raise ValueError(
                f"tenant {self.name!r}: slo_class={self.slo_class!r} "
                f"unknown (want one of {SLO_CLASSES})"
            )
        if self.weight < 1:
            raise ValueError(
                f"tenant {self.name!r}: weight={self.weight} < 1"
            )


@dataclasses.dataclass
class _Package:
    """One in-flight prefill→decode handoff: the request, its pool
    reservation, and the (possibly still-computing) prefill outputs."""

    req: ServeRequest
    res: dict
    spec: TenantSpec
    t_launch: float
    seq: int
    tok: Any  # [1] device array (un-fetched: async failures surface at get)
    slot_cache: Any
    s_p: int
    s_c: int
    m: int
    l_suf: int
    #: The RNG split this attempt consumed (reused verbatim on retry —
    #: the worker-failure rng-neutrality contract).
    rng: Any = None
    #: Stamped when the prefill COMPLETED (readiness confirmed) — the
    #: honest end of prefill wall time; slot-wait in the ready list is
    #: queueing, not prefill, and must not pollute TTFT.
    t_ready: float = 0.0


class PrefillWorker:
    """The prefill half of the disaggregated engine: owns the prefill
    jit caches and (optionally) a separate mesh partition with its own
    params replica. Stateless across requests — every package it emits
    is self-contained, which is what makes worker death recoverable by
    re-queueing (nothing to reconstruct)."""

    def __init__(
        self,
        model: Any,
        params: Any,
        *,
        sample_kw: dict,
        min_bucket: int,
        seq_len: int,
        shared_env: Any,
        partition: Any = None,
    ):
        self.model = model
        self.seq_len = seq_len
        self.min_bucket = int(min_bucket)
        self._sample_kw = dict(sample_kw)
        #: None = share the decode partition (programs trace under the
        #: decode mesh env; the handoff is a pure re-own). A MeshEnv
        #: over a device subset = a separate partition: params are
        #: replicated onto it and prefills run (async) there.
        self.partition = partition
        self._shared_env = shared_env
        #: Cost record of the replica placement when it rode the
        #: redistribution service (the Checkpointer.last_restore_plan
        #: convention); None on the shared partition / device_put path.
        self.replica_plan = None
        if partition is not None:
            # The worker's replica rides the redistribution service
            # (ISSUE 15) when the decode-side shards are addressable
            # (single-process): leaf-at-a-time bounded assembly with a
            # plan recording what moved. Multi-process falls back to
            # the plain device_put — the chunked executor needs every
            # source shard in-process, and a worker replica must never
            # fail to construct over an accounting nicety.
            import jax as _jax

            from frl_distributed_ml_scaffold_tpu import redistribute
            from jax.sharding import PartitionSpec as P

            addressable = all(
                getattr(l, "is_fully_addressable", True)
                for l in _jax.tree_util.tree_leaves(params)
            )
            if addressable:
                params, self.replica_plan = redistribute.to_mesh(
                    params, partition, spec_of=lambda _p, _l: P()
                )
            else:
                params = jax.device_put(params, partition.replicated())
        self.params = params
        self._prefill_jit: dict[int, Any] = {}
        self._seeded_jit: dict[tuple[int, int], Any] = {}

    @property
    def separate(self) -> bool:
        return self.partition is not None

    def _ctx(self):
        from frl_distributed_ml_scaffold_tpu.dist.mesh import mesh_context

        return mesh_context(
            self.partition if self.partition is not None else self._shared_env
        )

    def _bucket_for(self, needed: int) -> int:
        return next_cache_bucket(self.seq_len, needed, floor=self.min_bucket)

    def _model_at(self, cache_len: int):
        return self.model.clone(cache_len=int(cache_len))

    def _prefill_fn(self, s_p: int):
        from frl_distributed_ml_scaffold_tpu.serving.engine import (
            make_prefill_program,
        )

        if s_p not in self._prefill_jit:
            self._prefill_jit[s_p] = make_prefill_program(
                self._model_at(s_p), self._sample_kw
            )
        return self._prefill_jit[s_p]

    def _prefill_seeded_fn(self, s_p: int, s_c: int):
        from frl_distributed_ml_scaffold_tpu.serving.engine import (
            make_seeded_prefill_program,
        )

        if (s_p, s_c) not in self._seeded_jit:
            self._seeded_jit[(s_p, s_c)] = make_seeded_prefill_program(
                self._model_at(s_c), self._sample_kw
            )
        return self._seeded_jit[(s_p, s_c)]

    def prefill(
        self, req: ServeRequest, res: dict, rng, *,
        block_size: int, seed_cache: Any = None,
    ) -> tuple[Any, Any, int, int, int, int]:
        """Run (dispatch) the request's prefill; returns the un-fetched
        package ``(tok, slot_cache, s_p, s_c, m, l_suf)`` by the shared
        ``engine.prefill_request`` recipe — the exact code colocated
        ``_prefill_package`` runs — against THIS worker's
        params/partition, so the two admission paths cannot drift.
        Consults the ``serve.prefill_worker`` fault site; on a separate
        partition the dispatch is async, so program failures surface at
        the scheduler's readiness check and take the same re-queue
        path."""
        from frl_distributed_ml_scaffold_tpu.serving.engine import (
            prefill_request,
        )

        faults.maybe_raise("serve.prefill_worker", key=req.id)
        with self._ctx():
            return prefill_request(
                req, res, rng,
                block_size=block_size, bucket_for=self._bucket_for,
                params=self.params, prefill_fn=self._prefill_fn,
                seeded_fn=self._prefill_seeded_fn, seed_cache=seed_cache,
            )


class DisaggServingEngine:
    """The disaggregated serving facade: ``ServingEngine``'s public face
    (submit/step/run/close, typed ``Completion``s) over a PrefillWorker
    + DecodeWorker pair coordinated by the multi-tenant SLO scheduler.
    Paged-cache only — the handoff is a block-table splice.

    Usage::

        eng = DisaggServingEngine(
            model, params, num_slots=4, kv_block_size=16,
            tenants=[TenantSpec("fg", "latency"),
                     TenantSpec("bg", "best_effort")],
        )
        eng.submit(prompt, max_new_tokens=32, tenant="fg")
        done = eng.run()
    """

    def __init__(
        self,
        model: Any,
        params: Any,
        *,
        num_slots: int = 4,
        eos_id: int | None = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        rng: jax.Array | None = None,
        min_bucket: int = 8,
        serving: ServingConfig | None = None,
        max_queue_depth: int = 0,
        default_deadline_s: float = 0.0,
        kv_block_size: int = 0,
        kv_pool_blocks: int = 0,
        prefix_cache: bool | None = None,
        speculate: str | None = None,
        speculate_k: int = 0,
        draft_model: Any = None,
        draft_params: Any = None,
        tenants: Sequence[TenantSpec] | None = None,
        prefill_env: Any = None,
        prefill_max_per_tick: int | None = None,
        handoff_retries: int | None = None,
        telemetry: MetricsRegistry | None = None,
        tracer: Tracer | None = None,
        stall_timeout_s: float = 0.0,
        stall_dump_path: str | None = None,
        stall_first_beat_scale: float = 5.0,
    ):
        if serving is not None:
            if (max_queue_depth or default_deadline_s or kv_block_size
                    or kv_pool_blocks or prefix_cache is not None
                    or speculate is not None or speculate_k):
                raise ValueError(
                    "pass either serving=ServingConfig(...) or the "
                    "scalar knobs, not both"
                )
            max_queue_depth = serving.max_queue_depth
            default_deadline_s = serving.default_deadline_s
            kv_block_size = serving.kv_block_size
            if prefill_max_per_tick is None:
                prefill_max_per_tick = serving.prefill_max_per_tick
            if handoff_retries is None:
                handoff_retries = serving.handoff_retries
            # The decode worker never sheds or deadline-checks at its
            # (empty) queue — the scheduler owns admission policy.
            decode_serving = dataclasses.replace(
                serving, max_queue_depth=0, default_deadline_s=0.0,
                disaggregate=False,
            )
        else:
            decode_serving = None
        if kv_block_size <= 0:
            raise ValueError(
                "disaggregated serving requires the paged cache "
                "(kv_block_size > 0): the prefill→decode handoff is a "
                "block-table splice — the bucketed cache would need a "
                "cache copy, which is exactly what this engine exists "
                "to avoid"
            )
        self.prefill_max_per_tick = int(
            1 if prefill_max_per_tick is None else prefill_max_per_tick
        )
        if self.prefill_max_per_tick < 1:
            raise ValueError(
                f"prefill_max_per_tick={self.prefill_max_per_tick} < 1: "
                "the scheduler could never admit"
            )
        self.handoff_retries = int(
            2 if handoff_retries is None else handoff_retries
        )
        self.max_queue_depth = int(max_queue_depth)
        self.default_deadline_s = float(default_deadline_s)
        self._rng = jax.random.key(0) if rng is None else rng

        # The decode worker: a paged ServingEngine driven with an empty
        # queue (the scheduler admits via admit_handoff, never submit).
        decode_kw = (
            dict(serving=decode_serving) if decode_serving is not None
            else dict(
                kv_block_size=kv_block_size, kv_pool_blocks=kv_pool_blocks,
                prefix_cache=prefix_cache, speculate=speculate,
                speculate_k=speculate_k,
            )
        )
        self.decode = ServingEngine(
            model, params,
            num_slots=num_slots, eos_id=eos_id, temperature=temperature,
            top_k=top_k, top_p=top_p, min_bucket=min_bucket,
            draft_model=draft_model, draft_params=draft_params,
            telemetry=telemetry, tracer=tracer,
            stall_timeout_s=stall_timeout_s, stall_dump_path=stall_dump_path,
            stall_first_beat_scale=stall_first_beat_scale,
            **decode_kw,
        )
        self.prefill_worker = PrefillWorker(
            self.decode.model, self.decode.params,
            sample_kw=self.decode._sample_kw,
            min_bucket=self.decode.min_bucket,
            seq_len=self.decode.seq_len,
            shared_env=self.decode._env,
            partition=prefill_env,
        )

        # Tenant registry + queues. Unknown tenants at submit() register
        # themselves with the default (standard, weight 1) contract, so
        # single-tenant callers never touch TenantSpec.
        self._tenants: dict[str, TenantSpec] = {}
        self._queues: dict[str, collections.deque[ServeRequest]] = {}
        self._rr_cycle: dict[str, list[str]] = {c: [] for c in SLO_CLASSES}
        self._rr_pos: dict[str, int] = {c: 0 for c in SLO_CLASSES}
        self._tenant_of: dict[int, str] = {}
        self._retries: dict[int, int] = {}
        # RNG key a failed attempt consumed, reused verbatim on the
        # retry: a worker failure must not shift any request's sampling
        # stream (the chaos token-identity contract for temperature>0 —
        # the disaggregated analog of colocated _try_admit's rng
        # rollback, which cannot work here because other launches may
        # split between failure and retry).
        self._retry_rng: dict[int, Any] = {}
        self._inflight: list[_Package] = []
        self._ready: list[_Package] = []
        self._parked: list[dict] = []  # {state, spec, seq}
        self._seq = 0
        self._stats = collections.Counter()

        t = self.telemetry
        self._m_t_ttft: dict[str, Any] = {}
        self._m_t_tpot: dict[str, Any] = {}
        self._m_t_shed: dict[str, Any] = {}
        self._m_handoff = t.histogram(
            "serve_handoff_seconds",
            help="prefill→decode handoff latency (transfer + splice; "
            "the block-table re-own — prefill wall time excluded)",
        )
        self._m_handoffs = t.counter(
            "serve_handoff_total", help="prefill→decode handoffs spliced"
        )
        self._m_handoff_failures = t.counter(
            "serve_handoff_failures_total",
            help="handoff splices that failed (request re-queued)",
        )
        self._m_pw_failures = t.counter(
            "serve_prefill_worker_failures_total",
            help="prefill-worker failures (request re-queued)",
        )
        self._m_preempt = t.counter(
            "serve_preemption_total",
            help="best-effort decode slots parked for latency-class "
            "handoffs",
        )
        self._m_resume = t.counter(
            "serve_resume_total", help="parked requests resumed"
        )
        self._m_parked_g = t.gauge(
            "serve_parked_requests", help="requests currently parked"
        )
        self._m_deferred = t.counter(
            "serve_prefill_deferred_total",
            help="scheduler ticks that deferred queued prefills "
            "(decoupled admission: the burst queues, decodes keep cadence)",
        )
        self._m_transfer = t.counter(
            "serve_handoff_transfer_bytes_total",
            help="bytes moved across partitions at handoff (0 when the "
            "partitions share the pool — the blocks merely re-own)",
        )
        for spec in tenants or ():
            self.register_tenant(spec)

    # ------------------------------------------------------------- plumbing

    @property
    def telemetry(self) -> MetricsRegistry:
        return self.decode.telemetry

    @property
    def paged(self) -> bool:
        return True

    @property
    def num_slots(self) -> int:
        return self.decode.num_slots

    @property
    def eos_id(self):
        return self.decode.eos_id

    @property
    def bucket(self) -> int:
        return self.decode.bucket

    @property
    def block_size(self) -> int:
        return self.decode.block_size

    @property
    def pool_blocks(self) -> int:
        return self.decode.pool_blocks

    @property
    def stats(self) -> collections.Counter:
        merged = collections.Counter(self.decode.stats)
        merged.update(self._stats)
        return merged

    def block_bytes(self) -> int:
        return self.decode.block_bytes()

    def bytes_per_slot(self) -> int:
        return self.decode.bytes_per_slot()

    def pool_utilization(self) -> float:
        return self.decode.pool_utilization()

    def export_trace(self, path: str) -> None:
        self.decode.export_trace(path)

    def close(self) -> None:
        self.decode.close()

    def reset_cache(self) -> None:
        """The serve_bench warm-up contract, facade-wide."""
        if self.pending:
            raise RuntimeError("reset_cache with requests in flight")
        self.decode.reset_cache()
        self._stats.clear()
        self._retries.clear()
        self._retry_rng.clear()
        self._tenant_of.clear()

    @property
    def pending(self) -> int:
        return (
            sum(len(q) for q in self._queues.values())
            + len(self._inflight)
            + len(self._ready)
            + len(self._parked)
            + int(self.decode._active.sum())
        )

    # ------------------------------------------------------------- frontend

    def register_tenant(self, spec: TenantSpec) -> None:
        if spec.name in self._tenants:
            raise ValueError(f"tenant {spec.name!r} already registered")
        clash = next(
            (n for n in self._tenants if _sanitize(n) == _sanitize(spec.name)),
            None,
        )
        if clash is not None:
            raise ValueError(
                f"tenant {spec.name!r} collides with {clash!r} after metric-"
                f"name sanitization ({_sanitize(spec.name)!r}) — their "
                "per-tenant histograms/counters would silently merge"
            )
        self._tenants[spec.name] = spec
        self._queues[spec.name] = collections.deque()
        # Weighted round-robin: the tenant appears ``weight`` times in
        # its class's cycle, so a weight-3 tenant gets 3 of every
        # (3 + peers) admissions while both have queued work.
        self._rr_cycle[spec.slo_class].extend([spec.name] * spec.weight)
        t, s = self.telemetry, _sanitize(spec.name)
        self._m_t_ttft[spec.name] = t.histogram(
            f"serve_ttft_seconds_tenant_{s}",
            help=f"TTFT, tenant {spec.name} ({spec.slo_class})",
        )
        self._m_t_tpot[spec.name] = t.histogram(
            f"serve_tpot_seconds_tenant_{s}",
            help=f"inter-token gap, tenant {spec.name} ({spec.slo_class})",
        )
        self._m_t_shed[spec.name] = t.counter(
            f"serve_shed_total_tenant_{s}",
            help=f"requests shed, tenant {spec.name}",
        )

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        request_id: int | None = None,
        *,
        deadline_s: float | None = None,
        tenant: str = "default",
    ) -> int:
        """Enqueue under ``tenant``'s SLO contract; returns the id.
        Sheds are typed (ISSUE 9) and SLO-ordered: a full global queue
        sheds the newest request of the LOWEST queued class to make room
        for a higher-class arrival."""
        spec = self._tenants.get(tenant)
        if spec is None:
            spec = TenantSpec(name=tenant)
            self.register_tenant(spec)
        if deadline_s is None and spec.default_deadline_s:
            deadline_s = spec.default_deadline_s
        req = self.decode._new_request(
            prompt, max_new_tokens, request_id,
            deadline_s=(self.default_deadline_s if deadline_s is None
                        else deadline_s),
        )
        self._tenant_of[req.id] = tenant
        q = self._queues[tenant]
        if spec.max_queue_depth and len(q) >= spec.max_queue_depth:
            self._shed(req, spec)
            return req.id
        if self.max_queue_depth:
            total = sum(len(qq) for qq in self._queues.values())
            if total >= self.max_queue_depth:
                victim = self._shed_victim(than=spec)
                if victim is None:
                    self._shed(req, spec)
                    return req.id
                vq, vspec = victim
                self._shed(vq.pop(), vspec)
        q.append(req)
        return req.id

    def _shed(self, req: ServeRequest, spec: TenantSpec) -> None:
        self.decode._m_shed.inc()
        self._m_t_shed[spec.name].inc()
        self._stats[f"shed_{spec.name}"] += 1
        self.decode._complete_unadmitted(req, "shed")

    def _shed_victim(self, than: TenantSpec):
        """The newest queued request of the lowest class STRICTLY below
        ``than`` — the request the SLO ordering says to sacrifice when
        the global queue is full. Lowest class first; among same-class
        tenants, the one whose queue TAIL arrived last (each queue is
        FIFO, so the tail is that tenant's newest). None = nothing
        outranked (the arrival itself sheds)."""
        best = None  # (rank, tail t_submit, name)
        for name, q in self._queues.items():
            if not q:
                continue
            r = _RANK[self._tenants[name].slo_class]
            if r <= _RANK[than.slo_class]:
                continue
            key = (r, q[-1].t_submit)
            if best is None or key > (best[0], best[1]):
                best = (r, q[-1].t_submit, name)
        if best is None:
            return None
        name = best[2]
        return self._queues[name], self._tenants[name]

    # ----------------------------------------------------------- scheduling

    def _next_request(self):
        """Highest-class, weighted-round-robin queued request (queued
        past-deadline requests shed typed on the way, like colocated
        ``_admit``). Returns ``(queue, req, spec, rr)`` WITHOUT popping
        or advancing the round-robin cursor — the caller pops AND
        commits ``rr`` only once the request actually launches, so a
        deferred head request (pool headroom, slot capacity) keeps its
        turn: same-class peers must not jump it on later ticks (the
        colocated FIFO-within-class contract; advancing eagerly would
        let a stream of small peers starve a large deferred head)."""
        for cls in SLO_CLASSES:
            order = self._rr_cycle[cls]
            n = len(order)
            start = self._rr_pos[cls] % n if n else 0
            for i in range(n):
                name = order[(start + i) % n]
                q = self._queues[name]
                while q:
                    req = q[0]
                    if self.decode._expired(req):
                        q.popleft()
                        self.decode._m_deadline.inc()
                        self.decode._complete_unadmitted(req, "deadline")
                        continue
                    return (
                        q, req, self._tenants[name],
                        (cls, (start + i + 1) % n),
                    )
        return None

    def _commit_rr(self, rr) -> None:
        cls, pos = rr
        self._rr_pos[cls] = pos

    def _preemptible_slots(self) -> list[int]:
        """Active decode slots owned by best-effort tenants (the only
        preemptible class), most-remaining-budget first."""
        out = []
        for slot in np.flatnonzero(self.decode._active):
            slot = int(slot)
            req = self.decode._req[slot]
            spec = self._tenants.get(self._tenant_of.get(req.id, ""), None)
            if spec is not None and spec.slo_class == "best_effort":
                remaining = req.max_new_tokens - len(self.decode._tokens[slot])
                out.append((remaining, slot))
        return [s for _, s in sorted(out, reverse=True)]

    def _launch_prefills(self) -> None:
        """Start up to ``prefill_max_per_tick`` prefills — the decoupled
        admission bound. A prefill only launches when a handoff target
        exists (a free slot net of in-flight handoffs, or — for a
        latency-class request — a preemptible best-effort slot); pool
        headroom defers the head request exactly like colocated
        admission (FIFO within the class, typed sheds via the queue
        bound under sustained pressure)."""
        launched = 0
        while launched < self.prefill_max_per_tick:
            pick = self._next_request()
            if pick is None:
                break
            q, req, spec, rr = pick
            free = int((~self.decode._active).sum())
            pending = len(self._inflight) + len(self._ready)
            # Parked requests do NOT reserve slots here: they already
            # outrank non-latency handoffs at placement time (resumes
            # run before ``_place_ready(only_latency=False)``), and
            # counting them would deadlock against the resume guard —
            # a queued latency request and a parked best-effort victim
            # each waiting for the other's slot.
            cap = free - pending
            if cap <= 0 and spec.slo_class == "latency":
                n_lat_pending = sum(
                    1 for p in self._inflight + self._ready
                    if p.spec.slo_class == "latency"
                )
                cap += max(
                    0, len(self._preemptible_slots()) - n_lat_pending
                )
            if cap <= 0:
                self._stats["prefill_deferred"] += 1
                self._m_deferred.inc()
                break
            res = self.decode._pool_reserve(req)
            if res is None:
                self._stats["admission_deferred"] += 1
                self._m_deferred.inc()
                break
            q.popleft()
            self._commit_rr(rr)
            t_launch = time.perf_counter()
            self.decode._phase(
                "queue_wait", t0=req.t_submit,
                dur_s=t_launch - req.t_submit,
                trace=req.trace, parent=req.span, tenant=spec.name,
            )
            sub = self._retry_rng.pop(req.id, None)
            if sub is None:
                self._rng, sub = jax.random.split(self._rng)
            try:
                # The shared-prefix seed gathers from the POOL — the
                # decode partition's memory (the shared seed half of the
                # admission recipe, ``engine._seed_for``) — and crosses
                # to the prefill partition with the package's arrays.
                with self.decode._trace_ctx():
                    seed_cache = self.decode._seed_for(req, res)
                if seed_cache is not None and self.prefill_worker.separate:
                    # Transfer only the OCCUPIED prefix (m blocks); the
                    # zero tail of the s_c-capacity seed is re-padded on
                    # the prefill partition — the link carries the data,
                    # not the bucket.
                    m_tok = res["m"] * self.decode.block_size
                    s_c = self.decode._bucket_for(int(req.prompt.size))
                    seed_cache, moved = self._put(
                        _capacity_slice(seed_cache, 0, m_tok, s_c),
                        self.prefill_worker.partition,
                    )
                    self._count_transfer(moved)
                    seed_cache = _capacity_pad(seed_cache, m_tok, s_c)
                tok, slot_cache, s_p, s_c, m, l_suf = (
                    self.prefill_worker.prefill(
                        req, res, sub,
                        block_size=self.decode.block_size,
                        seed_cache=seed_cache,
                    )
                )
            except Exception as e:
                self._worker_failed(
                    req, res, spec, e, site="prefill_worker", rng=sub
                )
                continue
            self._seq += 1
            self._inflight.append(_Package(
                req=req, res=res, spec=spec, t_launch=t_launch,
                seq=self._seq, tok=tok, slot_cache=slot_cache,
                s_p=s_p, s_c=s_c, m=m, l_suf=l_suf, rng=sub,
            ))
            self._stats["prefills_launched"] += 1
            launched += 1
        if launched >= self.prefill_max_per_tick and any(
            self._queues.values()
        ):
            # Budget exhausted with work still queued: the deferral the
            # decoupling exists for.
            self._stats["prefill_deferred"] += 1
            self._m_deferred.inc()

    def _poll_inflight(self, block: bool = False) -> None:
        """Move completed prefills to the ready list, stamping
        ``t_ready`` (the end of honest prefill wall time — slot-wait in
        the ready list is queueing, not prefill). Shared partition: the
        package completes here, paying the same wait colocated
        admission's token fetch pays. Separate partition: readiness is
        polled (``Array.is_ready``) so the decode tick never waits on
        prefill wall time; ``block=True`` forces the oldest package (the
        progress guarantee when nothing is decoding). A prefill program
        that FAILED surfaces here — before any preemption decision could
        park a victim for a package that can never splice — and takes
        the prefill-worker re-queue path."""
        still: list[_Package] = []
        for i, pkg in enumerate(self._inflight):
            ready = (
                not self.prefill_worker.separate
                or (block and i == 0 and not still)
                or not hasattr(pkg.tok, "is_ready")
                or pkg.tok.is_ready()
            )
            if not ready:
                still.append(pkg)
                continue
            try:
                jax.block_until_ready(pkg.tok)
            except Exception as e:
                self._worker_failed(
                    pkg.req, pkg.res, pkg.spec, e,
                    site="prefill_worker", rng=pkg.rng,
                )
                continue
            pkg.t_ready = time.perf_counter()
            self._ready.append(pkg)
        self._inflight = still

    def _fill_slots(self) -> None:
        """Place ready handoffs + resume parked requests, SLO-ordered:
        expired parked requests retire typed first (no slot needed —
        their blocks come straight back), then latency handoffs
        (preempting best-effort slots when full), then parked resumes
        (they hold pool blocks hostage — finishing them frees memory),
        then the remaining handoffs."""
        self._expire_parked()
        self._ready.sort(key=lambda p: (_RANK[p.spec.slo_class], p.seq))
        self._place_ready(only_latency=True)
        self._resume_parked()
        self._place_ready(only_latency=False)
        self._m_parked_g.set(float(len(self._parked)))

    def _expire_parked(self) -> None:
        """A parked request past its deadline must not hold its blocks
        hostage waiting for a slot it no longer wants: retire it typed
        ``"deadline"`` IN PLACE (``ServingEngine.retire_parked`` — the
        completion carries the tokens generated before the park, the
        blocks and worst-case reservation release immediately)."""
        still: list[dict] = []
        for entry in self._parked:
            req = entry["state"]["req"]
            if self.decode._expired(req):
                self.decode._m_deadline.inc()
                self.decode.retire_parked(entry["state"], "deadline")
            else:
                still.append(entry)
        self._parked = still

    def _free_slot(self) -> int | None:
        free = np.flatnonzero(~self.decode._active)
        return int(free[0]) if free.size else None

    def _place_ready(self, *, only_latency: bool) -> None:
        rest: list[_Package] = []
        for pkg in self._ready:
            if only_latency and pkg.spec.slo_class != "latency":
                rest.append(pkg)
                continue
            if self.decode._expired(pkg.req):
                # Expired while prefilling / waiting for a slot: resolve
                # typed NOW (queued-shed semantics — the prefill output
                # is discarded) instead of parking a healthy victim and
                # splicing for an answer nobody wants.
                self.decode._pool_release(pkg.res)
                self.decode._m_deadline.inc()
                self._retries.pop(pkg.req.id, None)
                self.decode._complete_unadmitted(pkg.req, "deadline")
                continue
            slot = self._free_slot()
            if slot is None and pkg.spec.slo_class == "latency":
                victims = self._preemptible_slots()
                if victims:
                    slot = victims[0]
                    vreq = self.decode._req[slot]
                    vspec = self._tenants[self._tenant_of[vreq.id]]
                    state = self.decode.park_slot(slot)
                    self._parked.append(
                        {"state": state, "spec": vspec, "seq": self._seq}
                    )
                    self._seq += 1
                    self._m_preempt.inc()
                    self._stats["preemptions"] += 1
            if slot is None:
                rest.append(pkg)
                continue
            self._complete_handoff(pkg, slot)
        self._ready = rest

    def _resume_parked(self) -> None:
        """Resume parked requests into free slots, class-ordered. A
        best-effort parked request stays parked while a latency handoff
        is waiting for a slot (resuming it would be preempted right
        back — thrash, not progress)."""
        latency_waiting = any(
            p.spec.slo_class == "latency"
            for p in self._inflight + self._ready
        ) or any(
            q and self._tenants[n].slo_class == "latency"
            for n, q in self._queues.items()
        )
        self._parked.sort(
            key=lambda e: (_RANK[e["spec"].slo_class], e["seq"])
        )
        still: list[dict] = []
        for entry in self._parked:
            slot = self._free_slot()
            if slot is None or (
                latency_waiting
                and entry["spec"].slo_class == "best_effort"
            ):
                still.append(entry)
                continue
            self.decode.resume_parked(entry["state"], slot)
            self._m_resume.inc()
        self._parked = still

    def _count_transfer(self, moved: int) -> None:
        self._stats["handoff_transfer_bytes"] += moved
        self._m_transfer.inc(moved)

    @staticmethod
    def _put(tree, target) -> tuple[Any, int]:
        """Move a pytree to ``target`` — a ``MeshEnv`` (replicated onto
        its partition) or a bare device — returning the tree and its
        byte count (the cross-partition handoff traffic, ONE site so
        meshed and unmeshed workers price transfers identically)."""
        moved = sum(
            int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
            for l in jax.tree.leaves(tree)
        )
        if hasattr(target, "replicated"):
            target = target.replicated()
        return jax.device_put(tree, target), moved

    def _complete_handoff(self, pkg: _Package, slot: int) -> None:
        """Fetch the package (async prefill failures surface HERE and
        take the prefill-worker re-queue path), transfer its private
        blocks to the decode partition when the partitions are separate,
        and splice. The splice is the ONLY decode-partition work — a
        table re-own when the pool is shared."""
        req, res, spec = pkg.req, pkg.res, pkg.spec
        try:
            tok = int(jax.device_get(pkg.tok)[0])
        except Exception as e:
            self._worker_failed(
                req, res, spec, e, site="prefill_worker", rng=pkg.rng
            )
            return
        # Prefill wall = launch→completion (t_ready, stamped at the
        # readiness check); slot-wait in the ready list is queueing and
        # stays out of TTFT, per the engine's TTFT contract.
        prefill_s = (pkg.t_ready or time.perf_counter()) - pkg.t_launch
        t_h0 = time.perf_counter()
        try:
            faults.maybe_raise("serve.handoff", key=req.id)
            slot_cache = pkg.slot_cache
            sliced = False
            if self.prefill_worker.separate:
                # Transfer EXACTLY the private blocks that change owner
                # — the [m*bs, n_g*bs) capacity window (shared prefix
                # blocks already live in the decode partition's pool;
                # the bucket's zero tail carries nothing). The splice
                # then reads the window at m0=0.
                bs = self.decode.block_size
                n_g = blocks_for_tokens(int(req.prompt.size), bs)
                slot_cache, moved = self._put(
                    _capacity_slice(
                        slot_cache, pkg.m * bs, n_g * bs, pkg.s_c
                    ),
                    self.decode._env if self.decode._env is not None
                    else jax.devices()[0],
                )
                self._count_transfer(moved)
                sliced = True
            self.decode.admit_handoff(
                slot, req, res, slot_cache, tok,
                m=pkg.m, prefill_s=prefill_s, sliced=sliced,
            )
        except Exception as e:
            self._worker_failed(req, res, spec, e, site="handoff",
                                rng=pkg.rng)
            return
        dt = time.perf_counter() - t_h0
        self._m_handoff.observe(dt)
        self._m_handoffs.inc()
        self._stats["handoffs"] += 1
        self._retries.pop(req.id, None)
        self._m_t_ttft[spec.name].observe(prefill_s + dt)

    def _worker_failed(
        self, req: ServeRequest, res: dict, spec: TenantSpec,
        err: Exception, *, site: str, rng: Any = None,
    ) -> None:
        """The cross-worker never-hangs contract (ISSUE 9 extended):
        release the reservation, count, re-queue at the head of the
        tenant queue; past ``handoff_retries`` the request resolves as a
        typed ``"error"`` — a worker death can delay a request, never
        strand it."""
        self.decode._pool_release(res)
        counter = (
            self._m_pw_failures if site == "prefill_worker"
            else self._m_handoff_failures
        )
        counter.inc()
        self._stats[f"{site}_failures"] += 1
        from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

        n = self._retries.get(req.id, 0) + 1
        self._retries[req.id] = n
        if n > self.handoff_retries:
            get_logger().warning(
                "serving: %s failed for request %d (%s: %s) — retries "
                "exhausted (%d), resolving typed error",
                site, req.id, type(err).__name__, err, self.handoff_retries,
            )
            self._retries.pop(req.id, None)
            self._retry_rng.pop(req.id, None)
            self.decode._m_quarantined.inc()
            self.decode.stats["quarantined"] += 1
            self.decode._complete_unadmitted(req, "error")
            return
        get_logger().warning(
            "serving: %s failed for request %d (%s: %s) — re-queueing "
            "(attempt %d/%d)",
            site, req.id, type(err).__name__, err, n, self.handoff_retries,
        )
        self._stats[f"{site}_requeued"] += 1
        if rng is not None:
            # The retry reuses this attempt's split, so the request's
            # sampling stream — and every later request's — matches a
            # fault-free run (rng-neutral chaos, temperature>0 included).
            self._retry_rng[req.id] = rng
        self._queues[spec.name].appendleft(req)

    # ----------------------------------------------------------------- step

    def step(self) -> list[Completion]:
        """One scheduler tick: complete ready handoffs, resume parked
        requests, launch (at most ``prefill_max_per_tick``) prefills,
        then run ONE decode iteration. Returns completions, tenant-
        annotated, typed resolutions included."""
        self._poll_inflight()
        self._fill_slots()
        self._launch_prefills()
        self._poll_inflight()
        self._fill_slots()
        if (
            self._inflight
            and not self._ready
            and not self.decode._active.any()
        ):
            # Progress guarantee: nothing is decoding and everything
            # outstanding is an un-ready async prefill — block on the
            # oldest (the one wait colocated admission always pays).
            self._poll_inflight(block=True)
            self._fill_slots()
        out = self.decode.step()
        self.decode._m_queue.set(
            float(sum(len(q) for q in self._queues.values()))
        )
        for c in out:
            self._annotate(c)
        return out

    def run(self, max_steps: int | None = None) -> list[Completion]:
        """Drain everything; the engine ``run`` contract (every
        submitted id resolves exactly once, typed resolutions ride
        along)."""
        out: list[Completion] = []
        steps = 0
        while self.pending:
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        tail = self.decode._drain_completed() + list(self.decode._early)
        self.decode._early.clear()
        for c in tail:
            self._annotate(c)
        out.extend(tail)
        return out

    def _annotate(self, c: Completion) -> None:
        """Tenant attribution + per-tenant SLO observations (TPOT as
        inter-token GAPS — the number a tenant actually experiences,
        inline prefill stalls included, unlike the program-time
        ``token_latencies_s``)."""
        name = self._tenant_of.pop(c.id, "")
        self._retries.pop(c.id, None)
        self._retry_rng.pop(c.id, None)
        c.tenant = name
        h = self._m_t_tpot.get(name)
        if h is not None and len(c.token_times_s) > 1:
            for gap in np.diff(np.asarray(c.token_times_s)):
                h.observe(float(gap))
