"""Decode-optimized serving subsystem (the inference counterpart of the
training-side overlap schedules).

Three layers: the fused split-KV decode kernel (ops/decode_attention.py),
the model-sharded KV cache the GPT decode path emits under a live
``model`` mesh axis (models/gpt.py), and the host-side continuous-batching
engine here — a fixed slot array with per-slot length tracking, eos
retirement, and power-of-two cache buckets (serving/engine.py).
"""

from frl_distributed_ml_scaffold_tpu.serving.engine import (
    CacheGrowError,
    Completion,
    ServeRequest,
    ServingEngine,
    ngram_propose,
)

__all__ = [
    "CacheGrowError",
    "Completion",
    "ServeRequest",
    "ServingEngine",
    "ngram_propose",
]
