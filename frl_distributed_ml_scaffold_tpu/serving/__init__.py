"""Decode-optimized serving subsystem (the inference counterpart of the
training-side overlap schedules).

Four layers: the fused split-KV decode kernel (ops/decode_attention.py),
the model-sharded KV cache the GPT decode path emits under a live
``model`` mesh axis (models/gpt.py), the host-side continuous-batching
engine — a fixed slot array with per-slot length tracking, eos
retirement, and the paged block-table KV pool (serving/engine.py) — and
the disaggregated prefill/decode split with the multi-tenant SLO
scheduler on top (serving/scheduler.py, ISSUE 12): prefill and decode
workers coordinated through block-table-splice handoffs, per-tenant
priority queues, and best-effort preemption with free park/resume.
"""

from frl_distributed_ml_scaffold_tpu.serving.engine import (
    CacheGrowError,
    Completion,
    ServeRequest,
    ServingEngine,
    ngram_propose,
)
from frl_distributed_ml_scaffold_tpu.serving.scheduler import (
    SLO_CLASSES,
    DisaggServingEngine,
    PrefillWorker,
    TenantSpec,
)


def build_engine(model, params, *, serving, rules=None, **kw):
    """Config-driven engine construction: dispatch on
    ``serving.disaggregate`` (ISSUE 12) so callers holding a
    ``ServingConfig`` get the right engine without knowing both
    constructors. ``kw`` passes through (num_slots, eos_id, tenants,
    prefill_env, telemetry, ...).

    ``rules`` (ISSUE 15): the model's TP partition rules — when given
    and a mesh context is live, params are placed onto the serving
    layout first, via ``parallel.partition.shard_params_for_serving``
    (which routes device-resident training layouts through the
    redistribution service: the train→serve handoff moves only shard
    deltas, never a replicated host round-trip)."""
    if rules is not None:
        from frl_distributed_ml_scaffold_tpu.dist.mesh import (
            current_mesh_env,
        )
        from frl_distributed_ml_scaffold_tpu.parallel.partition import (
            shard_params_for_serving,
        )

        env = current_mesh_env()
        if env is not None:
            params = shard_params_for_serving(params, env, rules)
    cls = DisaggServingEngine if serving.disaggregate else ServingEngine
    return cls(model, params, serving=serving, **kw)


__all__ = [
    "CacheGrowError",
    "Completion",
    "DisaggServingEngine",
    "PrefillWorker",
    "SLO_CLASSES",
    "ServeRequest",
    "ServingEngine",
    "TenantSpec",
    "build_engine",
    "ngram_propose",
]
