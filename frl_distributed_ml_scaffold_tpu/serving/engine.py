"""Host-side continuous-batching decode engine over a fixed slot array.

TPU serving wants the same static-shape discipline as TPU training: every
device program the engine runs is one of a SMALL closed set of compiled
shapes — a prefill per prompt bucket, a decode step per cache bucket, a
cache graft per (prompt bucket, cache bucket) pair — all powers of two up
to ``config.seq_len`` (``models/generation.next_cache_bucket``). Requests
of any length mix freely inside those shapes:

- **Slots**: the decode batch is a fixed ``[num_slots]`` row array. Each
  row is an independent request; per-row cache indices/positions
  (models/gpt.py decode path) mean rows at different occupancies decode
  together in one program.
- **Continuous batching**: when a row emits eos (or exhausts its budget)
  it RETIRES — the completion is returned and the slot is freed — and the
  next queued request is prefilled into the freed row while the other
  rows keep decoding. Admission never stalls the running rows: a prompt
  is prefilled as a [1, prompt_bucket] program and its cache rows are
  grafted into the engine cache at the slot index (a dynamic-update-slice,
  not a reshard).
- **Cache buckets**: the engine cache starts at the smallest bucket that
  covers the live requests and GROWS bucket-by-bucket (a pad along the
  cache axis) only when an active slot actually needs the room. Short
  requests therefore never pay full-context cache traffic — and the
  decode kernel additionally reads only each row's occupied prefix within
  the bucket.

Everything here is host logic around jitted pure functions; under a live
mesh (captured at construction) the same loop serves model-sharded caches
— the jitted programs trace under ``mesh_context`` so the decode
attention runs head-sharded (ops/decode_attention.py router).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from frl_distributed_ml_scaffold_tpu import faults
from frl_distributed_ml_scaffold_tpu.config.schema import ServingConfig
from frl_distributed_ml_scaffold_tpu.models.generation import (
    _decode_step,
    _plain_stack,
    _prefill,
    _sample,
    cache_batch_axis,
    cache_bytes_per_slot,
    cache_capacity_axis,
    next_cache_bucket,
)
from frl_distributed_ml_scaffold_tpu.telemetry import (
    Histogram,
    MetricsRegistry,
    StallWatchdog,
    Timeline,
    Tracer,
)


class CacheGrowError(RuntimeError):
    """Growing the KV cache to the next bucket failed (allocation failure
    at high occupancy, or the ``serve.grow`` fault site). The engine
    degrades instead of dying: requests that NEED the larger bucket are
    retired with ``finish_reason="error"``; requests that still fit keep
    decoding (see ``ServingEngine.step``)."""


@dataclasses.dataclass
class ServeRequest:
    """One queued generation request (prompt is an unpadded 1-D int array).

    ``trace``/``span``/``t_submit`` are the tracing handles (ISSUE 8):
    every request gets its own trace id at enqueue, and the root
    ``request`` span stays open from submit to retire so the exported
    trace reads as one connected tree per request. ``deadline_s`` is the
    submit-relative deadline (0 = none; ISSUE 9)."""

    id: int
    prompt: np.ndarray
    max_new_tokens: int
    trace: int = 0
    t_submit: float = 0.0
    span: Any = None
    deadline_s: float = 0.0


@dataclasses.dataclass
class Completion:
    """A finished request: prompt + generated tokens and per-token wall
    latencies (the decode steps this request was live for), plus the
    serving-SLO summary of those latencies: ``ttft_s`` (time to first
    token — the prefill) and p50/p99 time-per-output-token over the
    decode steps, computed through the telemetry histogram's log2-bucket
    quantile estimator so per-request numbers and the engine's aggregate
    ``serve_tpot_seconds`` histogram read on the same scale.

    ``finish_reason`` is the TYPED failure contract (ISSUE 9): every
    submitted request resolves to exactly one completion —
    ``"eos"``/``"length"`` (served in full), ``"shed"`` (load-shed at
    admission: queue bound hit, no tokens generated), ``"deadline"``
    (deadline passed — queued requests shed before prefill, mid-decode
    requests are cancelled carrying the tokens generated so far), or
    ``"error"`` (poison request quarantined / cache growth failed; any
    tokens generated before the fault are carried). A caller therefore
    never hangs on a faulted request and can always tell a served answer
    from a degraded one."""

    id: int
    tokens: np.ndarray  # [prompt_len + n_generated]
    prompt_len: int
    finish_reason: str  # "eos" | "length" | "shed" | "deadline" | "error"
    token_latencies_s: list[float]
    ttft_s: float = 0.0
    tpot_p50_s: float = 0.0
    tpot_p99_s: float = 0.0

    @property
    def ok(self) -> bool:
        """Served in full (not shed / expired / quarantined)."""
        return self.finish_reason in ("eos", "length")


def _log2_quantiles(vals, qs) -> list[float]:
    """Quantiles of ``vals`` through a detached log2-bucket Histogram —
    the same estimator (and thus the same 2x-granularity scale) as the
    engine's aggregate latency histograms."""
    h = Histogram(MetricsRegistry(), "q", help="")
    for v in vals:
        h.observe(v)
    return [h.quantile(q) for q in qs]


def _hbm_gib() -> dict[str, float]:
    """In-use/peak HBM GiB (empty on backends without memory stats)."""
    from frl_distributed_ml_scaffold_tpu.utils.profiling import (
        device_memory_stats,
    )

    stats = device_memory_stats()
    return {
        k: v for k, v in stats.items()
        if k in ("hbm_in_use_gib", "hbm_peak_gib")
    }


class ServingEngine:
    """Continuous-batching engine; see the module docstring.

    Usage::

        eng = ServingEngine(model, params, num_slots=4, eos_id=50256)
        eng.submit([5, 3, 8], max_new_tokens=32)
        done = eng.run()          # or step() for one decode iteration
    """

    def __init__(
        self,
        model: Any,
        params: Any,
        *,
        num_slots: int = 4,
        eos_id: int | None = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        rng: jax.Array | None = None,
        min_bucket: int = 8,
        serving: ServingConfig | None = None,
        max_queue_depth: int = 0,
        default_deadline_s: float = 0.0,
        telemetry: MetricsRegistry | None = None,
        tracer: Tracer | None = None,
        stall_timeout_s: float = 0.0,
        stall_dump_path: str | None = None,
        stall_first_beat_scale: float = 5.0,
    ):
        model, params = _plain_stack(model, params)
        self.model, self.params = model, params
        if num_slots < 1:
            raise ValueError(
                f"num_slots={num_slots} < 1: zero slots can never admit, "
                "so run() would spin on a non-empty queue forever"
            )
        self.num_slots = int(num_slots)
        self.eos_id = eos_id
        self._sample_kw = dict(
            temperature=temperature, top_k=top_k, top_p=top_p
        )
        self._rng = jax.random.key(0) if rng is None else rng
        self.min_bucket = int(min_bucket)
        self.seq_len = model.config.seq_len
        # Graceful degradation (ISSUE 9): `serving=` takes the whole
        # ServingConfig (the `serving.*` section of an ExperimentConfig)
        # — THE config-driven path; the scalar kwargs remain for callers
        # without a config. Passing both is a caller bug, refused.
        if serving is not None:
            if max_queue_depth or default_deadline_s:
                raise ValueError(
                    "pass either serving=ServingConfig(...) or the "
                    "max_queue_depth/default_deadline_s scalars, not both"
                )
            max_queue_depth = serving.max_queue_depth
            default_deadline_s = serving.default_deadline_s
        if max_queue_depth < 0:
            raise ValueError(f"max_queue_depth={max_queue_depth} < 0")
        self.max_queue_depth = int(max_queue_depth)
        self.default_deadline_s = float(default_deadline_s)

        # The mesh is captured ONCE: every jitted program traces under it,
        # so replicated and sharded engines never share a trace.
        from frl_distributed_ml_scaffold_tpu.dist.mesh import current_mesh_env

        self._env = current_mesh_env()

        self._queue: collections.deque[ServeRequest] = collections.deque()
        # Typed completions produced OUTSIDE a slot (shed at submit,
        # deadline-expired while queued, quarantined at admission) wait
        # here until the next step()/run() returns them — a faulted
        # request always resolves, never hangs.
        self._early: list[Completion] = []
        self._next_id = 0
        self._issued_ids: set[int] = set()
        # Host-side slot state.
        self._req: list[ServeRequest | None] = [None] * self.num_slots
        self._tokens: list[list[int]] = [[] for _ in range(self.num_slots)]
        self._len = np.zeros(self.num_slots, np.int64)  # prompt+generated
        self._active = np.zeros(self.num_slots, bool)
        self._latency: list[list[float]] = [[] for _ in range(self.num_slots)]
        self._last_tok = np.zeros(self.num_slots, np.int32)

        self.cache: Any = None
        self.bucket = 0
        # Jit caches keyed on the static shapes they close over.
        self._prefill_jit: dict[int, Any] = {}
        self._decode_jit: dict[int, Any] = {}
        self._graft_jit: dict[tuple[int, int], Any] = {}
        self._grow_jit: dict[tuple[int, int], Any] = {}
        # Observability: how often each compiled-shape class actually ran.
        self.stats = collections.Counter()
        # Telemetry (ISSUE 7): every metric is registered up front so both
        # exporters always carry the full serving catalog (a gauge that
        # never fired still scrapes as 0, which is itself a signal). All
        # host-side, around the jitted programs — never inside them
        # (graft-lint `metrics-in-traced` enforces this).
        self.telemetry = telemetry if telemetry is not None else MetricsRegistry()
        self.timeline = Timeline(enabled=self.telemetry.enabled)
        # Tracing (ISSUE 8): one span tree per request (trace id assigned
        # at submit), plus an "engine" lane for the slot-array-scoped
        # programs (decode steps, bucket grows). Spans tee into the
        # Timeline, so the existing drain/export path still carries the
        # phase records, while the tracer ring holds the tree for
        # export_trace(). Host-side only, same contract as the metrics.
        self.tracing = (
            tracer if tracer is not None
            else Tracer(enabled=self.telemetry.enabled, timeline=self.timeline)
        )
        # A caller-supplied tracer (its own timeline, or disabled) breaks
        # the tee into THIS engine's timeline — _phase() then falls back
        # to bare timeline events so telemetry.jsonl's phase records and
        # the watchdog's timeline tail never depend on tracing state.
        self._phases_via_tee = (
            self.tracing.enabled and self.tracing.timeline is self.timeline
        )
        self._engine_trace = self.tracing.new_trace("engine")
        t = self.telemetry
        self._m_ttft = t.histogram(
            "serve_ttft_seconds", help="time to first token (prefill+graft)"
        )
        self._m_tpot = t.histogram(
            "serve_tpot_seconds",
            help="per-output-token latency over live slots (decode steps)",
        )
        self._m_queue = t.gauge("serve_queue_depth", help="requests waiting")
        self._m_occupancy = t.gauge(
            "serve_slot_occupancy", help="active slots / num_slots"
        )
        self._m_bytes_slot = t.gauge(
            "serve_bytes_per_slot",
            help="per-slot HBM of the live cache at its current bucket",
        )
        self._m_hbm_used = t.gauge(
            "serve_hbm_in_use_gib", help="device HBM in use (0 when the "
            "backend exposes no stats, e.g. CPU sim)"
        )
        self._m_hbm_peak = t.gauge(
            "serve_hbm_peak_gib", help="device HBM high-watermark"
        )
        self._m_prefills = t.counter("serve_prefill_total", help="prefills run")
        self._m_decodes = t.counter(
            "serve_decode_steps_total", help="slot-array decode iterations"
        )
        self._m_grows = t.counter(
            "serve_bucket_grow_total", help="cache bucket growths"
        )
        self._m_grafts = t.counter(
            "serve_cache_graft_total", help="prefill-cache grafts into slots"
        )
        self._m_completed = t.counter(
            "serve_completed_total", help="requests finished"
        )
        # Failure-semantics counters (ISSUE 9): the OBSERVED side of the
        # fault ledger — chaos drills diff these against the FaultPlan's
        # injected counts to prove detection.
        self._m_shed = t.counter(
            "serve_shed_total",
            help="requests load-shed at submit (queue bound)",
        )
        self._m_deadline = t.counter(
            "serve_deadline_miss_total",
            help="requests past deadline (shed queued / cancelled decoding)",
        )
        self._m_quarantined = t.counter(
            "serve_quarantined_total",
            help="poison requests whose prefill failed (batch kept alive)",
        )
        self._m_grow_failures = t.counter(
            "serve_grow_failures_total",
            help="cache bucket growths that failed (degraded, not fatal)",
        )
        self.watchdog = StallWatchdog(
            stall_timeout_s,
            name="serve",
            registry=t,
            timeline=self.timeline,
            dump_path=stall_dump_path,
            first_beat_scale=stall_first_beat_scale,
        )

    def _phase(self, name, *, t0, dur_s, trace=None, parent=None, **attrs):
        """Span plus guaranteed phase record: the engine-built tracer tees
        finished spans into ``self.timeline``, which is what keeps
        ``telemetry.jsonl`` carrying the phase records; with any other
        tracer the span (if recorded at all) lands elsewhere, so emit a
        bare timeline event too."""
        self.tracing.emit(
            name, t0=t0, dur_s=dur_s, trace=trace, parent=parent,
            cat="serve", **attrs,
        )
        if not self._phases_via_tee:
            self.timeline.event(
                name, dur_s=round(max(float(dur_s), 0.0), 9), **attrs
            )

    # ----------------------------------------------------------- frontend

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        request_id: int | None = None,
        *,
        deadline_s: float | None = None,
    ) -> int:
        """Enqueue a request; returns its id. ``deadline_s`` (seconds
        from now; ``None`` = the engine's ``default_deadline_s``, 0 = no
        deadline) bounds the request's total latency — see
        ``Completion.finish_reason`` for the typed outcomes. Malformed
        requests still raise here (caller bugs), but LOAD conditions
        (queue full) come back as a typed ``"shed"`` completion, so a
        client library can treat overload as data, not control flow."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} < 1: prefill always "
                "samples the first token, so a request must want at least "
                "one (this also keeps prompt_len + 1 within the cache)"
            )
        if prompt.size + max_new_tokens > self.seq_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the model context ({self.seq_len})"
            )
        rid = self._next_id if request_id is None else request_id
        if rid in self._issued_ids:
            raise ValueError(
                f"request_id {rid} already used — completions are keyed "
                "by id, so a duplicate would silently shadow a result"
            )
        self._issued_ids.add(rid)
        self._next_id = max(self._next_id, rid) + 1
        req = ServeRequest(rid, prompt, int(max_new_tokens))
        req.deadline_s = (
            self.default_deadline_s if deadline_s is None else float(deadline_s)
        )
        # Trace-id propagation contract: the id is born HERE, at enqueue,
        # and every span this request generates (queue_wait, prefill,
        # graft, decode ticks, retire) carries it — the root "request"
        # span stays open until retirement so the tree spans
        # enqueue→retire.
        req.trace = self.tracing.new_trace(f"request {rid}")
        req.span = self.tracing.begin(
            "request", trace=req.trace, cat="serve", request=rid,
            prompt_len=int(prompt.size),
        )
        # One clock read serves both: queue_wait is emitted retroactively
        # from t_submit, so it must start exactly where the root does or
        # the tree's containment invariant breaks by a few microseconds.
        req.t_submit = getattr(req.span, "t0", None) or time.perf_counter()
        # Bounded admission (ISSUE 9): beyond max_queue_depth QUEUED
        # requests, shed typed instead of growing the queue without
        # bound — active slots are not counted (they already have their
        # memory), so the bound is exactly "work not yet started".
        if self.max_queue_depth and len(self._queue) >= self.max_queue_depth:
            self._m_shed.inc()
            self._complete_unadmitted(req, "shed")
            return rid
        self._queue.append(req)
        return rid

    def _complete_unadmitted(self, req: ServeRequest, reason: str) -> None:
        """Resolve a request that never occupied a slot (shed / expired
        in queue / quarantined at admission) with a typed completion: the
        prompt comes back untouched, zero generated tokens, and the root
        span closes so the trace tree still reads enqueue→resolution."""
        comp = Completion(
            id=req.id,
            tokens=req.prompt.copy(),
            prompt_len=int(req.prompt.size),
            finish_reason=reason,
            token_latencies_s=[],
        )
        self._early.append(comp)
        self.stats["completed"] += 1
        self.stats[f"finish_{reason}"] += 1
        self._m_completed.inc()
        self._phase(
            "retire", t0=time.perf_counter(), dur_s=0.0,
            trace=req.trace, parent=req.span,
            request=req.id, reason=reason, n_tokens=0,
        )
        req.span.end(finish_reason=reason, n_tokens=0)

    def _expired(self, req: ServeRequest, now: float | None = None) -> bool:
        if not req.deadline_s:
            return False
        now = time.perf_counter() if now is None else now
        return now - req.t_submit > req.deadline_s

    @property
    def pending(self) -> int:
        return len(self._queue) + int(self._active.sum())

    def reset_cache(self) -> None:
        """Drop the device cache and bucket state (jit caches survive —
        they are keyed on shapes, not state). For measurement loops that
        want a cold-state pass over warm compiled programs
        (tools/serve_bench.py): the bucket trajectory replays instead of
        starting at the warm pass's terminal bucket. Refuses while
        requests are in flight."""
        if self._active.any():
            raise RuntimeError("reset_cache with active slots in flight")
        self.cache = None
        self.bucket = 0
        self.stats.clear()
        # The warm pass's observations include compile time — drop them
        # so the measured pass's histograms report serving, not XLA.
        self.telemetry.reset()
        self.timeline.drain()
        self.tracing.drain()

    def bytes_per_slot(self) -> int:
        """Per-slot HBM of the LIVE engine cache at its current bucket —
        from the actual device arrays, so quantization scale tensors and
        per-slot bookkeeping are included (the accounting the bucket HBM
        estimates and serve_bench's bytes-per-slot column must agree
        with; pinned against ``generation.estimate_cache_bytes_per_slot``
        in tests/test_serving.py). 0 before the first admission."""
        if self.cache is None:
            return 0
        return cache_bytes_per_slot(self.cache, self.num_slots)

    def close(self) -> None:
        """Stop the watchdog thread (daemon — leak-safe either way)."""
        self.watchdog.stop()

    def export_trace(self, path: str) -> None:
        """Write the span ring as Chrome-trace-event JSON (Perfetto /
        chrome://tracing). One named lane per request plus the engine
        lane; non-consuming, so it can be called mid-serve."""
        self.tracing.write_chrome_trace(path)

    def run(self, max_steps: int | None = None) -> list[Completion]:
        """Drain the queue; returns completions in finish order (typed
        shed/deadline/error completions included — every submitted id
        resolves exactly once, the never-hangs contract)."""
        out: list[Completion] = []
        steps = 0
        while self.pending:
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        # Requests resolved without ever entering a slot (e.g. every
        # submit shed on a full queue) never pass through step().
        out.extend(self._early)
        self._early.clear()
        return out

    # ------------------------------------------------------ jitted shapes

    def _model_at(self, cache_len: int):
        return self.model.clone(cache_len=int(cache_len))

    def _trace_ctx(self):
        from frl_distributed_ml_scaffold_tpu.dist.mesh import mesh_context

        return mesh_context(self._env)

    def _prefill_fn(self, s_p: int):
        if s_p not in self._prefill_jit:
            m = self._model_at(s_p)
            kw = dict(self._sample_kw)

            def fn(params, prompt, lengths, rng):
                logits, cache = _prefill(m, params, prompt, lengths)
                return _sample(logits, rng, **kw), cache

            self._prefill_jit[s_p] = jax.jit(fn)
        return self._prefill_jit[s_p]

    def _decode_fn(self, s: int):
        if s not in self._decode_jit:
            m = self._model_at(s)
            kw = dict(self._sample_kw)

            def fn(params, cache, tok, rng):
                logits, cache = _decode_step(m, params, cache, tok)
                return _sample(logits, rng, **kw), cache

            # Donate the cache (the PR 5 graft-lint audit's find): the
            # engine immediately rebinds self.cache to the step's output,
            # so the input cache is dead the moment the call is issued —
            # without donation every decode step transiently holds TWO
            # full KV caches live (cache-in + cache-out), exactly the
            # allocation spike continuous batching sizes its slot count
            # against. Pinned by tests/test_serving.py donation pins via
            # analysis.pins.assert_donated/assert_aliased.
            self._decode_jit[s] = jax.jit(fn, donate_argnums=(1,))
        return self._decode_jit[s]

    def _graft_fn(self, s_p: int, s: int):
        """Write one prefilled request's cache rows into the engine cache
        at a (traced) slot index: a dynamic-update-slice at the leaf's
        slot-row axis (``generation.cache_batch_axis`` — THE cache-leaf
        taxonomy; the beam gather/repeat route through the same
        classifier, so new leaf classes stay in lockstep)."""
        if (s_p, s) not in self._graft_jit:
            n = self.num_slots

            def fn(cache, slot_cache, slot):
                def leaf(e, p):
                    ax = cache_batch_axis(e, n)
                    assert ax is not None, (
                        f"cache leaf {e.shape} carries no slot rows"
                    )
                    idx = (0,) * ax + (slot,) + (0,) * (e.ndim - ax - 1)
                    return jax.lax.dynamic_update_slice(
                        e, p.astype(e.dtype), idx
                    )

                return jax.tree.map(leaf, cache, slot_cache)

            # The engine cache is rebound to the graft's output too —
            # donate it (same audit find as _decode_fn; the slot cache is
            # NOT donated: its rows are read strided into the update).
            self._graft_jit[(s_p, s)] = jax.jit(fn, donate_argnums=(0,))
        return self._graft_jit[(s_p, s)]

    def _grow_fn(self, s_old: int, s_new: int):
        if (s_old, s_new) not in self._grow_jit:

            def fn(cache):
                def leaf(e):
                    # Pad every capacity-bearing leaf (K/V stacks AND
                    # their quantization-scale stacks) along the cache
                    # axis; bookkeeping leaves pass through.
                    ax = cache_capacity_axis(e, s_old)
                    if ax is None:
                        return e
                    pad = [(0, 0)] * e.ndim
                    pad[ax] = (0, s_new - s_old)
                    return jnp.pad(e, pad)

                return jax.tree.map(leaf, cache)

            self._grow_jit[(s_old, s_new)] = jax.jit(fn)
        return self._grow_jit[(s_old, s_new)]

    # --------------------------------------------------------- scheduling

    def _bucket_for(self, needed: int) -> int:
        return next_cache_bucket(self.seq_len, needed, floor=self.min_bucket)

    def _empty_cache(self, slot_cache, s: int):
        """Zeros shaped like a 1-request slot cache widened to the slot
        array (row axis per ``cache_batch_axis``) at cache capacity ``s``
        (capacity-bearing leaves — K/V and scale stacks — per
        ``cache_capacity_axis``, the same taxonomy ``_grow_fn`` pads)."""
        n = self.num_slots

        def leaf(e):
            ax = cache_batch_axis(e, 1)  # slot cache has batch 1
            assert ax is not None, f"cache leaf {e.shape} carries no rows"
            shape = list(e.shape)
            shape[ax] = n
            cap = cache_capacity_axis(e, s)
            if cap is not None:
                shape[cap] = s
            return jnp.zeros(tuple(shape), e.dtype)

        return jax.tree.map(leaf, slot_cache)

    def _ensure_bucket(self, needed: int) -> None:
        """Grow the cache to cover ``needed`` tokens; raises
        ``CacheGrowError`` (counted) when the pad allocation fails — the
        callers degrade per-request instead of crashing the engine."""
        target = self._bucket_for(needed)
        if target > self.bucket:
            t0 = time.perf_counter()
            try:
                faults.maybe_raise(
                    "serve.grow", CacheGrowError,
                    msg=f"injected grow failure {self.bucket}->{target}",
                )
                grown = self._grow_fn(self.bucket, target)(self.cache)
            except Exception as e:
                self._m_grow_failures.inc()
                self.stats["grow_failures"] += 1
                if isinstance(e, CacheGrowError):
                    raise
                raise CacheGrowError(
                    f"cache grow {self.bucket}->{target} failed: {e}"
                ) from e
            self.cache = grown
            self.stats[f"grow_{self.bucket}->{target}"] += 1
            self._m_grows.inc()
            # Grows belong to the ENGINE lane, not any one request: the
            # pad reshapes the shared slot-array cache (the span's tee
            # keeps the old bucket_grow timeline record alive).
            self._phase(
                "bucket_grow", t0=t0, dur_s=time.perf_counter() - t0,
                trace=self._engine_trace,
                frm=self.bucket, to=target,
            )
            self.bucket = target
            self._m_bytes_slot.set(self.bytes_per_slot())

    def _admit(self) -> None:
        for slot in range(self.num_slots):
            if self._active[slot]:
                continue
            # One free slot keeps consuming the queue until a request
            # actually admits: expired and poison requests resolve typed
            # and must not burn the slot's admission for this step.
            while self._queue:
                req = self._queue.popleft()
                if self._expired(req):
                    # Past deadline while still queued: shedding now is
                    # strictly better than prefilling work whose answer
                    # the caller has already abandoned.
                    self._m_deadline.inc()
                    self._complete_unadmitted(req, "deadline")
                    continue
                if self._try_admit(slot, req):
                    break

    def _try_admit(self, slot: int, req: ServeRequest) -> bool:
        """Prefill + graft ``req`` into ``slot``. A failure ANYWHERE in
        the request's own admission work (poison prompt crashing the
        prefill, cache growth failing) quarantines THIS request with a
        typed ``"error"`` completion and leaves the engine serving — one
        failing request must never wedge the batch (ISSUE 9). The shared
        cache is only rebound to outputs of successful programs, so a
        failed admission cannot corrupt live slots."""
        l = int(req.prompt.size)
        s_p = self._bucket_for(l)
        prompt = np.zeros((1, s_p), np.int32)
        prompt[0, s_p - l :] = req.prompt  # left-pad, right-aligned
        prev_rng = self._rng
        self._rng, sub = jax.random.split(self._rng)
        t0 = time.perf_counter()
        # Queue wait is only known now — emit it retrospectively,
        # spanning submit→admission, as the request tree's first leaf.
        self._phase(
            "queue_wait", t0=req.t_submit, dur_s=t0 - req.t_submit,
            trace=req.trace, parent=req.span, slot=slot,
        )
        try:
            faults.maybe_raise("serve.prefill", key=req.id)
            with self._trace_ctx():
                tok, slot_cache = self._prefill_fn(s_p)(
                    self.params,
                    jnp.asarray(prompt),
                    jnp.asarray([l], jnp.int32),
                    sub,
                )
                if self.cache is None:
                    self.cache = self._empty_cache(slot_cache, s_p)
                    self.bucket = s_p
                t_graft = time.perf_counter()
                self._ensure_bucket(max(s_p, l + 1))
                self.cache = self._graft_fn(s_p, self.bucket)(
                    self.cache, slot_cache, jnp.int32(slot)
                )
                self._phase(
                    "graft", t0=t_graft,
                    dur_s=time.perf_counter() - t_graft,
                    trace=req.trace, parent=req.span,
                    slot=slot, bucket=self.bucket,
                )
            tok = int(jax.device_get(tok)[0])
        except Exception as e:
            # Quarantine: typed resolution + counter + a loud log with
            # the cause — systemic breakage (every request failing) shows
            # up immediately in serve_quarantined_total's rate. The
            # failed admission's RNG split is rolled back, so later
            # requests see exactly the splits a fault-free run would
            # give them — chaos token-identity holds for SAMPLED
            # (temperature>0) decode too, not just greedy.
            self._rng = prev_rng
            self._m_quarantined.inc()
            self.stats["quarantined"] += 1
            from frl_distributed_ml_scaffold_tpu.utils.logging import (
                get_logger,
            )

            get_logger().warning(
                "serving: request %d quarantined at admission "
                "(%s: %s) — slot %d stays free, batch keeps decoding",
                req.id, type(e).__name__, e, slot,
            )
            self._complete_unadmitted(req, "error")
            return False
        dt = time.perf_counter() - t0
        self.stats[f"prefill_{s_p}"] += 1
        # TTFT = submit-to-first-token work this engine performed for
        # the request: prefill + graft + the forced first-token fetch.
        # (Queue wait is visible separately via serve_queue_depth.)
        self._m_ttft.observe(dt)
        self._m_prefills.inc()
        self._m_grafts.inc()
        self._m_bytes_slot.set(self.bytes_per_slot())
        self._phase(
            "prefill", t0=t0, dur_s=dt, trace=req.trace,
            parent=req.span,
            slot=slot, bucket=s_p, request=req.id,
        )
        self.watchdog.beat()

        self._req[slot] = req
        self._tokens[slot] = [tok]
        self._len[slot] = l + 1
        self._active[slot] = True
        self._latency[slot] = [dt]
        self._last_tok[slot] = tok
        # The first sampled token can already finish the request.
        self._finishes(slot, tok)
        return True

    def _finishes(self, slot: int, tok: int) -> bool:
        req = self._req[slot]
        if self.eos_id is not None and tok == self.eos_id:
            self._retire(slot, "eos")
            return True
        if len(self._tokens[slot]) >= req.max_new_tokens:
            self._retire(slot, "length")
            return True
        return False

    def _retire(self, slot: int, reason: str) -> None:
        req = self._req[slot]
        lat = self._latency[slot]
        # Per-request SLO columns, through the same log2-bucket estimator
        # the aggregate serve_tpot_seconds histogram uses: ttft is the
        # prefill latency (lat[0]); tpot covers the decode steps (lat[1:]).
        tpot = _log2_quantiles(lat[1:], (0.50, 0.99))
        comp = Completion(
            id=req.id,
            tokens=np.concatenate(
                [req.prompt, np.asarray(self._tokens[slot], np.int32)]
            ),
            prompt_len=int(req.prompt.size),
            finish_reason=reason,
            token_latencies_s=lat,
            ttft_s=lat[0] if lat else 0.0,
            tpot_p50_s=tpot[0],
            tpot_p99_s=tpot[1],
        )
        self._completed.append(comp)
        self._req[slot] = None
        self._active[slot] = False
        self.stats["completed"] += 1
        self.stats[f"finish_{reason}"] += 1
        self._m_completed.inc()
        self._phase(
            "retire", t0=time.perf_counter(), dur_s=0.0,
            trace=req.trace, parent=req.span,
            slot=slot, request=req.id, reason=reason,
            n_tokens=len(self._tokens[slot]),
        )
        # Close the root: the request tree now spans enqueue→retire.
        req.span.end(finish_reason=reason, n_tokens=len(self._tokens[slot]))

    # --------------------------------------------------------------- step

    def step(self) -> list[Completion]:
        """Admit into free slots, run ONE decode iteration over the slot
        array, retire finished rows. Returns requests completed during
        this step (possibly at admission, for 1-token budgets; typed
        shed/deadline/error resolutions ride along)."""
        self._completed: list[Completion] = []
        self._m_queue.set(len(self._queue))
        self._admit()
        # Typed completions resolved since the last step (shed at
        # submit) and during this admission round (expired/quarantined).
        self._completed.extend(self._early)
        self._early.clear()
        self._m_occupancy.set(float(self._active.sum()) / self.num_slots)
        if not self._active.any():
            return self._completed

        # Bucket must hold every active row's next write position: an
        # active row holds cache_index == _len - 1 (prefill sets idx=l
        # with _len=l+1; both advance together), so this step writes
        # position _len - 1 and needs capacity exactly _len.
        try:
            self._ensure_bucket(int(self._len[self._active].max()))
        except CacheGrowError as e:
            # Degrade, don't die: rows that NEED the larger bucket are
            # retired typed ("error", carrying their tokens so far); rows
            # still inside the current bucket keep decoding — a capacity
            # failure at high occupancy costs the big requests, never the
            # whole batch.
            from frl_distributed_ml_scaffold_tpu.utils.logging import (
                get_logger,
            )

            victims = [
                s for s in np.flatnonzero(self._active)
                if self._len[s] > self.bucket
            ]
            get_logger().warning(
                "serving: cache grow failed (%s); retiring %d slot(s) "
                "needing the larger bucket, %d keep decoding",
                e, len(victims), int(self._active.sum()) - len(victims),
            )
            for s in victims:
                self._retire(int(s), "error")
            if not self._active.any():
                return self._completed

        self._rng, sub = jax.random.split(self._rng)
        t0 = time.perf_counter()
        with self._trace_ctx():
            nxt, self.cache = self._decode_fn(self.bucket)(
                self.params,
                self.cache,
                jnp.asarray(self._last_tok),
                sub,
            )
        nxt = np.asarray(jax.device_get(nxt))
        dt = time.perf_counter() - t0
        self.stats[f"decode_{self.bucket}"] += 1
        self.stats["decode_steps"] += 1
        self._m_decodes.inc()
        # One engine-lane span per slot-array decode program...
        self._phase(
            "decode", t0=t0, dur_s=dt, trace=self._engine_trace,
            bucket=self.bucket, active=int(self._active.sum()),
        )
        self.watchdog.beat()
        if self.telemetry.enabled:
            # memory_stats() is a per-device PJRT runtime call — real cost
            # on a ~ms decode step, so the disabled path must skip the
            # query itself, not just the no-op gauge write.
            for k, v in _hbm_gib().items():
                (self._m_hbm_used if k == "hbm_in_use_gib"
                 else self._m_hbm_peak).set(v)

        for slot in range(self.num_slots):
            if not self._active[slot]:
                continue
            req = self._req[slot]
            tok = int(nxt[slot])
            self._tokens[slot].append(tok)
            self._len[slot] += 1
            self._latency[slot].append(dt)
            self._m_tpot.observe(dt)
            self._last_tok[slot] = tok
            # ...and one request-lane tick per live row, sharing the
            # program's timing (rows decode together in one program, so
            # a per-row clock would be fiction).
            self._phase(
                "decode_tick", t0=t0, dur_s=dt, trace=req.trace,
                parent=req.span, slot=slot,
                token=len(self._tokens[slot]) - 1,
            )
            if self._finishes(slot, tok):
                continue
            # Mid-decode deadline cancellation (ISSUE 9): a natural
            # finish (eos/budget) wins; otherwise a request past its
            # deadline retires NOW with the tokens it has — the slot is
            # freed for refill instead of burning decode steps on an
            # answer the caller has stopped waiting for.
            if self._expired(req):
                self._m_deadline.inc()
                self._retire(slot, "deadline")
        return self._completed
