"""Host-side continuous-batching decode engine over a fixed slot array.

TPU serving wants the same static-shape discipline as TPU training: every
device program the engine runs is one of a SMALL closed set of compiled
shapes — a prefill per prompt bucket, a decode step per cache bucket, a
cache graft per (prompt bucket, cache bucket) pair — all powers of two up
to ``config.seq_len`` (``models/generation.next_cache_bucket``). Requests
of any length mix freely inside those shapes:

- **Slots**: the decode batch is a fixed ``[num_slots]`` row array. Each
  row is an independent request; per-row cache indices/positions
  (models/gpt.py decode path) mean rows at different occupancies decode
  together in one program.
- **Continuous batching**: when a row emits eos (or exhausts its budget)
  it RETIRES — the completion is returned and the slot is freed — and the
  next queued request is prefilled into the freed row while the other
  rows keep decoding. Admission never stalls the running rows: a prompt
  is prefilled as a [1, prompt_bucket] program and its cache rows are
  grafted into the engine cache at the slot index (a dynamic-update-slice,
  not a reshard).
- **Cache buckets**: the engine cache starts at the smallest bucket that
  covers the live requests and GROWS bucket-by-bucket (a pad along the
  cache axis) only when an active slot actually needs the room. Short
  requests therefore never pay full-context cache traffic — and the
  decode kernel additionally reads only each row's occupied prefix within
  the bucket.
- **Paged cache** (``kv_block_size > 0``, ISSUE 10): the bucketed
  per-slot cache is replaced by a fixed POOL of fixed-size KV blocks
  plus per-slot block tables (ops/decode_attention.py paged kernel; the
  tables ride the scalar-prefetch channel next to the per-row lengths).
  Growth becomes appending one block to a table — no cache clone, no
  bucket ladder, ONE compiled decode shape — and admission is priced in
  pool headroom: a request reserves its worst-case block count up front,
  so mid-decode appends can never fail, and a full pool makes the queue
  head WAIT (backpressure that composes with ``max_queue_depth``'s shed
  bound: pool exhaustion -> queue growth -> typed sheds). Prefill stays
  contiguous; the graft scatters exactly the blocks that change owner
  into the pool (the arXiv 2112.01075 gather-at-the-boundary
  discipline). Refcounted SHARED-PREFIX caching rides the same
  allocator: a prompt whose leading full blocks match a cached chain
  reuses those physical blocks (prefill runs only on the suffix, seeded
  with the shared prefix gathered block-wise) with copy-on-write at the
  first divergent/partial block — a common system prompt prefills
  exactly once, and prefill work scales with UNIQUE prefixes, not
  requests.

Everything here is host logic around jitted pure functions; under a live
mesh (captured at construction) the same loop serves model-sharded caches
— the jitted programs trace under ``mesh_context`` so the decode
attention runs head-sharded (ops/decode_attention.py router).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from frl_distributed_ml_scaffold_tpu import faults
from frl_distributed_ml_scaffold_tpu.config.schema import ServingConfig
from frl_distributed_ml_scaffold_tpu.models.generation import (
    POOL_LEAF_OF,
    SLOT_LEAF_OF,
    _decode_step,
    _plain_stack,
    _prefill,
    _sample,
    _verify_step,
    blocks_for_tokens,
    cache_batch_axis,
    cache_bytes_per_slot,
    cache_capacity_axis,
    generate,
    next_cache_bucket,
    pool_block_bytes,
    rewind_cache_indices,
    splice_pool_blocks,
)
from frl_distributed_ml_scaffold_tpu.telemetry import (
    Histogram,
    MetricsRegistry,
    StallWatchdog,
    Timeline,
    Tracer,
)


def ngram_propose(
    history: np.ndarray, k: int, max_ngram: int = 3
) -> np.ndarray:
    """Tier-A draft proposer (ISSUE 11): prompt-lookup / n-gram
    self-speculation. Find the most recent EARLIER occurrence of the
    history's trailing n-gram (longest n first, n = max_ngram..1) and
    propose the up-to-``k`` tokens that followed it — on repetitive or
    structured text (code, templated prose, the model's own greedy
    cycles) the continuation of a repeated n-gram is usually the same
    tokens again, so the target model accepts most of the draft and
    each verify step retires several tokens for one pool read.

    Pure host-side numpy over the slot's own token history (prompt +
    emitted) — no second model, no device work, deterministic. Returns
    an empty array when nothing matches (the slot then single-steps
    inside the shared verify program). Drafting is ADVISORY: a bad
    draft costs only its rejected verify position, never correctness.
    """
    h = np.asarray(history).reshape(-1)
    n_h = int(h.size)
    if k < 1 or n_h < 2:
        return h[:0]
    for n in range(min(max_ngram, n_h - 1), 0, -1):
        suffix = h[n_h - n :]
        # Most recent earlier occurrence WITH a full-k continuation,
        # else the most recent match at all. Overlapping matches are
        # deliberately allowed — a period-p cycle matches at n_h-n-p
        # and proposes the periodic continuation, the whole tier-A win
        # — but a match butting against the end of history truncates
        # its continuation (the period-1 extreme yields ONE token), so
        # when a slightly older occurrence can fill the whole draft
        # budget with the same pattern, prefer it. One vectorized pass
        # (this runs per active slot per verify step — an interpreted
        # backward scan would be O(len^2) host work per request, more
        # than the batched verify forward it gates).
        wins = np.lib.stride_tricks.sliding_window_view(h[:-1], n)
        hits = np.flatnonzero((wins == suffix).all(axis=1))
        if hits.size == 0:
            continue
        full = hits[hits <= n_h - n - k]
        i = int(full[-1]) if full.size else int(hits[-1])
        return h[i + n : i + n + k].copy()
    return h[:0]


def make_prefill_program(model, sample_kw: dict):
    """Build THE compiled prefill program for one prompt bucket (the
    model is already cloned to it): prefill + first-token sample. One
    builder for both admission paths (engine jit caches and the
    disaggregated ``PrefillWorker``'s), like ``prefill_request`` — a
    change here (donation, sampling) lands on both or neither."""
    kw = dict(sample_kw)

    def fn(params, prompt, lengths, rng):
        logits, cache = _prefill(model, params, prompt, lengths)
        return _sample(logits, rng, **kw), cache

    return jax.jit(fn)


def make_seeded_prefill_program(model, sample_kw: dict):
    """The shared-prefix variant: suffix prefill against a seeded slot
    cache (donated — the seed is single-use by construction)."""
    kw = dict(sample_kw)

    def fn(params, prompt, lengths, rng, cache0):
        logits, cache = _prefill(model, params, prompt, lengths, cache=cache0)
        return _sample(logits, rng, **kw), cache

    return jax.jit(fn, donate_argnums=(4,))


def prefill_request(
    req, res, rng, *, block_size: int, bucket_for, params,
    prefill_fn, seeded_fn, seed_cache=None,
):
    """THE admission prefill recipe, in one place (ISSUE 12): bucket the
    (possibly prefix-stripped) prompt, left-pad the suffix, and run the
    seeded or plain prefill program. Shared by the colocated engine
    (``_prefill_package``) and the disaggregated ``PrefillWorker`` —
    same recipe, different params/jit-caches/partition — so the two
    admission paths cannot drift. Returns the un-fetched package
    ``(tok, slot_cache, s_p, s_c, m, l_suf)``; ``l_suf >= 1`` by the
    ``_match_prefix`` cap (at least one token always prefills)."""
    l = int(req.prompt.size)
    m = res["m"] if res is not None else 0
    l_suf = l - m * block_size
    s_p = bucket_for(l_suf)
    s_c = bucket_for(l) if block_size else s_p
    prompt = np.zeros((1, s_p), np.int32)
    prompt[0, s_p - l_suf :] = req.prompt[m * block_size :]  # left-pad
    if m > 0:
        tok, slot_cache = seeded_fn(s_p, s_c)(
            params,
            jnp.asarray(prompt),
            jnp.asarray([l_suf], jnp.int32),
            rng,
            seed_cache,
        )
    else:
        tok, slot_cache = prefill_fn(s_p)(
            params,
            jnp.asarray(prompt),
            jnp.asarray([l], jnp.int32),
            rng,
        )
    return tok, slot_cache, s_p, s_c, m, l_suf


class CacheGrowError(RuntimeError):
    """Growing the KV cache to the next bucket failed (allocation failure
    at high occupancy, or the ``serve.grow`` fault site). The engine
    degrades instead of dying: requests that NEED the larger bucket are
    retired with ``finish_reason="error"``; requests that still fit keep
    decoding (see ``ServingEngine.step``)."""


@dataclasses.dataclass
class ServeRequest:
    """One queued generation request (prompt is an unpadded 1-D int array).

    ``trace``/``span``/``t_submit`` are the tracing handles (ISSUE 8):
    every request gets its own trace id at enqueue, and the root
    ``request`` span stays open from submit to retire so the exported
    trace reads as one connected tree per request. ``deadline_s`` is the
    submit-relative deadline (0 = none; ISSUE 9)."""

    id: int
    prompt: np.ndarray
    max_new_tokens: int
    trace: int = 0
    t_submit: float = 0.0
    span: Any = None
    deadline_s: float = 0.0


@dataclasses.dataclass
class Completion:
    """A finished request: prompt + generated tokens and per-token wall
    latencies (the decode steps this request was live for), plus the
    serving-SLO summary of those latencies: ``ttft_s`` (time to first
    token — the prefill) and p50/p99 time-per-output-token over the
    decode steps, computed through the telemetry histogram's log2-bucket
    quantile estimator so per-request numbers and the engine's aggregate
    ``serve_tpot_seconds`` histogram read on the same scale.

    ``finish_reason`` is the TYPED failure contract (ISSUE 9): every
    submitted request resolves to exactly one completion —
    ``"eos"``/``"length"`` (served in full), ``"shed"`` (load-shed at
    admission: queue bound hit, no tokens generated), ``"deadline"``
    (deadline passed — queued requests shed before prefill, mid-decode
    requests are cancelled carrying the tokens generated so far), or
    ``"error"`` (poison request quarantined / cache growth failed; any
    tokens generated before the fault are carried). A caller therefore
    never hangs on a faulted request and can always tell a served answer
    from a degraded one."""

    id: int
    tokens: np.ndarray  # [prompt_len + n_generated]
    prompt_len: int
    finish_reason: str  # "eos" | "length" | "shed" | "deadline" | "error"
    token_latencies_s: list[float]
    ttft_s: float = 0.0
    tpot_p50_s: float = 0.0
    tpot_p99_s: float = 0.0
    # Shared-prefix accounting (ISSUE 10), PER REQUEST — the paged
    # engine's prefix win measured where SLOs live, not just as an
    # aggregate gauge: did this request's prompt reuse cached prefix
    # blocks, and how many prompt tokens were never prefilled because
    # of it (serve_bench aggregates these into its SLO columns).
    prefix_cache_hit: bool = False
    prefill_tokens_saved: int = 0
    # Speculative-decode accounting (ISSUE 11), PER REQUEST — accepted
    # draft tokens / proposed draft tokens over this request's verify
    # steps (0.0 when nothing was proposed, e.g. speculate=off or a
    # degraded slot). The per-request SLO face of the aggregate
    # serve_spec_{proposed,accepted}_total counters, the same path as
    # prefix_cache_hit above.
    spec_accept_rate: float = 0.0
    # Token ARRIVAL times (ISSUE 12), seconds from submit, one per
    # generated token: the honest inter-token-gap record — unlike
    # ``token_latencies_s`` (the decode PROGRAM's wall time), gaps
    # between consecutive arrivals include everything the engine did in
    # between (inline prefills, grafts, handoffs), which is exactly the
    # decode-TPOT-under-prefill-burst number the disaggregation A/B
    # measures and the scheduler's per-tenant TPOT histograms observe.
    token_times_s: list[float] = dataclasses.field(default_factory=list)
    # Multi-tenant attribution (ISSUE 12): the tenant the request was
    # submitted under ("" on a plain single-tenant engine).
    tenant: str = ""

    @property
    def ok(self) -> bool:
        """Served in full (not shed / expired / quarantined)."""
        return self.finish_reason in ("eos", "length")


def _log2_quantiles(vals, qs) -> list[float]:
    """Quantiles of ``vals`` through a detached log2-bucket Histogram —
    the same estimator (and thus the same 2x-granularity scale) as the
    engine's aggregate latency histograms."""
    h = Histogram(MetricsRegistry(), "q", help="")
    for v in vals:
        h.observe(v)
    return [h.quantile(q) for q in qs]


def _hbm_gib() -> dict[str, float]:
    """In-use/peak HBM GiB (empty on backends without memory stats)."""
    from frl_distributed_ml_scaffold_tpu.utils.profiling import (
        device_memory_stats,
    )

    stats = device_memory_stats()
    return {
        k: v for k, v in stats.items()
        if k in ("hbm_in_use_gib", "hbm_peak_gib")
    }


class ServingEngine:
    """Continuous-batching engine; see the module docstring.

    Usage::

        eng = ServingEngine(model, params, num_slots=4, eos_id=50256)
        eng.submit([5, 3, 8], max_new_tokens=32)
        done = eng.run()          # or step() for one decode iteration
    """

    def __init__(
        self,
        model: Any,
        params: Any,
        *,
        num_slots: int = 4,
        eos_id: int | None = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        rng: jax.Array | None = None,
        min_bucket: int = 8,
        serving: ServingConfig | None = None,
        max_queue_depth: int = 0,
        default_deadline_s: float = 0.0,
        kv_block_size: int = 0,
        kv_pool_blocks: int = 0,
        prefix_cache: bool | None = None,
        speculate: str | None = None,
        speculate_k: int = 0,
        speculate_ngram_max: int = 3,
        speculate_window: int = 32,
        draft_model: Any = None,
        draft_params: Any = None,
        telemetry: MetricsRegistry | None = None,
        tracer: Tracer | None = None,
        stall_timeout_s: float = 0.0,
        stall_dump_path: str | None = None,
        stall_first_beat_scale: float = 5.0,
    ):
        model, params = _plain_stack(model, params)
        self.model, self.params = model, params
        if num_slots < 1:
            raise ValueError(
                f"num_slots={num_slots} < 1: zero slots can never admit, "
                "so run() would spin on a non-empty queue forever"
            )
        self.num_slots = int(num_slots)
        self.eos_id = eos_id
        self._sample_kw = dict(
            temperature=temperature, top_k=top_k, top_p=top_p
        )
        self._rng = jax.random.key(0) if rng is None else rng
        self.min_bucket = int(min_bucket)
        self.seq_len = model.config.seq_len
        # Graceful degradation (ISSUE 9): `serving=` takes the whole
        # ServingConfig (the `serving.*` section of an ExperimentConfig)
        # — THE config-driven path; the scalar kwargs remain for callers
        # without a config. Passing both is a caller bug, refused.
        if serving is not None:
            if (max_queue_depth or default_deadline_s or kv_block_size
                    or kv_pool_blocks or prefix_cache is not None
                    or speculate is not None or speculate_k):
                raise ValueError(
                    "pass either serving=ServingConfig(...) or the "
                    "max_queue_depth/default_deadline_s/kv_block_size/"
                    "kv_pool_blocks/prefix_cache/speculate/speculate_k "
                    "scalars, not both"
                )
            max_queue_depth = serving.max_queue_depth
            default_deadline_s = serving.default_deadline_s
            kv_block_size = serving.kv_block_size
            kv_pool_blocks = serving.kv_pool_blocks
            prefix_cache = serving.prefix_cache
            speculate = serving.speculate
            speculate_k = serving.speculate_k
        if max_queue_depth < 0:
            raise ValueError(f"max_queue_depth={max_queue_depth} < 0")
        self.max_queue_depth = int(max_queue_depth)
        self.default_deadline_s = float(default_deadline_s)
        # Paged-cache knobs (ISSUE 10). Block sizes are powers of two so
        # every prompt bucket is a whole number of blocks (the graft's
        # reshape-to-blocks relies on it) and the paged kernel's chunk is
        # tileable.
        self.paged = kv_block_size > 0
        if self.paged:
            bs = int(kv_block_size)
            if bs & (bs - 1) or bs > self.seq_len:
                raise ValueError(
                    f"kv_block_size={bs} must be a power of two "
                    f"<= seq_len={self.seq_len}"
                )
            self.block_size = bs
            self.table_blocks = blocks_for_tokens(self.seq_len, bs)
            if kv_pool_blocks == 0:
                # Auto: the never-blocks-admission worst case (+1 trash).
                kv_pool_blocks = self.num_slots * self.table_blocks + 1
            if kv_pool_blocks < 2:
                raise ValueError(
                    f"kv_pool_blocks={kv_pool_blocks} < 2: block 0 is the "
                    "reserved trash block, so a usable pool needs >= 2"
                )
            self.pool_blocks = int(kv_pool_blocks)
            # Prompt buckets must stay whole numbers of blocks.
            self.min_bucket = max(self.min_bucket, bs)
            self.prefix_cache_enabled = (
                True if prefix_cache is None else bool(prefix_cache)
            )
            # Allocator state: block 0 is TRASH (retired slots' tables
            # point at it, so the shared decode program's writes for
            # inactive rows land somewhere harmless instead of a freed —
            # possibly reallocated — block).
            self._free: list[int] = list(range(self.pool_blocks - 1, 0, -1))
            self._ref = np.zeros(self.pool_blocks, np.int64)
            self._reserved_future = 0
            self._slot_blocks: list[list[int]] = [
                [] for _ in range(self.num_slots)
            ]
            self._slot_future = np.zeros(self.num_slots, np.int64)
            # Blocks owned by PARKED requests (ISSUE 12), keyed by
            # request id: out of any slot but still refcounted — the
            # pool-demand accounting must keep seeing them.
            self._parked_held: dict[int, list[int]] = {}
            self._slot_prefix_hit = np.zeros(self.num_slots, bool)
            self._slot_tokens_saved = np.zeros(self.num_slots, np.int64)
            self._tables = np.zeros(
                (self.num_slots, self.table_blocks), np.int32
            )
            self._tables_dirty = True
            # prompt-prefix bytes -> tuple of physical block ids, LRU
            # order (move_to_end on hit, popitem(last=False) on evict).
            self._prefix_cache: collections.OrderedDict[
                bytes, tuple[int, ...]
            ] = collections.OrderedDict()

        # Speculative decoding (ISSUE 11): draft-propose k tokens per
        # slot, verify all k+1 positions in ONE batched forward, accept
        # the longest matching prefix, roll the rest back (a pointer
        # move on the paged cache). Greedy only — acceptance is exact
        # argmax matching, so speculative output is TOKEN-IDENTICAL to
        # generate(); this is a pure-perf knob.
        self.spec_mode = "off" if speculate is None else str(speculate)
        if self.spec_mode not in ("off", "ngram", "draft"):
            raise ValueError(
                f"speculate={self.spec_mode!r} unknown (off | ngram | draft)"
            )
        self.spec_k = int(speculate_k)
        self.spec_ngram_max = int(speculate_ngram_max)
        self.spec_window = int(speculate_window)
        self._draft = None
        if self.spec_mode != "off":
            if not self.paged:
                raise ValueError(
                    "speculative decoding runs on the PAGED engine "
                    "(serving.kv_block_size > 0): accept/rollback is "
                    "block-table pointer bookkeeping there — the "
                    "bucketed cache has no cheap rollback"
                )
            if self._sample_kw["temperature"] != 0.0:
                raise ValueError(
                    "speculate requires greedy decode (temperature=0): "
                    "acceptance is exact argmax matching; sampled "
                    "speculative decode needs rejection sampling, which "
                    "this engine does not implement"
                )
            if self.spec_k < 1:
                raise ValueError(
                    f"speculate_k={self.spec_k} < 1: a verify step must "
                    "carry at least one draft position"
                )
            if self.spec_mode == "draft":
                if draft_model is None or draft_params is None:
                    raise ValueError(
                        "speculate='draft' needs draft_model= and "
                        "draft_params= (a small GPT sharing the target's "
                        "tokenizer); use speculate='ngram' for "
                        "model-free self-speculation"
                    )
                dm, dp = _plain_stack(draft_model, draft_params)
                if dm.config.vocab_size != model.config.vocab_size:
                    raise ValueError(
                        "draft model must share the target tokenizer "
                        f"(vocab {dm.config.vocab_size} != "
                        f"{model.config.vocab_size})"
                    )
                # The draft proposes from a sliding WINDOW of each slot's
                # history (one compiled propose program: bucketed ragged
                # prefill + k greedy steps) — its cache is the window
                # bucket, so draft memory never contends with the pool.
                self.spec_window = min(
                    self.spec_window, dm.config.seq_len - self.spec_k
                )
                if self.spec_window < 1:
                    raise ValueError(
                        f"draft context ({dm.config.seq_len}) cannot fit "
                        f"a window + speculate_k={self.spec_k}"
                    )
                self._draft = (dm, dp)
        # Per-slot speculation state (reset at admission): sticky
        # degradation (draft-proposer failure -> plain decode for the
        # rest of the request) and the per-request accept accounting
        # behind Completion.spec_accept_rate.
        self._slot_spec_degraded = np.zeros(self.num_slots, bool)
        self._slot_spec_proposed = np.zeros(self.num_slots, np.int64)
        self._slot_spec_accepted = np.zeros(self.num_slots, np.int64)

        # The mesh is captured ONCE: every jitted program traces under it,
        # so replicated and sharded engines never share a trace.
        from frl_distributed_ml_scaffold_tpu.dist.mesh import current_mesh_env

        self._env = current_mesh_env()

        self._queue: collections.deque[ServeRequest] = collections.deque()
        # Typed completions produced OUTSIDE a slot (shed at submit,
        # deadline-expired while queued, quarantined at admission) wait
        # here until the next step()/run() returns them — a faulted
        # request always resolves, never hangs.
        self._early: list[Completion] = []
        # Completions retired since the last step() drain. PERSISTENT
        # (not rebound per step): disaggregated admission (ISSUE 12,
        # admit_handoff) retires 1-token-budget requests BETWEEN steps,
        # and a per-step rebind would silently drop them — every retire
        # path appends here, step() drains.
        self._completed: list[Completion] = []
        self._next_id = 0
        self._issued_ids: set[int] = set()
        # Host-side slot state.
        self._req: list[ServeRequest | None] = [None] * self.num_slots
        self._tokens: list[list[int]] = [[] for _ in range(self.num_slots)]
        self._len = np.zeros(self.num_slots, np.int64)  # prompt+generated
        self._active = np.zeros(self.num_slots, bool)
        self._latency: list[list[float]] = [[] for _ in range(self.num_slots)]
        # Token ARRIVAL times per slot (submit-relative) — the gap record
        # behind Completion.token_times_s (ISSUE 12).
        self._tok_times: list[list[float]] = [
            [] for _ in range(self.num_slots)
        ]
        self._last_tok = np.zeros(self.num_slots, np.int32)

        self.cache: Any = None
        self.bucket = 0
        # Jit caches keyed on the static shapes they close over.
        self._prefill_jit: dict[int, Any] = {}
        self._decode_jit: dict[int, Any] = {}
        self._graft_jit: dict[tuple[int, int], Any] = {}
        self._grow_jit: dict[tuple[int, int], Any] = {}
        # Paged-mode programs: ONE decode shape (the pool never grows),
        # seeded prefills keyed on (suffix bucket, cache bucket), prefix
        # seeds keyed on (cache bucket, shared blocks), block grafts
        # keyed on (cache bucket, private blocks written).
        self._paged_decode_jit: Any = None
        self._prefill_seeded_jit: dict[tuple[int, int], Any] = {}
        self._seed_jit: dict[tuple[int, int], Any] = {}
        self._paged_graft_jit: dict[tuple[int, int], Any] = {}
        # Speculation programs: ONE verify shape for the whole engine
        # lifetime (the [B, k+1] tile is fixed at construction — no
        # per-k ladder; slots with fewer drafts pad the tile), one
        # rollback (index rewind) shape, one draft-propose shape.
        self._verify_jit: Any = None
        self._rewind_jit: Any = None
        self._draft_jit: Any = None
        # Observability: how often each compiled-shape class actually ran.
        self.stats = collections.Counter()
        # Telemetry (ISSUE 7): every metric is registered up front so both
        # exporters always carry the full serving catalog (a gauge that
        # never fired still scrapes as 0, which is itself a signal). All
        # host-side, around the jitted programs — never inside them
        # (graft-lint `metrics-in-traced` enforces this).
        self.telemetry = telemetry if telemetry is not None else MetricsRegistry()
        self.timeline = Timeline(enabled=self.telemetry.enabled)
        # Tracing (ISSUE 8): one span tree per request (trace id assigned
        # at submit), plus an "engine" lane for the slot-array-scoped
        # programs (decode steps, bucket grows). Spans tee into the
        # Timeline, so the existing drain/export path still carries the
        # phase records, while the tracer ring holds the tree for
        # export_trace(). Host-side only, same contract as the metrics.
        self.tracing = (
            tracer if tracer is not None
            else Tracer(enabled=self.telemetry.enabled, timeline=self.timeline)
        )
        # A caller-supplied tracer (its own timeline, or disabled) breaks
        # the tee into THIS engine's timeline — _phase() then falls back
        # to bare timeline events so telemetry.jsonl's phase records and
        # the watchdog's timeline tail never depend on tracing state.
        self._phases_via_tee = (
            self.tracing.enabled and self.tracing.timeline is self.timeline
        )
        self._engine_trace = self.tracing.new_trace("engine")
        t = self.telemetry
        self._m_ttft = t.histogram(
            "serve_ttft_seconds", help="time to first token (prefill+graft)"
        )
        self._m_tpot = t.histogram(
            "serve_tpot_seconds",
            help="per-output-token latency over live slots (decode steps)",
        )
        self._m_queue = t.gauge("serve_queue_depth", help="requests waiting")
        self._m_occupancy = t.gauge(
            "serve_slot_occupancy", help="active slots / num_slots"
        )
        self._m_bytes_slot = t.gauge(
            "serve_bytes_per_slot",
            help="per-slot HBM of the live cache at its current bucket",
        )
        self._m_hbm_used = t.gauge(
            "serve_hbm_in_use_gib", help="device HBM in use (0 when the "
            "backend exposes no stats, e.g. CPU sim)"
        )
        self._m_hbm_peak = t.gauge(
            "serve_hbm_peak_gib", help="device HBM high-watermark"
        )
        self._m_prefills = t.counter("serve_prefill_total", help="prefills run")
        self._m_decodes = t.counter(
            "serve_decode_steps_total", help="slot-array decode iterations"
        )
        self._m_grows = t.counter(
            "serve_bucket_grow_total", help="cache bucket growths"
        )
        self._m_grafts = t.counter(
            "serve_cache_graft_total", help="prefill-cache grafts into slots"
        )
        self._m_completed = t.counter(
            "serve_completed_total", help="requests finished"
        )
        # Failure-semantics counters (ISSUE 9): the OBSERVED side of the
        # fault ledger — chaos drills diff these against the FaultPlan's
        # injected counts to prove detection.
        self._m_shed = t.counter(
            "serve_shed_total",
            help="requests load-shed at submit (queue bound)",
        )
        self._m_deadline = t.counter(
            "serve_deadline_miss_total",
            help="requests past deadline (shed queued / cancelled decoding)",
        )
        self._m_quarantined = t.counter(
            "serve_quarantined_total",
            help="poison requests whose prefill failed (batch kept alive)",
        )
        self._m_grow_failures = t.counter(
            "serve_grow_failures_total",
            help="cache bucket growths that failed (degraded, not fatal)",
        )
        # Paged-cache + shared-prefix observability (ISSUE 10). Always
        # registered (the full-catalog contract): 0 on a bucketed engine.
        self._m_pool_util = t.gauge(
            "serve_pool_utilization",
            help="allocated KV pool blocks / usable pool blocks "
            "(trash block excluded; 0 on a bucketed engine)",
        )
        self._m_block_appends = t.counter(
            "serve_block_append_total",
            help="mid-decode KV blocks appended to slot tables "
            "(the paged engine's 'grow': one block, never a cache clone)",
        )
        self._m_prefix_hits = t.counter(
            "serve_prefix_hits_total",
            help="admissions that reused cached prefix blocks",
        )
        self._m_prefix_saved = t.counter(
            "serve_prefix_tokens_saved_total",
            help="prompt tokens never prefilled thanks to prefix reuse",
        )
        self._m_prefix_hit_rate = t.gauge(
            "serve_prefix_hit_rate",
            help="prefix hits / admissions since engine start",
        )
        # Live re-spread observability (ISSUE 15). Always registered
        # (the full-catalog contract): 0 until a respread_pool call.
        self._m_respread = t.counter(
            "serve_pool_respread_total",
            help="live model-axis re-spreads of the paged pool "
            "(redistribution service; in-flight slots park/resume)",
        )
        self._m_respread_bytes = t.counter(
            "serve_pool_respread_bytes_total",
            help="bytes the re-spread plans actually moved across "
            "devices (the shard delta, not the pool size)",
        )
        # Speculative-decode observability (ISSUE 11). Always registered
        # (the full-catalog contract): 0 with speculate=off.
        self._m_spec_proposed = t.counter(
            "serve_spec_proposed_total",
            help="draft tokens proposed to verify steps",
        )
        self._m_spec_accepted = t.counter(
            "serve_spec_accepted_total",
            help="draft tokens accepted by verify steps (bonus/corrected "
            "tokens not counted — they are free either way)",
        )
        self._m_spec_verifies = t.counter(
            "serve_spec_verify_total",
            help="batched verify-step program invocations",
        )
        self._m_spec_draft_failures = t.counter(
            "serve_spec_draft_failures_total",
            help="draft-proposer failures (slot degraded to plain "
            "single-token decode for the rest of its request)",
        )
        # On the shared log2 ladder like every histogram (counts, not
        # seconds: tokens emitted land in the 1/2/4/8 buckets, so
        # snapshots still merge and diff like the latency tables).
        self._m_spec_per_verify = t.histogram(
            "serve_spec_accepted_per_verify",
            help="tokens emitted per SPECULATING slot per verify step "
            "(accepted drafts + the corrected/bonus token; 1 = nothing "
            "accepted; zero-draft slots riding the tile are excluded)",
        )
        self.watchdog = StallWatchdog(
            stall_timeout_s,
            name="serve",
            registry=t,
            timeline=self.timeline,
            dump_path=stall_dump_path,
            first_beat_scale=stall_first_beat_scale,
        )

    def _phase(self, name, *, t0, dur_s, trace=None, parent=None, **attrs):
        """Span plus guaranteed phase record: the engine-built tracer tees
        finished spans into ``self.timeline``, which is what keeps
        ``telemetry.jsonl`` carrying the phase records; with any other
        tracer the span (if recorded at all) lands elsewhere, so emit a
        bare timeline event too."""
        self.tracing.emit(
            name, t0=t0, dur_s=dur_s, trace=trace, parent=parent,
            cat="serve", **attrs,
        )
        if not self._phases_via_tee:
            self.timeline.event(
                name, dur_s=round(max(float(dur_s), 0.0), 9), **attrs
            )

    # ----------------------------------------------------------- frontend

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        request_id: int | None = None,
        *,
        deadline_s: float | None = None,
    ) -> int:
        """Enqueue a request; returns its id. ``deadline_s`` (seconds
        from now; ``None`` = the engine's ``default_deadline_s``, 0 = no
        deadline) bounds the request's total latency — see
        ``Completion.finish_reason`` for the typed outcomes. Malformed
        requests still raise here (caller bugs), but LOAD conditions
        (queue full) come back as a typed ``"shed"`` completion, so a
        client library can treat overload as data, not control flow."""
        req = self._new_request(prompt, max_new_tokens, request_id,
                                deadline_s=deadline_s)
        # Bounded admission (ISSUE 9): beyond max_queue_depth QUEUED
        # requests, shed typed instead of growing the queue without
        # bound — active slots are not counted (they already have their
        # memory), so the bound is exactly "work not yet started".
        if self.max_queue_depth and len(self._queue) >= self.max_queue_depth:
            self._m_shed.inc()
            self._complete_unadmitted(req, "shed")
            return req.id
        self._queue.append(req)
        return req.id

    def _new_request(
        self,
        prompt,
        max_new_tokens: int,
        request_id: int | None = None,
        *,
        deadline_s: float | None = None,
    ) -> ServeRequest:
        """Validate + construct a traced ``ServeRequest`` (id issued,
        trace id born, root span opened) WITHOUT enqueueing it — the
        piece of ``submit`` the disaggregated scheduler (ISSUE 12,
        serving/scheduler.py) shares: its per-tenant queues own the
        enqueue/shed policy, but the request object, the id ledger, and
        the span tree must stay THIS engine's so completions and traces
        read identically either way."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} < 1: prefill always "
                "samples the first token, so a request must want at least "
                "one (this also keeps prompt_len + 1 within the cache)"
            )
        if prompt.size + max_new_tokens > self.seq_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the model context ({self.seq_len})"
            )
        if self.paged:
            _, total = self._request_blocks(int(prompt.size), max_new_tokens)
            if total > self.pool_blocks - 1:
                raise ValueError(
                    f"request needs {total} KV blocks but the pool holds "
                    f"{self.pool_blocks - 1} usable — it could never admit "
                    "(raise serving.kv_pool_blocks or shrink the request)"
                )
        rid = self._next_id if request_id is None else request_id
        if rid in self._issued_ids:
            raise ValueError(
                f"request_id {rid} already used — completions are keyed "
                "by id, so a duplicate would silently shadow a result"
            )
        self._issued_ids.add(rid)
        self._next_id = max(self._next_id, rid) + 1
        req = ServeRequest(rid, prompt, int(max_new_tokens))
        req.deadline_s = (
            self.default_deadline_s if deadline_s is None else float(deadline_s)
        )
        # Trace-id propagation contract: the id is born HERE, at enqueue,
        # and every span this request generates (queue_wait, prefill,
        # graft, decode ticks, retire) carries it — the root "request"
        # span stays open until retirement so the tree spans
        # enqueue→retire.
        req.trace = self.tracing.new_trace(f"request {rid}")
        req.span = self.tracing.begin(
            "request", trace=req.trace, cat="serve", request=rid,
            prompt_len=int(prompt.size),
        )
        # One clock read serves both: queue_wait is emitted retroactively
        # from t_submit, so it must start exactly where the root does or
        # the tree's containment invariant breaks by a few microseconds.
        req.t_submit = getattr(req.span, "t0", None) or time.perf_counter()
        return req

    def _complete_unadmitted(self, req: ServeRequest, reason: str) -> None:
        """Resolve a request that never occupied a slot (shed / expired
        in queue / quarantined at admission) with a typed completion: the
        prompt comes back untouched, zero generated tokens, and the root
        span closes so the trace tree still reads enqueue→resolution."""
        comp = Completion(
            id=req.id,
            tokens=req.prompt.copy(),
            prompt_len=int(req.prompt.size),
            finish_reason=reason,
            token_latencies_s=[],
        )
        self._early.append(comp)
        self.stats["completed"] += 1
        self.stats[f"finish_{reason}"] += 1
        self._m_completed.inc()
        self._phase(
            "retire", t0=time.perf_counter(), dur_s=0.0,
            trace=req.trace, parent=req.span,
            request=req.id, reason=reason, n_tokens=0,
        )
        req.span.end(finish_reason=reason, n_tokens=0)

    def _expired(self, req: ServeRequest, now: float | None = None) -> bool:
        if not req.deadline_s:
            return False
        now = time.perf_counter() if now is None else now
        return now - req.t_submit > req.deadline_s

    @property
    def pending(self) -> int:
        return len(self._queue) + int(self._active.sum())

    def reset_cache(self) -> None:
        """Drop the device cache and bucket state (jit caches survive —
        they are keyed on shapes, not state). For measurement loops that
        want a cold-state pass over warm compiled programs
        (tools/serve_bench.py): the bucket trajectory replays instead of
        starting at the warm pass's terminal bucket. Refuses while
        requests are in flight."""
        if self._active.any():
            raise RuntimeError("reset_cache with active slots in flight")
        self.cache = None
        self.bucket = 0
        if self.paged:
            self._free = list(range(self.pool_blocks - 1, 0, -1))
            self._ref[:] = 0
            self._reserved_future = 0
            self._slot_blocks = [[] for _ in range(self.num_slots)]
            self._slot_future[:] = 0
            self._parked_held.clear()
            self._slot_prefix_hit[:] = False
            self._slot_tokens_saved[:] = 0
            self._tables[:] = 0
            self._tables_dirty = True
            self._prefix_cache.clear()
        self._slot_spec_degraded[:] = False
        self._slot_spec_proposed[:] = 0
        self._slot_spec_accepted[:] = 0
        self.stats.clear()
        # The warm pass's observations include compile time — drop them
        # so the measured pass's histograms report serving, not XLA.
        self.telemetry.reset()
        self.timeline.drain()
        self.tracing.drain()

    def bytes_per_slot(self) -> int:
        """Per-slot HBM of the LIVE engine cache at its current bucket —
        from the actual device arrays, so quantization scale tensors and
        per-slot bookkeeping are included (the accounting the bucket HBM
        estimates and serve_bench's bytes-per-slot column must agree
        with; pinned against ``generation.estimate_cache_bytes_per_slot``
        in tests/test_serving.py). 0 before the first admission.

        Paged mode: the cache is a shared pool, so "per slot" is the
        PROVISIONED share — total cache-tree bytes (pool + tables +
        bookkeeping) / num_slots. The per-REQUEST cost paged admission
        actually prices is ``block_bytes()`` x blocks reserved, which is
        what lets a deliberately small pool host more slots than the
        bucketed accounting would (serve_bench's paged capacity column)."""
        if self.cache is None:
            return 0
        if self.paged:
            total = sum(
                int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
                for leaf in jax.tree.leaves(self.cache)
            )
            return total // self.num_slots
        return cache_bytes_per_slot(self.cache, self.num_slots)

    def close(self) -> None:
        """Stop the watchdog thread (daemon — leak-safe either way)."""
        self.watchdog.stop()

    def export_trace(self, path: str) -> None:
        """Write the span ring as Chrome-trace-event JSON (Perfetto /
        chrome://tracing). One named lane per request plus the engine
        lane; non-consuming, so it can be called mid-serve."""
        self.tracing.write_chrome_trace(path)

    def run(self, max_steps: int | None = None) -> list[Completion]:
        """Drain the queue; returns completions in finish order (typed
        shed/deadline/error completions included — every submitted id
        resolves exactly once, the never-hangs contract)."""
        out: list[Completion] = []
        steps = 0
        while self.pending:
            out.extend(self.step())
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        # Requests resolved without ever entering a slot (e.g. every
        # submit shed on a full queue) never pass through step().
        out.extend(self._early)
        self._early.clear()
        return out

    # ------------------------------------------------------ jitted shapes

    def _model_at(self, cache_len: int):
        return self.model.clone(cache_len=int(cache_len))

    def _trace_ctx(self):
        from frl_distributed_ml_scaffold_tpu.dist.mesh import mesh_context

        return mesh_context(self._env)

    def _prefill_fn(self, s_p: int):
        if s_p not in self._prefill_jit:
            self._prefill_jit[s_p] = make_prefill_program(
                self._model_at(s_p), self._sample_kw
            )
        return self._prefill_jit[s_p]

    def _decode_fn(self, s: int):
        if s not in self._decode_jit:
            m = self._model_at(s)
            kw = dict(self._sample_kw)

            def fn(params, cache, tok, rng):
                logits, cache = _decode_step(m, params, cache, tok)
                return _sample(logits, rng, **kw), cache

            # Donate the cache (the PR 5 graft-lint audit's find): the
            # engine immediately rebinds self.cache to the step's output,
            # so the input cache is dead the moment the call is issued —
            # without donation every decode step transiently holds TWO
            # full KV caches live (cache-in + cache-out), exactly the
            # allocation spike continuous batching sizes its slot count
            # against. Pinned by tests/test_serving.py donation pins via
            # analysis.pins.assert_donated/assert_aliased.
            self._decode_jit[s] = jax.jit(fn, donate_argnums=(1,))
        return self._decode_jit[s]

    def _graft_fn(self, s_p: int, s: int):
        """Write one prefilled request's cache rows into the engine cache
        at a (traced) slot index: a dynamic-update-slice at the leaf's
        slot-row axis (``generation.cache_batch_axis`` — THE cache-leaf
        taxonomy; the beam gather/repeat route through the same
        classifier, so new leaf classes stay in lockstep)."""
        if (s_p, s) not in self._graft_jit:
            n = self.num_slots

            def fn(cache, slot_cache, slot):
                def leaf(e, p):
                    ax = cache_batch_axis(e, n)
                    assert ax is not None, (
                        f"cache leaf {e.shape} carries no slot rows"
                    )
                    idx = (0,) * ax + (slot,) + (0,) * (e.ndim - ax - 1)
                    return jax.lax.dynamic_update_slice(
                        e, p.astype(e.dtype), idx
                    )

                return jax.tree.map(leaf, cache, slot_cache)

            # The engine cache is rebound to the graft's output too —
            # donate it (same audit find as _decode_fn; the slot cache is
            # NOT donated: its rows are read strided into the update).
            self._graft_jit[(s_p, s)] = jax.jit(fn, donate_argnums=(0,))
        return self._graft_jit[(s_p, s)]

    def _grow_fn(self, s_old: int, s_new: int):
        if (s_old, s_new) not in self._grow_jit:

            def fn(cache):
                def leaf(e):
                    # Pad every capacity-bearing leaf (K/V stacks AND
                    # their quantization-scale stacks) along the cache
                    # axis; bookkeeping leaves pass through.
                    ax = cache_capacity_axis(e, s_old)
                    if ax is None:
                        return e
                    pad = [(0, 0)] * e.ndim
                    pad[ax] = (0, s_new - s_old)
                    return jnp.pad(e, pad)

                return jax.tree.map(leaf, cache)

            self._grow_jit[(s_old, s_new)] = jax.jit(fn)
        return self._grow_jit[(s_old, s_new)]

    # ------------------------------------------------------- paged programs

    def _paged_model(self):
        return self.model.clone(
            kv_block_size=self.block_size, kv_pool_blocks=self.pool_blocks
        )

    def _init_paged_cache(self) -> None:
        """Zero pool + tables + bookkeeping, shaped by the paged model's
        own cache structure (eval_shape — nothing runs), so the engine
        never hardcodes the cache tree. All-zero tables point every row
        at the trash block 0."""
        m = self._paged_model()
        tok = jax.ShapeDtypeStruct((self.num_slots, 1), jnp.int32)
        shapes = jax.eval_shape(
            lambda p, t: m.apply(
                {"params": p}, t, decode=True, mutable=["cache"]
            )[1]["cache"],
            self.params, tok,
        )
        with self._trace_ctx():
            self.cache = jax.jit(
                lambda: jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), shapes
                )
            )()
        self._tables_dirty = True

    def _paged_decode_fn(self):
        """THE paged decode program — one compiled shape for the whole
        engine lifetime (the pool never grows; per-row capacity is the
        block table, which is data, not shape)."""
        if self._paged_decode_jit is None:
            m = self._paged_model()
            kw = dict(self._sample_kw)

            def fn(params, cache, tok, rng):
                logits, cache = _decode_step(m, params, cache, tok)
                return _sample(logits, rng, **kw), cache

            # Donate the cache (pool included) — the same two-caches-live
            # audit fix as _decode_fn, now sized at the POOL.
            self._paged_decode_jit = jax.jit(fn, donate_argnums=(1,))
        return self._paged_decode_jit

    def _prefill_seeded_fn(self, s_p: int, s_c: int):
        """Suffix prefill for shared-prefix admissions: the prompt SUFFIX
        (bucketed to ``s_p``) prefills against an initial slot cache of
        capacity ``s_c`` whose leading positions hold the shared prefix's
        K/V and whose indices start at the prefix length — the attention
        math is identical to a full-prompt prefill minus the prefix
        tokens' projection/score work (that is the prefill-once win)."""
        if (s_p, s_c) not in self._prefill_seeded_jit:
            self._prefill_seeded_jit[(s_p, s_c)] = (
                make_seeded_prefill_program(
                    self._model_at(s_c), self._sample_kw
                )
            )
        return self._prefill_seeded_jit[(s_p, s_c)]

    def _seed_fn(self, s_c: int, m: int):
        """Gather ``m`` shared pool blocks into the leading positions of
        a fresh slot cache at capacity ``s_c`` (indices seeded to
        ``m*block_size``): exactly the blocks that change hands move —
        never a logical-cache materialization (gather at the boundary)."""
        if (s_c, m) not in self._seed_jit:
            bs = self.block_size

            def fn(cache, ids):
                from flax.traverse_util import flatten_dict, unflatten_dict

                flat = flatten_dict(cache)
                out = {}
                for kp, leaf in flat.items():
                    name = kp[-1]
                    if name in SLOT_LEAF_OF:
                        # [L, N, bs, ...] -> [L, m, bs, ...] gather ->
                        # [L, 1, m*bs, ...] contiguous prefix, padded to
                        # the slot-cache capacity.
                        g = jnp.take(leaf, ids, axis=1)
                        contig = g.reshape(
                            (leaf.shape[0], 1, m * bs) + leaf.shape[3:]
                        )
                        pad = [(0, 0)] * contig.ndim
                        pad[2] = (0, s_c - m * bs)
                        out[kp[:-1] + (SLOT_LEAF_OF[name],)] = jnp.pad(
                            contig, pad
                        )
                    elif name == "cache_index":
                        out[kp] = jnp.full(
                            (leaf.shape[0], 1), m * bs, jnp.int32
                        )
                    elif name == "pos_index":
                        out[kp] = jnp.full((1,), m * bs, jnp.int32)
                    # block_tables: slot caches carry none.
                return unflatten_dict(out)

            self._seed_jit[(s_c, m)] = jax.jit(fn)
        return self._seed_jit[(s_c, m)]

    def _paged_graft_fn(self, s_c: int, n_priv: int):
        """The handoff SPLICE program (``generation.splice_pool_blocks``
        — one shared artifact: the colocated admission graft, the
        disaggregated prefill→decode handoff, and graft-lint's
        ``serving:handoff`` program are all this function): scatter the
        ``n_priv`` private blocks starting at logical block ``m0`` to
        the physical ids in ``blk_ids`` and set the slot's cache_index /
        pos_index rows — shared prefix blocks are already in the pool
        and are NOT touched (move only the blocks that change owner).
        The engine cache (pool) is donated like every program that
        rebinds it; appends and growth never clone it."""
        if (s_c, n_priv) not in self._paged_graft_jit:
            import functools

            self._paged_graft_jit[(s_c, n_priv)] = jax.jit(
                functools.partial(
                    splice_pool_blocks, block_size=self.block_size
                ),
                donate_argnums=(0,),
            )
        return self._paged_graft_jit[(s_c, n_priv)]

    # ------------------------------------------------- speculative decoding

    def _verify_fn(self):
        """THE verify program — ONE compiled shape for the engine
        lifetime (the [B, k+1] tile is fixed at construction; no per-k
        bucket ladder — graft-lint's ``serving:verify_step_paged``
        program and the compile-once test pin this). Scores all k+1
        positions of every row against the paged cache in one forward
        and returns the greedy argmax per position; the engine accepts
        the longest draft prefix matching these predictions host-side
        — exact, which is the token-identity contract."""
        if self._verify_jit is None:
            m = self._paged_model()

            def fn(params, cache, tile):
                logits, cache = _verify_step(m, params, cache, tile)
                preds = jnp.argmax(
                    logits.astype(jnp.float32), axis=-1
                ).astype(jnp.int32)
                return preds, cache

            # Donate the cache (pool included) — same two-pools-live
            # audit contract as the decode program.
            self._verify_jit = jax.jit(fn, donate_argnums=(1,))
        return self._verify_jit

    def _rewind_fn(self):
        """Speculative ROLLBACK: rewind every row's cache/position
        cursor to its accepted length (``generation.rewind_cache_indices``
        — a pointer move over the donated cache; rejected positions'
        K/V are simply abandoned past the cursor). Freed tail blocks
        are returned host-side by ``step()``'s release loop."""
        if self._rewind_jit is None:
            self._rewind_jit = jax.jit(
                rewind_cache_indices, donate_argnums=(0,)
            )
        return self._rewind_jit

    def _draft_fn(self):
        """Tier-B draft proposer: ONE compiled program batching every
        slot — a ragged (left-padded) prefill of each slot's trailing
        ``spec_window`` history tokens through the small draft model,
        then k greedy steps (``generation.generate`` under jit). The
        draft's cache is the window bucket, re-derived per proposal
        round: no persistent draft cache to keep consistent, nothing to
        roll back — the target pool stays the only stateful cache."""
        if self._draft_jit is None:
            dm, _ = self._draft
            k, w = self.spec_k, self.spec_window

            def fn(params, windows, lengths):
                out = generate(
                    dm, params, windows, max_new_tokens=k,
                    temperature=0.0, prompt_lengths=lengths,
                )
                return out[:, w:]

            self._draft_jit = jax.jit(fn)
        return self._draft_jit

    def _propose(self) -> dict[int, np.ndarray]:
        """Draft tokens per active slot for this step's verify tile:
        ``{slot: [n_j] int tokens}`` with ``1 <= n_j <= spec_k``; a slot
        missing here single-steps (rides the verify program with zero
        drafts, or the plain decode program when nobody proposed).

        Caps: ``n_j <= remaining_budget - 1`` — emitting more than the
        budget is wasted AND would write cache positions past the
        admission reservation (the worst-case block count covers exactly
        positions < prompt + budget - 1). Failure semantics (ISSUE 9
        style): a proposer exception — including the ``serve.draft``
        fault site — degrades THAT slot to plain decode for the rest of
        its request (counted, never sheds, never hangs; output is
        identical because drafting is advisory)."""
        out: dict[int, np.ndarray] = {}
        want: list[int] = []
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            req = self._req[slot]
            r = req.max_new_tokens - len(self._tokens[slot])
            if r < 2 or self._slot_spec_degraded[slot]:
                continue
            try:
                faults.maybe_raise("serve.draft", key=req.id)
            except Exception as e:
                self._spec_degrade(slot, e)
                continue
            want.append(slot)
        if not want:
            return out
        if self.spec_mode == "ngram":
            for slot in want:
                req = self._req[slot]
                r = req.max_new_tokens - len(self._tokens[slot])
                try:
                    hist = np.concatenate(
                        [req.prompt,
                         np.asarray(self._tokens[slot], np.int32)]
                    )
                    d = ngram_propose(
                        hist, min(self.spec_k, r - 1),
                        max_ngram=self.spec_ngram_max,
                    )
                except Exception as e:
                    self._spec_degrade(slot, e)
                    continue
                if d.size:
                    out[slot] = d.astype(np.int64)
            return out
        # Draft-model tier: one batched propose over every wanting slot.
        w = self.spec_window
        windows = np.zeros((self.num_slots, w), np.int32)
        lens = np.ones(self.num_slots, np.int32)
        for slot in want:
            req = self._req[slot]
            hist = np.concatenate(
                [req.prompt, np.asarray(self._tokens[slot], np.int32)]
            )[-w:]
            windows[slot, w - hist.size :] = hist
            lens[slot] = hist.size
        try:
            with self._trace_ctx():
                drafts = np.asarray(jax.device_get(
                    self._draft_fn()(
                        self._draft[1],
                        jnp.asarray(windows),
                        jnp.asarray(lens),
                    )
                ))
        except Exception as e:
            # The batched call failed: every participating slot degrades
            # (a crashing draft model would crash every later round too).
            for slot in want:
                self._spec_degrade(slot, e)
            return out
        for slot in want:
            req = self._req[slot]
            r = req.max_new_tokens - len(self._tokens[slot])
            d = drafts[slot, : min(self.spec_k, r - 1)]
            if d.size:
                out[slot] = d.astype(np.int64)
        return out

    def _spec_degrade(self, slot: int, err: Exception) -> None:
        """Sticky per-request degradation to plain single-token decode."""
        self._slot_spec_degraded[slot] = True
        self._m_spec_draft_failures.inc()
        self.stats["spec_draft_failures"] += 1
        from frl_distributed_ml_scaffold_tpu.utils.logging import get_logger

        get_logger().warning(
            "serving: draft proposer failed for slot %d (%s: %s) — "
            "degrading to plain single-token decode for this request",
            slot, type(err).__name__, err,
        )

    def _spec_verify(self, drafts: dict[int, np.ndarray]) -> None:
        """One speculative step over the slot array: build the [B, k+1]
        tile (each row's last token + its drafts, zero-padded — pad
        positions write into the trash block or past-occupancy slots,
        masked out of every later read), run THE verify program, accept
        each row's longest draft prefix matching the greedy predictions
        (EXACT, so the emitted tokens equal plain decode's), then roll
        back: freed tail blocks return to the pool via the reservation
        accounting and every row's device cursor rewinds to its accepted
        length. Deadlines/sheds/quarantine see the emitted group
        ATOMICALLY (PR 9 semantics): eos/budget retire mid-group, the
        deadline check runs once after the group."""
        k = self.spec_k
        tile = np.zeros((self.num_slots, k + 1), np.int32)
        tile[:, 0] = self._last_tok
        n_prop = 0
        for slot, d in drafts.items():
            tile[slot, 1 : 1 + d.size] = d
            self._slot_spec_proposed[slot] += d.size
            n_prop += int(d.size)
        self._m_spec_proposed.inc(n_prop)
        self.stats["spec_proposed"] += n_prop
        t0 = time.perf_counter()
        fn = self._verify_fn()
        with self._trace_ctx():
            preds, self.cache = fn(
                self.params, self.cache, jnp.asarray(tile)
            )
        preds = np.asarray(jax.device_get(preds))
        dt = time.perf_counter() - t0
        n_active = int(self._active.sum())
        self.stats["decode_verify"] += 1
        self.stats["decode_steps"] += 1
        self.stats["slot_steps"] += n_active
        self._m_decodes.inc()
        self._m_spec_verifies.inc()
        self._phase(
            "verify", t0=t0, dur_s=dt, trace=self._engine_trace,
            active=n_active, proposed=n_prop, k=k,
        )
        self.watchdog.beat()
        if self.telemetry.enabled:
            for name, v in _hbm_gib().items():
                (self._m_hbm_used if name == "hbm_in_use_gib"
                 else self._m_hbm_peak).set(v)

        bs = self.block_size
        for slot in range(self.num_slots):
            if not self._active[slot]:
                continue
            req = self._req[slot]
            d = drafts.get(slot)
            n_j = int(d.size) if d is not None else 0
            # Longest accepted draft prefix: draft j survives iff it
            # equals the target's greedy prediction at position j-1.
            a = 0
            while a < n_j and tile[slot, a + 1] == preds[slot, a]:
                a += 1
            # Emitted group: the accepted drafts plus the target's own
            # next token at the first mismatch (the bonus/corrected
            # token — a verify step ALWAYS emits at least one token, so
            # speculation never regresses below plain decode).
            group = [int(x) for x in tile[slot, 1 : a + 1]]
            group.append(int(preds[slot, a]))
            per_tok = dt / len(group)
            emitted = 0
            retired = False
            t_group = time.perf_counter() - req.t_submit
            for i, tok in enumerate(group):
                self._tokens[slot].append(tok)
                self._len[slot] += 1
                self._latency[slot].append(per_tok)
                # The group lands together — one verify program — so its
                # tokens share one arrival time (gaps inside a group are
                # zero; the next gap spans the next verify).
                self._tok_times[slot].append(t_group)
                self._m_tpot.observe(per_tok)
                self._last_tok[slot] = tok
                emitted += 1
                if i < a:
                    self._slot_spec_accepted[slot] += 1
                    self._m_spec_accepted.inc()
                    self.stats["spec_accepted"] += 1
                if self._finishes(slot, tok):
                    retired = True
                    break
            self.stats["step_tokens"] += emitted
            if n_j > 0:
                # Accepted-per-verify accounting covers SPECULATING
                # slots only — a zero-draft slot riding the tile is
                # just a plain decode step for that row (its token
                # still counts in slot_steps/step_tokens, the honest
                # whole-engine invocations-per-token denominator).
                self.stats["spec_emitted"] += emitted
                self.stats["spec_slot_verifies"] += 1
                self._m_spec_per_verify.observe(float(emitted))
            self._phase(
                "decode_tick", t0=t0, dur_s=dt, trace=req.trace,
                parent=req.span, slot=slot,
                token=len(self._tokens[slot]) - 1, spec_emitted=emitted,
            )
            if retired:
                continue
            # Mid-decode deadline cancellation, ATOMIC over the group.
            if self._expired(req):
                self._m_deadline.inc()
                self._retire(slot, "deadline")
                continue
            # Table-pointer rollback: blocks appended for rejected draft
            # positions return to the pool — popped off the table tail,
            # re-counted as future reservations (the admission worst
            # case still holds, so later appends still cannot fail).
            need = (int(self._len[slot]) - 1) // bs + 1
            while len(self._slot_blocks[slot]) > need:
                bid = self._slot_blocks[slot].pop()
                self._tables[slot, len(self._slot_blocks[slot])] = 0
                self._tables_dirty = True
                self._deref(bid)
                self._slot_future[slot] += 1
                self._reserved_future += 1
                self.stats["block_rollback"] += 1
        # Cursor rewind, one donated pointer-move program: the verify
        # step advanced every row's cache_index/pos_index by k+1; the
        # true occupancy is the accepted length (cache_index == _len - 1,
        # the engine invariant). Inactive rows park at 0 — their writes
        # land in the trash block regardless.
        new_idx = np.where(self._active, self._len - 1, 0).astype(np.int32)
        with self._trace_ctx():
            self.cache = self._rewind_fn()(
                self.cache, jnp.asarray(new_idx)
            )
        self._m_pool_util.set(self.pool_utilization())

    # ------------------------------------------------- paged block allocator

    def _deref(self, bid: int) -> None:
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            self._free.append(bid)

    def _evict_one(self) -> bool:
        """Drop the least-recently-used prefix-cache entry; its blocks
        free once no slot (and no other entry) references them."""
        if not self._prefix_cache:
            return False
        _, ids = self._prefix_cache.popitem(last=False)
        for bid in ids:
            self._deref(bid)
        self.stats["prefix_evictions"] += 1
        return True

    def _match_prefix(self, prompt: np.ndarray) -> tuple[int, tuple[int, ...]]:
        """Longest cached full-block chain matching the prompt's leading
        tokens, capped so at least one token remains to prefill (the
        suffix prefill produces the first sampled token's logits).
        Sharing is FULL-block granular: the block containing the first
        divergent (or final partial) position is never shared — it is
        re-derived privately at admission, the copy-on-write that keeps
        shared blocks immutable."""
        if not self.prefix_cache_enabled:
            return 0, ()
        bs = self.block_size
        n_full = (int(prompt.size) - 1) // bs
        # Keys are the EXACT token bytes per chain length (O(L^2/bs) key
        # bytes per unique prompt) — deliberately not per-block chain
        # hashes: a hash collision here would serve one tenant's KV to
        # another, and serving prompts are bounded by seq_len.
        for i in range(n_full, 0, -1):
            key = prompt[: i * bs].tobytes()
            entry = self._prefix_cache.get(key)
            if entry is not None:
                self._prefix_cache.move_to_end(key)
                return i, entry
        return 0, ()

    def _register_prefix(self, prompt: np.ndarray, blocks: list[int]) -> None:
        """Publish every full-block chain of this prompt (each entry
        holds one reference per block, released at eviction)."""
        if not self.prefix_cache_enabled:
            return
        bs = self.block_size
        for i in range(1, int(prompt.size) // bs + 1):
            key = prompt[: i * bs].tobytes()
            if key in self._prefix_cache:
                self._prefix_cache.move_to_end(key)
                continue
            ids = tuple(blocks[:i])
            for bid in ids:
                self._ref[bid] += 1
            self._prefix_cache[key] = ids

    def _request_blocks(self, l: int, n_new: int) -> tuple[int, int]:
        """(blocks allocated at admission, worst-case total): positions
        cached over the request's life are [0, l + n_new - 1) (the final
        sampled token is never written back), and admission allocates
        through position ``l`` — the first decode write — so appends are
        the only growth left."""
        bs = self.block_size
        highest = l + n_new - 2 if n_new >= 2 else l - 1
        total = highest // bs + 1
        now = min(total, l // bs + 1)
        return now, total

    def _pool_reserve(self, req: ServeRequest) -> dict | None:
        """Admission headroom: match the prefix, then reserve every block
        the request can ever need (private-now + future appends) against
        the free list, evicting idle prefix entries LRU if required.
        ``None`` = the pool cannot host the request yet — the queue head
        WAITS (retiring slots release blocks; with bounded admission the
        growing queue sheds new submits, the documented composition)."""
        l, n_new = int(req.prompt.size), req.max_new_tokens
        m, shared = self._match_prefix(req.prompt)
        n_now, n_total = self._request_blocks(l, n_new)
        need = (n_now - m) + (n_total - n_now)
        # Shared blocks are pinned FIRST so the eviction loop can never
        # free the chain we are about to reuse.
        for bid in shared:
            self._ref[bid] += 1
        if len(self._free) - self._reserved_future < need:
            # Evict ONLY if eviction can actually satisfy the request:
            # count the blocks the cache could free (ref held exclusively
            # by cache entries) before touching it — otherwise a
            # deferred oversized head request would strip the whole
            # prefix cache every step() while gaining nothing, silently
            # defeating prefill-once under exactly the load it targets.
            cache_refs = collections.Counter(
                bid for ids in self._prefix_cache.values() for bid in ids
            )
            freeable = sum(
                1 for bid, n in cache_refs.items() if self._ref[bid] == n
            )
            if (
                len(self._free) + freeable - self._reserved_future < need
            ):
                for bid in shared:
                    self._deref(bid)
                return None
            while (
                len(self._free) - self._reserved_future < need
                and self._evict_one()
            ):
                pass
        priv = [self._free.pop() for _ in range(n_now - m)]
        for bid in priv:
            self._ref[bid] += 1
        self._reserved_future += n_total - n_now
        return {
            "m": m,
            "shared": list(shared),
            "priv": priv,
            "future": n_total - n_now,
        }

    def _pool_release(self, res: dict) -> None:
        """Roll back a reservation whose admission failed (quarantine).
        Private ids were popped off the free list; _deref re-appends
        them at refcount zero, so the list is whole again."""
        for bid in res["priv"] + res["shared"]:
            self._deref(bid)
        self._reserved_future -= res["future"]

    def _note_pool_peak(self) -> None:
        """High-watermark of pool DEMAND — blocks held by slots (and by
        PARKED requests: preemption moves ownership out of the slot
        array, not out of the pool) plus worst-case reservations, with
        prefix sharing counted once. This is what serve_bench's paged
        capacity column prices a concurrent slot at: blocks held ONLY by
        the prefix cache are deliberately excluded (they are evicted on
        demand when admission needs the room, so they are a cache, not a
        capacity cost)."""
        held = {bid for blks in self._slot_blocks for bid in blks}
        held.update(
            bid for blks in self._parked_held.values() for bid in blks
        )
        demand = len(held) + self._reserved_future
        if demand > self.stats["pool_peak_blocks"]:
            self.stats["pool_peak_blocks"] = demand

    def pool_utilization(self) -> float:
        """Allocated blocks / usable blocks (trash excluded)."""
        if not self.paged:
            return 0.0
        usable = self.pool_blocks - 1
        return (usable - len(self._free)) / max(usable, 1)

    def block_bytes(self) -> int:
        """HBM bytes of one pool block (all layers, scales included) —
        the unit paged admission is priced in. 0 before the pool exists."""
        if not self.paged or self.cache is None:
            return 0
        return pool_block_bytes(self.cache)

    # --------------------------------------------------------- scheduling

    def _bucket_for(self, needed: int) -> int:
        return next_cache_bucket(self.seq_len, needed, floor=self.min_bucket)

    def _empty_cache(self, slot_cache, s: int):
        """Zeros shaped like a 1-request slot cache widened to the slot
        array (row axis per ``cache_batch_axis``) at cache capacity ``s``
        (capacity-bearing leaves — K/V and scale stacks — per
        ``cache_capacity_axis``, the same taxonomy ``_grow_fn`` pads)."""
        n = self.num_slots

        def leaf(e):
            ax = cache_batch_axis(e, 1)  # slot cache has batch 1
            assert ax is not None, f"cache leaf {e.shape} carries no rows"
            shape = list(e.shape)
            shape[ax] = n
            cap = cache_capacity_axis(e, s)
            if cap is not None:
                shape[cap] = s
            return jnp.zeros(tuple(shape), e.dtype)

        return jax.tree.map(leaf, slot_cache)

    def _ensure_bucket(self, needed: int) -> None:
        """Grow the cache to cover ``needed`` tokens; raises
        ``CacheGrowError`` (counted) when the pad allocation fails — the
        callers degrade per-request instead of crashing the engine."""
        target = self._bucket_for(needed)
        if target > self.bucket:
            t0 = time.perf_counter()
            try:
                faults.maybe_raise(
                    "serve.grow", CacheGrowError,
                    msg=f"injected grow failure {self.bucket}->{target}",
                )
                grown = self._grow_fn(self.bucket, target)(self.cache)
            except Exception as e:
                self._m_grow_failures.inc()
                self.stats["grow_failures"] += 1
                if isinstance(e, CacheGrowError):
                    raise
                raise CacheGrowError(
                    f"cache grow {self.bucket}->{target} failed: {e}"
                ) from e
            self.cache = grown
            self.stats[f"grow_{self.bucket}->{target}"] += 1
            self._m_grows.inc()
            # Grows belong to the ENGINE lane, not any one request: the
            # pad reshapes the shared slot-array cache (the span's tee
            # keeps the old bucket_grow timeline record alive).
            self._phase(
                "bucket_grow", t0=t0, dur_s=time.perf_counter() - t0,
                trace=self._engine_trace,
                frm=self.bucket, to=target,
            )
            self.bucket = target
            self._m_bytes_slot.set(self.bytes_per_slot())

    def _admit(self) -> None:
        for slot in range(self.num_slots):
            if self._active[slot]:
                continue
            # One free slot keeps consuming the queue until a request
            # actually admits: expired and poison requests resolve typed
            # and must not burn the slot's admission for this step.
            while self._queue:
                req = self._queue[0]
                if self._expired(req):
                    # Past deadline while still queued: shedding now is
                    # strictly better than prefilling work whose answer
                    # the caller has already abandoned.
                    self._queue.popleft()
                    self._m_deadline.inc()
                    self._complete_unadmitted(req, "deadline")
                    continue
                res = None
                if self.paged:
                    res = self._pool_reserve(req)
                    if res is None:
                        # Pool headroom exhausted: the head request
                        # WAITS (FIFO — no smaller request jumps it) for
                        # retiring slots to release blocks. Backpressure,
                        # not failure: with max_queue_depth set, the
                        # queue growing past the bound sheds new submits
                        # typed, which is the documented pool-exhaustion
                        # x bounded-admission composition.
                        self.stats["admission_deferred"] += 1
                        return
                self._queue.popleft()
                if self._try_admit(slot, req, res):
                    break

    def _prefill_package(self, req: ServeRequest, res: dict | None, sub):
        """The PREFILL-WORKER half of admission (ISSUE 12): gather the
        shared-prefix seed from the pool (when hit) and run the shared
        prefill recipe (``prefill_request``) against this engine's own
        programs/params. Must run under ``_trace_ctx`` with the paged
        pool initialized; device arrays come back un-fetched so a
        disaggregated caller can dispatch asynchronously."""
        return prefill_request(
            req, res, sub,
            block_size=self.block_size if self.paged else 0,
            bucket_for=self._bucket_for, params=self.params,
            prefill_fn=self._prefill_fn,
            seeded_fn=self._prefill_seeded_fn,
            seed_cache=self._seed_for(req, res),
        )

    def _seed_for(self, req: ServeRequest, res: dict | None):
        """The SEED half of a shared-prefix admission, in one place for
        both admission paths (colocated ``_prefill_package`` and the
        disaggregated scheduler): gather the matched prefix blocks from
        the pool into a slot-cache seed — ``None`` when there is no
        prefix hit. Must run under ``_trace_ctx`` (the pool lives on the
        decode partition; a separate prefill partition receives the seed
        via the scheduler's transfer)."""
        m = res["m"] if res is not None else 0
        if m == 0:
            return None
        s_c = self._bucket_for(int(req.prompt.size))
        return self._seed_fn(s_c, m)(
            self.cache, jnp.asarray(res["shared"], jnp.int32)
        )

    def _graft_package(
        self, slot: int, req: ServeRequest, res: dict | None,
        slot_cache, s_p: int, s_c: int, m: int, m0: int | None = None,
    ) -> None:
        """The SPLICE half of admission: move the prefilled cache into
        the shared engine cache. Paged: the block-table splice —
        ``generation.splice_pool_blocks`` writes only the private blocks
        that change owner into the pool, then ownership lands as a
        host-side table-row write (zero logical-cache copy; the handoff
        the disaggregated scheduler rides). Bucketed: the
        dynamic-update-slice graft. Must run under ``_trace_ctx``."""
        l = int(req.prompt.size)
        if self.paged:
            n_g = blocks_for_tokens(l, self.block_size)
            # ``m0`` is the private blocks' logical offset WITHIN the
            # slot cache: ``m`` for a full bucketed cache, 0 when the
            # scheduler pre-sliced the cross-partition transfer down to
            # the private window.
            self.cache = self._paged_graft_fn(s_c, n_g - m)(
                self.cache,
                slot_cache,
                jnp.asarray(res["priv"][: n_g - m], jnp.int32),
                jnp.int32(m if m0 is None else m0),
                jnp.int32(slot),
            )
            # The re-own: ownership moves as one table-row write.
            blocks = res["shared"] + res["priv"]
            self._tables[slot, :] = 0
            self._tables[slot, : len(blocks)] = blocks
            self._tables_dirty = True
        else:
            if self.cache is None:
                self.cache = self._empty_cache(slot_cache, s_p)
                self.bucket = s_p
            self._ensure_bucket(max(s_p, l + 1))
            self.cache = self._graft_fn(s_p, self.bucket)(
                self.cache, slot_cache, jnp.int32(slot)
            )

    def _try_admit(
        self, slot: int, req: ServeRequest, res: dict | None = None
    ) -> bool:
        """Prefill + graft ``req`` into ``slot``. A failure ANYWHERE in
        the request's own admission work (poison prompt crashing the
        prefill, cache growth failing) quarantines THIS request with a
        typed ``"error"`` completion and leaves the engine serving — one
        failing request must never wedge the batch (ISSUE 9). The shared
        cache is only rebound to outputs of successful programs, so a
        failed admission cannot corrupt live slots."""
        l = int(req.prompt.size)
        prev_rng = self._rng
        self._rng, sub = jax.random.split(self._rng)
        t0 = time.perf_counter()
        # Queue wait is only known now — emit it retrospectively,
        # spanning submit→admission, as the request tree's first leaf.
        self._phase(
            "queue_wait", t0=req.t_submit, dur_s=t0 - req.t_submit,
            trace=req.trace, parent=req.span, slot=slot,
        )
        try:
            faults.maybe_raise("serve.prefill", key=req.id)
            with self._trace_ctx():
                if self.paged and self.cache is None:
                    self._init_paged_cache()
                tok, slot_cache, s_p, s_c, m, l_suf = self._prefill_package(
                    req, res, sub
                )
                t_graft = time.perf_counter()
                self._graft_package(slot, req, res, slot_cache, s_p, s_c, m)
                self._phase(
                    "graft", t0=t_graft,
                    dur_s=time.perf_counter() - t_graft,
                    trace=req.trace, parent=req.span,
                    slot=slot, bucket=self.bucket,
                    **({"blocks": blocks_for_tokens(l, self.block_size) - m,
                        "shared": m} if self.paged
                       else {}),
                )
            tok = int(jax.device_get(tok)[0])
        except Exception as e:
            # Quarantine: typed resolution + counter + a loud log with
            # the cause — systemic breakage (every request failing) shows
            # up immediately in serve_quarantined_total's rate. The
            # failed admission's RNG split is rolled back, so later
            # requests see exactly the splits a fault-free run would
            # give them — chaos token-identity holds for SAMPLED
            # (temperature>0) decode too, not just greedy.
            self._rng = prev_rng
            if res is not None:
                self._pool_release(res)
            self._m_quarantined.inc()
            self.stats["quarantined"] += 1
            from frl_distributed_ml_scaffold_tpu.utils.logging import (
                get_logger,
            )

            get_logger().warning(
                "serving: request %d quarantined at admission "
                "(%s: %s) — slot %d stays free, batch keeps decoding",
                req.id, type(e).__name__, e, slot,
            )
            self._complete_unadmitted(req, "error")
            return False
        self._finish_admit(
            slot, req, res, tok,
            t0=t0, dt=time.perf_counter() - t0, s_p=s_p, m=m, l_suf=l_suf,
        )
        return True

    def _finish_admit(
        self, slot: int, req: ServeRequest, res: dict | None, tok: int,
        *, t0: float, dt: float, s_p: int, m: int, l_suf: int,
    ) -> None:
        """Admission bookkeeping shared by the colocated path
        (``_try_admit``) and the disaggregated handoff
        (``admit_handoff``): stats, SLO observations, prefix publication,
        and slot activation. ``dt`` is the TTFT this engine charges the
        request (prefill + splice, however they were scheduled)."""
        l = int(req.prompt.size)
        bs = self.block_size if self.paged else 0
        self.stats[f"prefill_{s_p}"] += 1
        self.stats["admitted"] += 1
        self.stats["prefill_tokens"] += l_suf
        # TTFT = submit-to-first-token work this engine performed for
        # the request: prefill + graft + the forced first-token fetch.
        # (Queue wait is visible separately via serve_queue_depth.)
        self._m_ttft.observe(dt)
        self._m_prefills.inc()
        self._m_grafts.inc()
        self._m_bytes_slot.set(self.bytes_per_slot())
        if self.paged:
            self._slot_blocks[slot] = res["shared"] + res["priv"]
            self._slot_future[slot] = res["future"]
            self._note_pool_peak()
            self._slot_prefix_hit[slot] = m > 0
            self._slot_tokens_saved[slot] = m * bs
            if m > 0:
                self.stats["prefix_hits"] += 1
                self.stats["prefill_tokens_saved"] += m * bs
                self._m_prefix_hits.inc()
                self._m_prefix_saved.inc(m * bs)
            self._m_prefix_hit_rate.set(
                self.stats["prefix_hits"] / self.stats["admitted"]
            )
            self._m_pool_util.set(self.pool_utilization())
            # Publish this prompt's full-block chains for later
            # admissions (refcounted by the cache itself).
            self._register_prefix(req.prompt, self._slot_blocks[slot])
        self._phase(
            "prefill", t0=t0, dur_s=dt, trace=req.trace,
            parent=req.span,
            slot=slot, bucket=s_p, request=req.id,
            **({"prefix_hit": m > 0, "tokens_saved": m * bs}
               if self.paged else {}),
        )
        self.watchdog.beat()

        self._req[slot] = req
        self._tokens[slot] = [tok]
        self._len[slot] = l + 1
        self._active[slot] = True
        self._latency[slot] = [dt]
        self._tok_times[slot] = [time.perf_counter() - req.t_submit]
        self._last_tok[slot] = tok
        self._slot_spec_degraded[slot] = False
        self._slot_spec_proposed[slot] = 0
        self._slot_spec_accepted[slot] = 0
        # The first sampled token can already finish the request.
        self._finishes(slot, tok)

    # ------------------------------------------- disaggregated entry points

    def admit_handoff(
        self, slot: int, req: ServeRequest, res: dict,
        slot_cache, tok: int, *, m: int, prefill_s: float,
        sliced: bool = False,
    ) -> None:
        """DECODE-WORKER admission of a prefill-worker package (ISSUE
        12): splice the package's private blocks into the pool —
        ``generation.splice_pool_blocks``, the same program colocated
        admission jits, so the two paths cannot drift — and activate the
        slot. ``prefill_s`` is the prefill worker's wall time, folded
        into the request's TTFT. Raises on splice failure: the scheduler
        RE-QUEUES the request (quarantine is the colocated admission
        contract; re-queue is the disaggregated one — the prefill can be
        retried on a healthy worker), and the engine state is untouched
        because the pool is only rebound to a successful program's
        output and the table/slot bookkeeping runs after it."""
        assert self.paged, "handoff admission is a paged-engine contract"
        assert not self._active[slot], f"slot {slot} is occupied"
        l = int(req.prompt.size)
        bs = self.block_size
        s_c = self._bucket_for(l)
        t0 = time.perf_counter()
        with self._trace_ctx():
            if self.cache is None:
                self._init_paged_cache()
            self._graft_package(
                slot, req, res, slot_cache, self._bucket_for(l - m * bs),
                s_c, m, m0=0 if sliced else None,
            )
        dt_splice = time.perf_counter() - t0
        self.stats["handoff_splices"] += 1
        self._phase(
            "handoff", t0=t0, dur_s=dt_splice, trace=req.trace,
            parent=req.span, slot=slot,
            blocks=blocks_for_tokens(l, bs) - m, shared=m,
        )
        # The prefill span must END now, not prefill_s in the future:
        # the prefill ran on the worker BEFORE the splice, so the span's
        # honest interval is [splice_start - prefill_s, now] (it may
        # overlap other requests' spans — concurrent prefill is the
        # point of the split).
        self._finish_admit(
            slot, req, res, tok,
            t0=t0 - prefill_s, dt=prefill_s + dt_splice,
            s_p=self._bucket_for(l - m * bs), m=m, l_suf=l - m * bs,
        )

    def park_slot(self, slot: int) -> dict:
        """Preemption PARK (ISSUE 12): deactivate ``slot`` while its
        request keeps owning its KV blocks — ZERO device work (the paged
        pool is what makes parking free: the row's table points back at
        the trash block, the physical blocks stay referenced by the
        parked request, and the worst-case reservation stays accounted so
        the resumed request's appends still can never fail). Returns the
        opaque parked state ``resume_parked`` restores."""
        assert self.paged, "parking is a paged-engine contract"
        assert self._active[slot], f"slot {slot} has nothing to park"
        parked = {
            "req": self._req[slot],
            "tokens": self._tokens[slot],
            "len": int(self._len[slot]),
            "last_tok": int(self._last_tok[slot]),
            "latency": self._latency[slot],
            "tok_times": self._tok_times[slot],
            "blocks": self._slot_blocks[slot],
            "future": int(self._slot_future[slot]),
            "prefix_hit": bool(self._slot_prefix_hit[slot]),
            "tokens_saved": int(self._slot_tokens_saved[slot]),
            "spec": (
                bool(self._slot_spec_degraded[slot]),
                int(self._slot_spec_proposed[slot]),
                int(self._slot_spec_accepted[slot]),
            ),
        }
        self._req[slot] = None
        self._active[slot] = False
        self._tokens[slot] = []
        self._latency[slot] = []
        self._tok_times[slot] = []
        self._len[slot] = 0
        self._slot_blocks[slot] = []
        self._slot_future[slot] = 0
        self._parked_held[parked["req"].id] = parked["blocks"]
        self._tables[slot, :] = 0
        self._tables_dirty = True
        self.stats["parked"] += 1
        req = parked["req"]
        self._phase(
            "park", t0=time.perf_counter(), dur_s=0.0,
            trace=req.trace, parent=req.span, slot=slot,
            n_tokens=len(parked["tokens"]),
        )
        return parked

    def resume_parked(self, parked: dict, slot: int) -> None:
        """Preemption RESUME: re-own the parked block table into ``slot``
        (a table-row write) and restore the row's device cursors with one
        pointer-move program (``rewind_cache_indices`` — the speculation
        rollback reused: active rows already sit at ``len - 1``, the
        engine invariant, so the move only touches the resumed row). The
        request then continues decoding from its parked ``last_tok``,
        token-identically — nothing about its K/V ever moved."""
        assert self.paged and not self._active[slot]
        req = parked["req"]
        self._req[slot] = req
        self._tokens[slot] = parked["tokens"]
        self._len[slot] = parked["len"]
        self._last_tok[slot] = parked["last_tok"]
        self._latency[slot] = parked["latency"]
        self._tok_times[slot] = parked["tok_times"]
        self._slot_blocks[slot] = parked["blocks"]
        self._slot_future[slot] = parked["future"]
        self._slot_prefix_hit[slot] = parked["prefix_hit"]
        self._slot_tokens_saved[slot] = parked["tokens_saved"]
        (self._slot_spec_degraded[slot], self._slot_spec_proposed[slot],
         self._slot_spec_accepted[slot]) = parked["spec"]
        self._parked_held.pop(req.id, None)
        self._active[slot] = True
        self._tables[slot, :] = 0
        self._tables[slot, : len(parked["blocks"])] = parked["blocks"]
        self._tables_dirty = True
        new_idx = np.where(self._active, self._len - 1, 0).astype(np.int32)
        with self._trace_ctx():
            self.cache = self._rewind_fn()(self.cache, jnp.asarray(new_idx))
        self.stats["resumed"] += 1
        self._phase(
            "resume", t0=time.perf_counter(), dur_s=0.0,
            trace=req.trace, parent=req.span, slot=slot,
            n_tokens=len(parked["tokens"]),
        )

    def retire_parked(self, parked: dict, reason: str) -> None:
        """Resolve a PARKED request without resuming it (ISSUE 12 —
        today's caller: the scheduler's parked-deadline sweep): build
        the typed completion carrying the tokens generated before the
        park, release the request's blocks and worst-case reservation,
        and close the span. Needs no slot and no device work — the
        parked K/V are simply abandoned."""
        assert self.paged, "parking is a paged-engine contract"
        req = parked["req"]
        lat = parked["latency"]
        tpot = _log2_quantiles(lat[1:], (0.50, 0.99))
        comp = Completion(
            id=req.id,
            tokens=np.concatenate(
                [req.prompt, np.asarray(parked["tokens"], np.int32)]
            ),
            prompt_len=int(req.prompt.size),
            finish_reason=reason,
            token_latencies_s=lat,
            ttft_s=lat[0] if lat else 0.0,
            tpot_p50_s=tpot[0],
            tpot_p99_s=tpot[1],
            prefix_cache_hit=parked["prefix_hit"],
            prefill_tokens_saved=parked["tokens_saved"],
            spec_accept_rate=(
                parked["spec"][2] / parked["spec"][1]
                if parked["spec"][1] else 0.0
            ),
            token_times_s=parked["tok_times"],
        )
        self._completed.append(comp)
        for bid in parked["blocks"]:
            self._deref(bid)
        self._reserved_future -= parked["future"]
        self._parked_held.pop(req.id, None)
        self._m_pool_util.set(self.pool_utilization())
        self.stats["completed"] += 1
        self.stats[f"finish_{reason}"] += 1
        self._m_completed.inc()
        self._phase(
            "retire", t0=time.perf_counter(), dur_s=0.0,
            trace=req.trace, parent=req.span,
            request=req.id, reason=reason, n_tokens=len(parked["tokens"]),
        )
        req.span.end(finish_reason=reason, n_tokens=len(parked["tokens"]))

    def respread_pool(self, new_env, *, scratch_limit_bytes=None) -> dict:
        """Live model-axis RE-SPREAD (ISSUE 15, the serving-autoscaling
        seam): move the engine — params, the paged KV pool with its
        quantization-scale leaves, and every cursor/table leaf — onto
        ``new_env``'s mesh when the model axis grows or shrinks, without
        dropping in-flight work:

        1. every active slot PARKS (free under the paged pool — the PR
           12 machinery: blocks stay owned, the reservation stays
           accounted, zero device work);
        2. the redistribution service moves params (specs carried over,
           per-axis degradation) and the cache tree (pool leaves re-spread
           over heads per the ``generation.pool_heads_axis`` taxonomy;
           block ids are LOGICAL, so tables, the allocator free list,
           refcounts, and the prefix cache all survive untouched);
        3. the jitted program caches are dropped (they traced under the
           old mesh) and every parked slot RESUMES — decode continues
           token-identically (sharded == replicated is the pinned decode
           contract; the RNG is sharding-invariant by construction).

        ``new_env`` is a ``MeshEnv`` or an int model-axis size (a
        model-only mesh over the first N devices). Returns the executed
        plans (``{"params": ..., "cache": ..., "draft_params": ...}``)
        for cost attribution — ``bytes_moved`` is the shard delta, not
        the pool size. The move is DONATED end to end (the subsystem's
        in-place contract: peak transient ~= one leaf's src + dst, not
        two trees): the engine takes ownership of the param buffers it
        was constructed with, so callers sharing that exact tree with
        another consumer must re-place their copy first."""
        if not self.paged:
            raise ValueError(
                "respread_pool is a paged-engine contract "
                "(serving.kv_block_size > 0): the bucketed cache has no "
                "shared pool to re-spread"
            )
        from frl_distributed_ml_scaffold_tpu import redistribute
        from frl_distributed_ml_scaffold_tpu.dist.mesh import (
            MeshConfig as _MeshCfg,
            build_mesh,
        )
        from frl_distributed_ml_scaffold_tpu.models.generation import (
            pool_leaf_spec,
        )

        if isinstance(new_env, int):
            n = new_env
            new_env = build_mesh(
                _MeshCfg(data=1, model=n), devices=jax.devices()[:n]
            )
        n_model = new_env.axis_size("model")
        if n_model > 1 and self.model.config.num_heads % n_model != 0:
            raise ValueError(
                f"model axis {n_model} does not divide num_heads="
                f"{self.model.config.num_heads} — the pool shards heads"
            )
        t0 = time.perf_counter()
        # COMPILE every plan before touching any engine state: plan
        # errors (unclean layouts, indivisible dims, non-addressable
        # shards caught at chunking) surface with nothing parked and
        # nothing donated.
        plans: dict[str, Any] = {}
        plans["params"] = redistribute.compile_tree_plan(
            self.params,
            redistribute.mesh_shardings(self.params, new_env),
            scratch_limit_bytes=scratch_limit_bytes,
        )
        if self._draft is not None:
            plans["draft_params"] = redistribute.compile_tree_plan(
                self._draft[1],
                redistribute.mesh_shardings(self._draft[1], new_env),
                scratch_limit_bytes=scratch_limit_bytes,
            )
        if self.cache is not None:
            from flax.traverse_util import flatten_dict, unflatten_dict

            flat = flatten_dict(self.cache)
            dst = {}
            for kp, leaf in flat.items():
                spec = pool_leaf_spec(kp[-1], leaf)
                if spec is None:
                    spec = getattr(
                        getattr(leaf, "sharding", None), "spec", None
                    )
                if spec is None:
                    from jax.sharding import PartitionSpec as P

                    spec = P()
                dst[kp] = redistribute.spec_on(new_env.mesh, leaf, spec)
            plans["cache"] = redistribute.compile_tree_plan(
                self.cache, unflatten_dict(dst),
                scratch_limit_bytes=scratch_limit_bytes,
            )
        parked = [
            (int(s), self.park_slot(int(s)))
            for s in np.flatnonzero(self._active)
        ]
        try:
            self.params = redistribute.execute(
                plans["params"], self.params, donate=True
            )
            if self._draft is not None:
                dm, dp = self._draft
                self._draft = (
                    dm,
                    redistribute.execute(
                        plans["draft_params"], dp, donate=True
                    ),
                )
            if self.cache is not None:
                self.cache = redistribute.execute(
                    plans["cache"], self.cache, donate=True
                )
        except BaseException:
            # A mid-move failure leaves the device state partially
            # migrated (donation is per-leaf) — the engine cannot
            # safely resume decoding, but the NEVER-HANGS contract
            # survives: every parked request resolves typed "error"
            # (blocks + reservations released, host-side only) instead
            # of being stranded in an unreachable parked dict.
            for _slot, p in parked:
                self.retire_parked(p, "error")
            raise
        # Programs traced under the old mesh are unusable (and would
        # silently recompute on stale shardings): drop every jit cache;
        # they rebuild lazily under the new mesh context.
        self._env = new_env
        self._prefill_jit.clear()
        self._decode_jit.clear()
        self._graft_jit.clear()
        self._grow_jit.clear()
        self._paged_decode_jit = None
        self._prefill_seeded_jit.clear()
        self._seed_jit.clear()
        self._paged_graft_jit.clear()
        self._verify_jit = None
        self._rewind_jit = None
        self._draft_jit = None
        self._tables_dirty = True
        for slot, p in parked:
            self.resume_parked(p, slot)
        moved = sum(p.bytes_moved for p in plans.values())
        self.stats["respread"] += 1
        self._m_respread.inc()
        self._m_respread_bytes.inc(moved)
        self._phase(
            "respread", t0=t0, dur_s=time.perf_counter() - t0,
            trace=self._engine_trace, model_axis=n_model,
            bytes_moved=moved, parked=len(parked),
        )
        return plans

    def _finishes(self, slot: int, tok: int) -> bool:
        req = self._req[slot]
        if self.eos_id is not None and tok == self.eos_id:
            self._retire(slot, "eos")
            return True
        if len(self._tokens[slot]) >= req.max_new_tokens:
            self._retire(slot, "length")
            return True
        return False

    def _retire(self, slot: int, reason: str) -> None:
        req = self._req[slot]
        lat = self._latency[slot]
        # Per-request SLO columns, through the same log2-bucket estimator
        # the aggregate serve_tpot_seconds histogram uses: ttft is the
        # prefill latency (lat[0]); tpot covers the decode steps (lat[1:]).
        tpot = _log2_quantiles(lat[1:], (0.50, 0.99))
        comp = Completion(
            id=req.id,
            tokens=np.concatenate(
                [req.prompt, np.asarray(self._tokens[slot], np.int32)]
            ),
            prompt_len=int(req.prompt.size),
            finish_reason=reason,
            token_latencies_s=lat,
            ttft_s=lat[0] if lat else 0.0,
            tpot_p50_s=tpot[0],
            tpot_p99_s=tpot[1],
            prefix_cache_hit=(
                bool(self._slot_prefix_hit[slot]) if self.paged else False
            ),
            prefill_tokens_saved=(
                int(self._slot_tokens_saved[slot]) if self.paged else 0
            ),
            spec_accept_rate=(
                float(self._slot_spec_accepted[slot])
                / float(self._slot_spec_proposed[slot])
                if self._slot_spec_proposed[slot] else 0.0
            ),
            token_times_s=self._tok_times[slot],
        )
        self._completed.append(comp)
        self._req[slot] = None
        self._active[slot] = False
        if self.paged:
            # Release the slot's block references (prefix-cache entries
            # keep shared chains alive past retirement — that is the
            # prefill-once cache), drop the unexercised reservation, and
            # point the table row at the trash block so this row's
            # writes in the shared decode program can never land in a
            # freed — possibly reallocated — block.
            for bid in self._slot_blocks[slot]:
                self._deref(bid)
            self._reserved_future -= int(self._slot_future[slot])
            self._slot_blocks[slot] = []
            self._slot_future[slot] = 0
            self._tables[slot, :] = 0
            self._tables_dirty = True
            self._m_pool_util.set(self.pool_utilization())
        self.stats["completed"] += 1
        self.stats[f"finish_{reason}"] += 1
        self._m_completed.inc()
        self._phase(
            "retire", t0=time.perf_counter(), dur_s=0.0,
            trace=req.trace, parent=req.span,
            slot=slot, request=req.id, reason=reason,
            n_tokens=len(self._tokens[slot]),
        )
        # Close the root: the request tree now spans enqueue→retire.
        req.span.end(finish_reason=reason, n_tokens=len(self._tokens[slot]))

    # --------------------------------------------------------------- step

    def _drain_completed(self) -> list[Completion]:
        out = self._completed
        self._completed = []
        return out

    def step(self) -> list[Completion]:
        """Admit into free slots, run ONE decode iteration over the slot
        array, retire finished rows. Returns requests completed during
        this step (possibly at admission, for 1-token budgets; typed
        shed/deadline/error resolutions ride along)."""
        self._m_queue.set(len(self._queue))
        self._admit()
        # Typed completions resolved since the last step (shed at
        # submit) and during this admission round (expired/quarantined).
        self._completed.extend(self._early)
        self._early.clear()
        self._m_occupancy.set(float(self._active.sum()) / self.num_slots)
        if not self._active.any():
            return self._drain_completed()

        # Speculative proposal round (ISSUE 11): drafts per slot for
        # this step's verify tile — BEFORE the block-append loop, which
        # must cover each row's draft write positions too.
        drafts: dict[int, np.ndarray] = {}
        if self.paged and self.spec_mode != "off":
            drafts = self._propose()

        if self.paged:
            # Paged growth: a row crossing a block boundary APPENDS one
            # reserved block to its table — a host-side int write plus a
            # table push, never a device-side cache clone. The
            # reservation made at admission guarantees a free block, so
            # the only failure left is the injected serve.grow fault
            # (kept on the same degrade-per-row contract as bucketed
            # growth: the crossing row retires typed, the batch lives).
            # A speculating row additionally covers its draft write
            # positions (idx .. idx + n_drafts — within the worst-case
            # reservation because drafts are capped at budget - 1);
            # rejected drafts hand their tail blocks back after the
            # verify step.
            for slot in np.flatnonzero(self._active):
                extra = len(drafts.get(int(slot), ()))
                need = (
                    int(self._len[slot]) - 1 + extra
                ) // self.block_size + 1
                while len(self._slot_blocks[slot]) < need:
                    try:
                        faults.maybe_raise(
                            "serve.grow", CacheGrowError,
                            msg=f"injected block-append failure slot {slot}",
                        )
                        bid = self._free.pop()
                    except Exception as e:
                        self._m_grow_failures.inc()
                        self.stats["grow_failures"] += 1
                        from frl_distributed_ml_scaffold_tpu.utils.logging import (
                            get_logger,
                        )

                        get_logger().warning(
                            "serving: block append failed for slot %d "
                            "(%s: %s); retiring it, batch keeps decoding",
                            slot, type(e).__name__, e,
                        )
                        drafts.pop(int(slot), None)
                        self._retire(int(slot), "error")
                        break
                    self._reserved_future -= 1
                    self._slot_future[slot] -= 1
                    self._ref[bid] += 1
                    # (No peak sample here: an append converts one
                    # reservation into one held block — demand is
                    # unchanged, the admission-time sample covers it.)
                    self._slot_blocks[slot].append(bid)
                    self._tables[slot, len(self._slot_blocks[slot]) - 1] = bid
                    self._tables_dirty = True
                    self.stats["block_append"] += 1
                    self._m_block_appends.inc()
                    self._phase(
                        "block_append", t0=time.perf_counter(), dur_s=0.0,
                        trace=self._engine_trace, slot=int(slot), block=bid,
                    )
            self._m_pool_util.set(self.pool_utilization())
            if not self._active.any():
                return self._drain_completed()
            if self._tables_dirty:
                self.cache = {
                    **self.cache,
                    "block_tables": jnp.asarray(self._tables),
                }
                self._tables_dirty = False
            if drafts:
                # At least one slot speculates: the whole batch rides
                # the ONE verify program (slots without drafts
                # single-step inside it — the mixed-batch contract).
                self._spec_verify(drafts)
                return self._drain_completed()
        else:
            # Bucket must hold every active row's next write position: an
            # active row holds cache_index == _len - 1 (prefill sets idx=l
            # with _len=l+1; both advance together), so this step writes
            # position _len - 1 and needs capacity exactly _len.
            try:
                self._ensure_bucket(int(self._len[self._active].max()))
            except CacheGrowError as e:
                # Degrade, don't die: rows that NEED the larger bucket are
                # retired typed ("error", carrying their tokens so far);
                # rows still inside the current bucket keep decoding — a
                # capacity failure at high occupancy costs the big
                # requests, never the whole batch.
                from frl_distributed_ml_scaffold_tpu.utils.logging import (
                    get_logger,
                )

                victims = [
                    s for s in np.flatnonzero(self._active)
                    if self._len[s] > self.bucket
                ]
                get_logger().warning(
                    "serving: cache grow failed (%s); retiring %d slot(s) "
                    "needing the larger bucket, %d keep decoding",
                    e, len(victims), int(self._active.sum()) - len(victims),
                )
                for s in victims:
                    self._retire(int(s), "error")
                if not self._active.any():
                    return self._drain_completed()

        self._rng, sub = jax.random.split(self._rng)
        t0 = time.perf_counter()
        fn = (
            self._paged_decode_fn() if self.paged
            else self._decode_fn(self.bucket)
        )
        with self._trace_ctx():
            nxt, self.cache = fn(
                self.params,
                self.cache,
                jnp.asarray(self._last_tok),
                sub,
            )
        nxt = np.asarray(jax.device_get(nxt))
        dt = time.perf_counter() - t0
        self.stats[
            "decode_paged" if self.paged else f"decode_{self.bucket}"
        ] += 1
        self.stats["decode_steps"] += 1
        # Slot-level invocation accounting (ISSUE 11): a plain step is
        # one invocation per active slot, emitting one token each — the
        # denominator serve_bench's decode-invocations-per-token column
        # (and the speculative reduction ratio) reads from.
        self.stats["slot_steps"] += int(self._active.sum())
        self.stats["step_tokens"] += int(self._active.sum())
        self._m_decodes.inc()
        # One engine-lane span per slot-array decode program...
        self._phase(
            "decode", t0=t0, dur_s=dt, trace=self._engine_trace,
            bucket=self.bucket, active=int(self._active.sum()),
        )
        self.watchdog.beat()
        if self.telemetry.enabled:
            # memory_stats() is a per-device PJRT runtime call — real cost
            # on a ~ms decode step, so the disabled path must skip the
            # query itself, not just the no-op gauge write.
            for k, v in _hbm_gib().items():
                (self._m_hbm_used if k == "hbm_in_use_gib"
                 else self._m_hbm_peak).set(v)

        for slot in range(self.num_slots):
            if not self._active[slot]:
                continue
            req = self._req[slot]
            tok = int(nxt[slot])
            self._tokens[slot].append(tok)
            self._len[slot] += 1
            self._latency[slot].append(dt)
            self._tok_times[slot].append(
                time.perf_counter() - req.t_submit
            )
            self._m_tpot.observe(dt)
            self._last_tok[slot] = tok
            # ...and one request-lane tick per live row, sharing the
            # program's timing (rows decode together in one program, so
            # a per-row clock would be fiction).
            self._phase(
                "decode_tick", t0=t0, dur_s=dt, trace=req.trace,
                parent=req.span, slot=slot,
                token=len(self._tokens[slot]) - 1,
            )
            if self._finishes(slot, tok):
                continue
            # Mid-decode deadline cancellation (ISSUE 9): a natural
            # finish (eos/budget) wins; otherwise a request past its
            # deadline retires NOW with the tokens it has — the slot is
            # freed for refill instead of burning decode steps on an
            # answer the caller has stopped waiting for.
            if self._expired(req):
                self._m_deadline.inc()
                self._retire(slot, "deadline")
        return self._drain_completed()
