"""ResNet family (BASELINE config 2: ResNet-50 ImageNet).

TPU-first choices: NHWC layout throughout (the TPU-native conv layout —
XLA tiles NHWC convs directly onto the MXU), bf16 compute via the precision
policy with fp32 BatchNorm statistics, and v1.5 bottlenecks (stride in the
3x3) matching the torchvision recipe the reference trains. BatchNorm runs as
sync-BN for free: under GSPMD the batch axis is a sharded *global* axis, so
the mean/var reduction spans all data shards (better than DDP's per-replica
BN).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from frl_distributed_ml_scaffold_tpu.config.schema import ResNetConfig
from frl_distributed_ml_scaffold_tpu.precision import Policy

STAGE_SIZES = {
    18: (2, 2, 2, 2),
    34: (3, 4, 6, 3),
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}
BOTTLENECK = {18: False, 34: False, 50: True, 101: True, 152: True}


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: Callable
    norm: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale: residual branches start as identity
        # (the standard large-batch ImageNet trick).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: Callable
    norm: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNetConfig
    policy: Policy

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        cfg = self.config
        dtype = self.policy.compute_dtype
        conv = partial(nn.Conv, use_bias=False, dtype=dtype, padding="SAME")
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=dtype,  # compute in bf16, stats kept fp32 by flax
        )
        x = x.astype(dtype)
        x = conv(64 * cfg.width_multiplier, (7, 7), strides=(2, 2))(x)
        x = norm()(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        block_cls = BottleneckBlock if BOTTLENECK[cfg.depth] else BasicBlock
        for stage, n_blocks in enumerate(STAGE_SIZES[cfg.depth]):
            for block in range(n_blocks):
                x = block_cls(
                    filters=64 * cfg.width_multiplier * (2**stage),
                    strides=2 if (block == 0 and stage > 0) else 1,
                    conv=conv,
                    norm=norm,
                )(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(cfg.num_classes, dtype=dtype)(x)
        return x.astype(self.policy.output_dtype)
