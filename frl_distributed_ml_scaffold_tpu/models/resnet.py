"""ResNet family (BASELINE config 2: ResNet-50 ImageNet).

TPU-first choices: NHWC layout throughout (the TPU-native conv layout —
XLA tiles NHWC convs directly onto the MXU), bf16 compute via the precision
policy with fp32 BatchNorm statistics, and v1.5 bottlenecks (stride in the
3x3) matching the torchvision recipe the reference trains. BatchNorm runs as
sync-BN for free: under GSPMD the batch axis is a sharded *global* axis, so
the mean/var reduction spans all data shards (better than DDP's per-replica
BN).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from frl_distributed_ml_scaffold_tpu.config.schema import ResNetConfig
from frl_distributed_ml_scaffold_tpu.precision import Policy

STAGE_SIZES = {
    10: (1, 1, 1, 1),  # ResNet-10: the minimal smoke/test depth
    18: (2, 2, 2, 2),
    34: (3, 4, 6, 3),
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}
BOTTLENECK = {10: False, 18: False, 34: False, 50: True, 101: True, 152: True}


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """NHWC space-to-depth: (B, H, W, C) -> (B, H/b, W/b, b*b*C), channel
    packing ``(di, dj, c)`` row-major (the order ``s2d_stem_weights``
    assumes)."""
    b, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(
            f"space_to_depth stem needs spatial dims divisible by {block}; "
            f"got {h}x{w} — use stem='conv7' for odd input sizes"
        )
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, block * block * c)


def s2d_stem_weights(w7: jnp.ndarray) -> jnp.ndarray:
    """Exact rewrite of a (7, 7, C, F) stride-2 SAME stem kernel as the
    (4, 4, 4C, F) stride-1 kernel over space-to-depth(2) input.

    Derivation: SAME padding for k=7, s=2 pads (2, 3), so output pixel x
    reads input a = 2x + i - 2, i in [0, 7). In s2d coordinates a = 2u + di
    with u = x + k - 1, hence i = 2k + di for tap k in [0, 4) — the 7x7
    taps relabel one-to-one onto (k, di) with (3, 1) (i.e. i == 7) zero.
    The MLPerf RN50-on-TPU stem trick, kept mathematically exact so the
    equivalence test can assert it.
    """
    k7, _, c, f = w7.shape
    assert k7 == 7
    w4 = jnp.zeros((4, 4, 4 * c, f), w7.dtype)
    for kh in range(4):
        for dh in range(2):
            ih = 2 * kh + dh
            if ih >= 7:
                continue
            for kw in range(4):
                for dw in range(2):
                    iw = 2 * kw + dw
                    if iw >= 7:
                        continue
                    ch = (dh * 2 + dw) * c
                    w4 = w4.at[kh, kw, ch : ch + c, :].set(w7[ih, iw])
    return w4


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: Callable
    norm: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale: residual branches start as identity
        # (the standard large-batch ImageNet trick).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: Callable
    norm: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNetConfig
    policy: Policy

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        cfg = self.config
        dtype = self.policy.compute_dtype
        conv = partial(nn.Conv, use_bias=False, dtype=dtype, padding="SAME")
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=dtype,  # compute in bf16, stats kept fp32 by flax
        )
        x = x.astype(dtype)
        if cfg.stem == "s2d":
            # MLPerf stem: the 7x7/s2 conv reads a 3-channel input, which
            # pads terribly onto the MXU's 128-lane tiles. Space-to-depth(2)
            # expresses the same function (see s2d_stem_weights for the
            # exact weight relabeling) as a 4x4/s1 conv over 12 channels at
            # quarter spatial size — a denser, MXU-friendlier contraction.
            x = space_to_depth(x, 2)
            x = conv(
                64 * cfg.width_multiplier, (4, 4), strides=(1, 1), name="stem_s2d"
            )(x)
        elif cfg.stem == "conv7":
            x = conv(64 * cfg.width_multiplier, (7, 7), strides=(2, 2))(x)
        else:
            # Silent config typos are how benchmarks lie (config/core.py):
            # an unknown stem must not quietly benchmark conv7 twice.
            raise ValueError(
                f"unknown ResNet stem {cfg.stem!r}; expected 'conv7' or 's2d'"
            )
        x = norm()(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        block_cls = BottleneckBlock if BOTTLENECK[cfg.depth] else BasicBlock
        for stage, n_blocks in enumerate(STAGE_SIZES[cfg.depth]):
            for block in range(n_blocks):
                x = block_cls(
                    filters=64 * cfg.width_multiplier * (2**stage),
                    strides=2 if (block == 0 and stage > 0) else 1,
                    conv=conv,
                    norm=norm,
                )(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(cfg.num_classes, dtype=dtype)(x)
        return x.astype(self.policy.output_dtype)
