"""ResNet family (BASELINE config 2: ResNet-50 ImageNet).

TPU-first choices: NHWC layout throughout (the TPU-native conv layout —
XLA tiles NHWC convs directly onto the MXU), bf16 compute via the precision
policy with fp32 BatchNorm statistics, and v1.5 bottlenecks (stride in the
3x3) matching the torchvision recipe the reference trains. BatchNorm runs as
sync-BN for free: under GSPMD the batch axis is a sharded *global* axis, so
the mean/var reduction spans all data shards (better than DDP's per-replica
BN).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from frl_distributed_ml_scaffold_tpu.config.schema import ResNetConfig
from frl_distributed_ml_scaffold_tpu.precision import Policy

STAGE_SIZES = {
    10: (1, 1, 1, 1),  # ResNet-10: the minimal smoke/test depth
    18: (2, 2, 2, 2),
    34: (3, 4, 6, 3),
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}
BOTTLENECK = {10: False, 18: False, 34: False, 50: True, 101: True, 152: True}


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """NHWC space-to-depth: (B, H, W, C) -> (B, H/b, W/b, b*b*C), channel
    packing ``(di, dj, c)`` row-major (the order ``s2d_stem_weights``
    assumes)."""
    b, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(
            f"space_to_depth stem needs spatial dims divisible by {block}; "
            f"got {h}x{w} — use stem='conv7' for odd input sizes"
        )
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, block * block * c)


def s2d_stem_weights(w7: jnp.ndarray) -> jnp.ndarray:
    """Exact rewrite of a (7, 7, C, F) stride-2 SAME stem kernel as the
    (4, 4, 4C, F) stride-1 kernel over space-to-depth(2) input.

    Derivation: SAME padding for k=7, s=2 pads (2, 3), so output pixel x
    reads input a = 2x + i - 2, i in [0, 7). In s2d coordinates a = 2u + di
    with u = x + k - 1, hence i = 2k + di for tap k in [0, 4) — the 7x7
    taps relabel one-to-one onto (k, di) with (3, 1) (i.e. i == 7) zero.
    The MLPerf RN50-on-TPU stem trick, kept mathematically exact so the
    equivalence test can assert it.
    """
    k7, _, c, f = w7.shape
    assert k7 == 7
    w4 = jnp.zeros((4, 4, 4 * c, f), w7.dtype)
    for kh in range(4):
        for dh in range(2):
            ih = 2 * kh + dh
            if ih >= 7:
                continue
            for kw in range(4):
                for dw in range(2):
                    iw = 2 * kw + dw
                    if iw >= 7:
                        continue
                    ch = (dh * 2 + dw) * c
                    w4 = w4.at[kh, kw, ch : ch + c, :].set(w7[ih, iw])
    return w4


def _stem_max_pool(x: jnp.ndarray) -> jnp.ndarray:
    return nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")


def _tap_shift(a: jnp.ndarray, dh: int, dw: int, fill) -> jnp.ndarray:
    """out[h, w] = a[h - dh, w - dw] with ``fill`` where out of range."""
    _, h, w, _ = a.shape
    ap = jnp.pad(a, ((0, 0), (dh, 0), (dw, 0), (0, 0)), constant_values=fill)
    return ap[:, :h, :w, :]


@jax.custom_vjp
def _max_pool_mask_grad(x: jnp.ndarray) -> jnp.ndarray:
    """3x3/s2 SAME max pool whose backward is a compare-and-sum pass.

    Autodiff of ``reduce_window(max)`` lowers to ``select_and_scatter``,
    which the v5e profiler trace pins at a fixed 3.5 ms/step on RN50's
    ``[B, 112, 112, 64]`` stem activations (BASELINE.md). The gradient is
    re-expressed as two fused elementwise passes: (1) per window, count how
    many entries equal the max; (2) per input position, sum ``dy/count``
    over the <=4 covering windows whose max it equals — both 9-tap stencils
    XLA fuses into single bandwidth-shaped kernels (~40% cheaper than the
    scatter). Tie semantics differ from autodiff: tied maxima split the
    gradient equally (a valid subgradient, gradient-mass preserving) where
    select_and_scatter routes it all to the first maximum; tie-free grads
    are identical (tested), and in RN50 the pool input is post-ReLU, where
    all-zero windows — the common tie — get their gradient killed by the
    ReLU backward regardless.
    """
    _check_mask_pool_shape(x)  # fail at trace time, not first grad
    return _stem_max_pool(x)


def _check_mask_pool_shape(x) -> None:
    _, h, w, _ = x.shape
    if h % 2 or w % 2:
        raise ValueError(
            "pool_grad='mask' needs even pool-INPUT spatial dims (its "
            f"dilation math assumes exact stride-2 coverage); got {h}x{w} "
            "into the stem pool — use pool_grad='scatter' for odd sizes"
        )


def _mpm_fwd(x):
    _check_mask_pool_shape(x)
    y = _stem_max_pool(x)
    return y, (x, y)


def _mpm_bwd(res, dy):
    x, y = res
    b, h, w, c = x.shape
    ho, wo = y.shape[1], y.shape[2]
    neg = jnp.array(-jnp.inf, x.dtype)
    # Pass 1 — count[p] = |{window entries == max}|. SAME padding for k=3,
    # s=2 on even dims pads (0, 1): window p reads inputs [2p, 2p+2].
    xp = jnp.pad(x, ((0, 0), (0, 2), (0, 2), (0, 0)), constant_values=neg)
    count = jnp.zeros(y.shape, dy.dtype)
    for th in range(3):
        for tw in range(3):
            patch = lax.slice(
                xp,
                (0, th, tw, 0),
                (b, th + 2 * ho - 1, tw + 2 * wo - 1, c),
                (1, 2, 2, 1),
            )
            count = count + (patch == y).astype(dy.dtype)
    scaled = dy / count  # count >= 1: the max itself is always in-window
    # Pass 2 — scatter-as-gather: dilate (y, dy/count) onto the input grid
    # (odd slots get -inf so they can never match) and sum the <=9 taps
    # whose window max equals x at this position. lax.pad interior dilation
    # (not .at[::2].set, which lowers to a scatter) keeps this fusible.
    dilate = ((0, 0, 0), (0, 1, 1), (0, 1, 1), (0, 0, 0))
    yd = lax.pad(y, neg, dilate)
    sd = lax.pad(scaled, jnp.zeros((), dy.dtype), dilate)
    dx = jnp.zeros_like(x)
    for dh in range(3):
        for dw in range(3):
            y_tap = _tap_shift(yd, dh, dw, neg)
            s_tap = _tap_shift(sd, dh, dw, jnp.zeros((), dy.dtype))
            dx = dx + jnp.where(x == y_tap, s_tap, 0).astype(x.dtype)
    return (dx,)


_max_pool_mask_grad.defvjp(_mpm_fwd, _mpm_bwd)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: Callable
    norm: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale: residual branches start as identity
        # (the standard large-batch ImageNet trick).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: Callable
    norm: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNetConfig
    policy: Policy
    # Blockwise param-gather apply hook (fsdp_overlap.OverlapHooks,
    # lowered from the declared OverlapSchedule's gather(fsdp,block) rule
    # by parallel/schedule.py): when set, each residual block's params are explicitly
    # all-gathered immediately before that block's compute — and the gather
    # of block k is tied (optimization_barrier) to the output of block
    # k - 1 - prefetch, which is the structurally enforced prefetch window
    # of the SimpleFSDP schedule. Attached by the Trainer; init always
    # runs unhooked, and the params tree is identical either way.
    param_hooks: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        cfg = self.config
        dtype = self.policy.compute_dtype
        conv = partial(nn.Conv, use_bias=False, dtype=dtype, padding="SAME")
        if cfg.fused_bn:
            # Same forward, fused Pallas backward (ops/fused_bn.py) — the
            # params/batch_stats tree is identical, so checkpoints and
            # partition rules are oblivious to the switch.
            from frl_distributed_ml_scaffold_tpu.ops.fused_bn import (
                FusedBatchNorm,
            )

            bn_cls = FusedBatchNorm
        else:
            bn_cls = nn.BatchNorm
        norm = partial(
            bn_cls,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=dtype,  # compute in bf16, stats kept fp32 by flax
        )
        x = x.astype(dtype)
        if cfg.stem == "s2d":
            # MLPerf stem: the 7x7/s2 conv reads a 3-channel input, which
            # pads terribly onto the MXU's 128-lane tiles. Space-to-depth(2)
            # expresses the same function (see s2d_stem_weights for the
            # exact weight relabeling) as a 4x4/s1 conv over 12 channels at
            # quarter spatial size — a denser, MXU-friendlier contraction.
            x = space_to_depth(x, 2)
            x = conv(
                64 * cfg.width_multiplier, (4, 4), strides=(1, 1), name="stem_s2d"
            )(x)
        elif cfg.stem == "conv7":
            x = conv(64 * cfg.width_multiplier, (7, 7), strides=(2, 2))(x)
        else:
            # Silent config typos are how benchmarks lie (config/core.py):
            # an unknown stem must not quietly benchmark conv7 twice.
            raise ValueError(
                f"unknown ResNet stem {cfg.stem!r}; expected 'conv7' or 's2d'"
            )
        x = norm()(x)
        x = nn.relu(x)
        if cfg.pool_grad == "mask":
            x = _max_pool_mask_grad(x)
        elif cfg.pool_grad == "scatter":
            x = _stem_max_pool(x)
        else:
            raise ValueError(
                f"unknown pool_grad {cfg.pool_grad!r}; "
                "expected 'scatter' or 'mask'"
            )

        block_cls = BottleneckBlock if BOTTLENECK[cfg.depth] else BasicBlock
        hooks = self.param_hooks
        if hooks is not None:
            from frl_distributed_ml_scaffold_tpu.parallel.fsdp_overlap import (
                overlap_remat_policy,
            )

            remat_policy = overlap_remat_policy("none")
        outs: list[jnp.ndarray] = []
        for stage, n_blocks in enumerate(STAGE_SIZES[cfg.depth]):
            for block in range(n_blocks):
                cls = block_cls
                kw = {}
                if hooks is not None:
                    # Prefetch window: block k's gather may issue only
                    # after block k-1-prefetch finishes — under it, the
                    # next gather runs while `prefetch` blocks compute.
                    k = len(outs)
                    tok_i = k - 1 - hooks.prefetch
                    token = outs[tok_i] if tok_i >= 0 else None
                    cls = nn.map_variables(
                        cls,
                        "params",
                        trans_in_fn=hooks.hook_factory(token),
                        init=False,
                    )
                    # Remat with the except-gathered policy: backward
                    # re-gathers instead of keeping full block params
                    # among the residuals.
                    cls = nn.remat(cls, prevent_cse=False, policy=remat_policy)
                    # Lifted transforms mangle auto-names; pin the name the
                    # UNhooked path would auto-assign so the param tree is
                    # layout-identical with hooks on or off.
                    kw["name"] = f"{block_cls.__name__}_{k}"
                x = cls(
                    filters=64 * cfg.width_multiplier * (2**stage),
                    strides=2 if (block == 0 and stage > 0) else 1,
                    conv=conv,
                    norm=norm,
                    **kw,
                )(x)
                outs.append(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(cfg.num_classes, dtype=dtype)(x)
        return x.astype(self.policy.output_dtype)
