"""Model zoo (SURVEY C15): the five reference families, flax-linen native.

- ``mlp``    — MNIST MLP (BASELINE config 1)
- ``resnet`` — ResNet-50 family (config 2)
- ``vit``    — ViT-B/16 (config 3)
- ``gpt``    — GPT-2-medium transformer LM, with TP/SP/EP-aware internals
               (config 4 + task-required parallelisms)
- ``video``  — tubelet-ViT video-clip classifier (config 5, Ego4D-style)

``create_model(model_cfg)`` dispatches on the config's ``family`` tag and
returns a flax Module. All modules take a precision ``Policy`` so compute
dtype follows the AMP config (SURVEY C10).
"""

from __future__ import annotations

from typing import Any

from frl_distributed_ml_scaffold_tpu.precision import Policy


def create_model(model_cfg: Any, policy: Policy):
    family = getattr(model_cfg, "family", None)
    if family == "mlp":
        from frl_distributed_ml_scaffold_tpu.models.mlp import MLP

        return MLP(config=model_cfg, policy=policy)
    if family == "resnet":
        from frl_distributed_ml_scaffold_tpu.models.resnet import ResNet

        return ResNet(config=model_cfg, policy=policy)
    if family == "vit":
        from frl_distributed_ml_scaffold_tpu.models.vit import ViT

        return ViT(config=model_cfg, policy=policy)
    if family == "gpt":
        from frl_distributed_ml_scaffold_tpu.models.gpt import GPT

        return GPT(config=model_cfg, policy=policy)
    if family == "video":
        from frl_distributed_ml_scaffold_tpu.models.video import VideoClassifier

        return VideoClassifier(config=model_cfg, policy=policy)
    raise KeyError(f"unknown model family {family!r}")
