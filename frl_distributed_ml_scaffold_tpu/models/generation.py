"""Autoregressive generation for the GPT family: prefill + KV-cache decode.

The training scaffold's inference story (call stack (e) in SURVEY.md §3 is
eval-forward; this extends it to sampling). TPU-idiomatic shape: one
compiled **prefill** over the whole prompt writes every layer's K/V cache,
then one compiled **decode step** inside ``lax.scan`` appends a token per
iteration — static shapes throughout (the cache is pre-sized to
``config.seq_len``), so the entire generate call is two XLA programs no
matter how many tokens are produced.

Sampling: greedy (``temperature=0``), temperature, top-k, and nucleus
(top-p) — all pure functions of the passed rng key, so generation is
reproducible.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def _sample(
    logits: jax.Array, rng, *, temperature: float, top_k: int,
    top_p: float = 0.0,
):
    """[B, V] logits -> [B] sampled token ids (fp32 for stable softmax)."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0 and top_k < logits.shape[-1]:  # k >= V keeps everything
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]  # O(V) threshold
        logits = jnp.where(logits < kth, jnp.finfo(jnp.float32).min, logits)
    if 0.0 < top_p < 1.0:
        # Nucleus: keep the smallest prefix of the sorted distribution
        # whose mass reaches p (the token crossing the threshold is kept —
        # the standard inclusive nucleus). The keep mask is scattered back
        # by POSITION, not compared by logit value: value thresholding
        # would keep every token tied with the boundary logit, silently
        # disabling the filter on uniform/tied distributions.
        b = logits.shape[0]
        # Negate for a genuinely stable descending order (reversing an
        # ascending stable sort would invert tie order at the boundary).
        order = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        mass_before = jnp.cumsum(probs, axis=-1) - probs
        keep_sorted = mass_before < top_p  # always keeps the top token
        keep = jnp.zeros(logits.shape, bool).at[
            jnp.arange(b)[:, None], order
        ].set(keep_sorted)
        logits = jnp.where(keep, logits, jnp.finfo(jnp.float32).min)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def next_cache_bucket(seq_len: int, needed: int, floor: int = 8) -> int:
    """The serving bucket policy: smallest power of two >= ``needed``
    (and >= ``floor``), with ``seq_len`` itself as the terminal bucket.
    Powers of two keep the number of distinct compiled decode programs at
    log2(seq_len) while short requests stop paying full-context cache
    traffic."""
    if needed > seq_len:
        raise ValueError(f"needed cache {needed} exceeds seq_len {seq_len}")
    b = 1 << max(needed, floor, 1).bit_length()
    if b // 2 >= max(needed, floor, 1):
        b //= 2
    return min(b, seq_len)


def _bucketed(model: Any, cache_len: int | None, needed: int) -> Any:
    """Clone the model with its decode cache sized to the active bucket
    (``cache_len=None`` = auto policy; pass ``model.config.seq_len`` for
    the legacy full-context cache)."""
    if cache_len is None:
        cache_len = next_cache_bucket(model.config.seq_len, needed)
    if cache_len < needed:
        raise ValueError(
            f"cache_len={cache_len} cannot hold prompt+new={needed} tokens"
        )
    return model.clone(cache_len=int(cache_len))


def _take_logits(out):
    """MoE models return (logits, aux) tuples from apply."""
    return out[0] if isinstance(out, tuple) else out


def _prefill(model: Any, params: Any, prompt: jax.Array,
             lengths: jax.Array | None, cache: Any = None):
    """One pass over the (possibly left-padded ragged) prompt creates +
    fills every layer's KV cache; returns (last-position logits [B, V],
    cache). Prompts are right-aligned, so logits[:, -1] is every row's
    real last token regardless of raggedness. The SHARED decode entry:
    generate and beam_search both start here, so they cannot drift.

    ``cache`` seeds the cache collection instead of the lazy zero init:
    the serving engine's shared-prefix path prefills only a prompt's
    SUFFIX against an initial cache whose leading positions hold the
    shared prefix's K/V (gathered block-wise from the pool) and whose
    ``cache_index``/``pos_index`` start at the prefix length — the
    attention math is then identical to a full-prompt prefill, minus
    the prefix tokens' projection/score work."""
    variables = {"params": params}
    if cache is not None:
        variables["cache"] = cache
    logits, vars_out = model.apply(
        variables, prompt, decode=True, lengths=lengths,
        mutable=["cache"],
    )
    return _take_logits(logits)[:, -1], vars_out["cache"]


def _decode_step(model: Any, params: Any, cache: Any, tok: jax.Array):
    """One single-token decode step for every row: returns (logits [B, V],
    updated cache). The SHARED step generate and beam_search scan over —
    both therefore route through the same ops/decode_attention entry
    point (flash-decode kernel or dense, per config.decode_attention)."""
    logits, vars_out = model.apply(
        {"params": params, "cache": cache},
        tok[:, None],
        decode=True,
        mutable=["cache"],
    )
    return _take_logits(logits)[:, 0], vars_out["cache"]


def _verify_step(model: Any, params: Any, cache: Any, toks: jax.Array):
    """One batched speculative-VERIFY forward (ISSUE 11): ``toks [B, T]``
    is each row's last accepted token followed by T-1 draft tokens;
    returns (logits ``[B, T, V]`` — ALL positions, unlike ``_prefill`` —
    and the updated cache). On a paged-cache model this is the verify
    tile: all T K/V are scattered into the pool and every position
    scores causally against the cache in one pass
    (ops/decode_attention.paged_verify_attention), so position 0's
    logits equal what ``_decode_step`` would produce and greedy
    acceptance against them is EXACT — which is the bit-exact contract
    speculative decoding rides. The cache indices advance by T
    unconditionally; rejected positions are rolled back afterwards via
    ``rewind_cache_indices`` (lengths are pointers in a paged cache, so
    rollback is a pointer move, never cache surgery)."""
    logits, vars_out = model.apply(
        {"params": params, "cache": cache},
        toks,
        decode=True,
        mutable=["cache"],
    )
    return _take_logits(logits), vars_out["cache"]


def rewind_cache_indices(cache: Any, new_idx: jax.Array) -> Any:
    """Speculative-decode ROLLBACK (ISSUE 11): set every row's cache
    write cursor — the per-layer ``cache_index`` rows ``[L, B]`` and the
    model-level ``pos_index`` ``[B]`` — to ``new_idx [B]``. A verify
    step advances every cursor by k+1; after host-side acceptance the
    true occupancy is ``len + accepted + 1``, so rejected draft
    positions are abandoned by rewinding the cursors (their K/V stay in
    the pool past the cursor, masked out of every later read and
    overwritten by later writes — the same discipline as the bucketed
    path's wrapped-pad garbage). Name-keyed like the pool taxonomy
    (``POOL_LEAF_OF``): every other leaf passes through untouched, so
    the engine can jit this with the cache donated and rollback is pure
    pointer bookkeeping."""
    from flax.traverse_util import flatten_dict, unflatten_dict

    flat = flatten_dict(cache)
    out = {}
    for kp, leaf in flat.items():
        name = kp[-1]
        if name == "cache_index":
            out[kp] = jnp.broadcast_to(
                new_idx.astype(leaf.dtype)[None, :], leaf.shape
            )
        elif name == "pos_index":
            out[kp] = new_idx.astype(leaf.dtype)
        else:
            out[kp] = leaf
    return unflatten_dict(out)


def _plain_stack(model: Any, params: Any) -> tuple[Any, Any]:
    """Decode always runs on the plain layer stack: a pipeline-trained
    model (``pipeline_stages > 1``) is swapped for its ``stages=1`` twin
    and the stage-stacked weights are restacked to ``[L, ...]`` (a pure
    reshape — models/gpt.py ``unstack_pipeline_params``). Weights are
    layout-compatible by construction, so PP checkpoints generate without
    any config surgery. The restack runs per call (free under jit after
    trace); an eager sampling loop over a large PP checkpoint should call
    ``unstack_pipeline_params`` once and pass the plain-stack pair."""
    cfg = getattr(model, "config", None)
    if cfg is None or getattr(cfg, "pipeline_stages", 1) <= 1:
        return model, params
    import dataclasses

    from frl_distributed_ml_scaffold_tpu.models.gpt import (
        unstack_pipeline_params,
    )

    plain = type(model)(
        config=dataclasses.replace(cfg, pipeline_stages=1),
        policy=model.policy,
    )
    return plain, unstack_pipeline_params(cfg, params)


def generate(
    model: Any,
    params: Any,
    prompt: jax.Array,
    *,
    max_new_tokens: int,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 0.0,
    eos_id: int | None = None,
    rng: jax.Array | None = None,
    prompt_lengths: jax.Array | None = None,
    cache_len: int | None = None,
) -> jax.Array:
    """Sample ``max_new_tokens`` continuations of ``prompt`` ([B, Tp] int).

    Returns [B, Tp + max_new_tokens]; positions after an ``eos_id`` emission
    (when given) are padded with ``eos_id``. Jit-compatible as long as
    ``max_new_tokens``/``temperature``/``top_k``/``top_p`` stay static — wrap with
    ``jax.jit(partial(generate, model, ...), static_argnames=...)`` or just
    call it; the two inner ``apply`` calls are where the time goes.

    Ragged batches: pass LEFT-padded prompts (real tokens right-aligned)
    plus ``prompt_lengths`` [B] — prefill then neither attends over nor
    caches the pad columns, so mixed-length batches are first-class.

    The KV cache is bucketed (``next_cache_bucket``) to the smallest
    power of two covering prompt+budget rather than pre-sized to
    ``config.seq_len``; pass ``cache_len=config.seq_len`` to force the
    legacy full-context cache.
    """
    model, params = _plain_stack(model, params)
    cfg = model.config
    b, tp = prompt.shape
    if tp + max_new_tokens > cfg.seq_len:
        raise ValueError(
            f"prompt ({tp}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"model context ({cfg.seq_len}) — the KV cache is sized to it"
        )
    # Prefill writes [0, Tp) and rows extend to at most len+new-1 < Tp+new.
    model = _bucketed(model, cache_len, tp + max_new_tokens)
    rng = jax.random.key(0) if rng is None else rng
    prompt = prompt.astype(jnp.int32)

    logits_last, cache = _prefill(model, params, prompt, prompt_lengths)
    rng, sub = jax.random.split(rng)
    tok = _sample(logits_last, sub, temperature=temperature,
                  top_k=top_k, top_p=top_p)
    done = jnp.zeros((b,), bool) if eos_id is None else tok == eos_id

    def step(carry, _):
        cache, tok, done, rng = carry
        logits, cache = _decode_step(model, params, cache, tok)
        rng, sub = jax.random.split(rng)
        nxt = _sample(logits, sub, temperature=temperature,
                      top_k=top_k, top_p=top_p)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        return (cache, nxt, done, rng), tok

    (_, last, _, _), toks = jax.lax.scan(
        step, (cache, tok, done, rng), None, length=max_new_tokens - 1
    ) if max_new_tokens > 1 else ((cache, tok, done, rng), jnp.zeros((0, b), jnp.int32))
    new = jnp.concatenate([toks.T, last[:, None]], axis=1)  # [B, max_new]
    return jnp.concatenate([prompt, new], axis=1)


def cache_batch_axis(leaf, batch_rows: int) -> int | None:
    """THE decode-cache leaf taxonomy, in one place: which axis of a
    cache leaf carries the request/beam rows. Per-layer K/V stacks
    ``[L, B, S, H, hd]``, their quantization-scale stacks
    ``[L, B, S, H]`` (``kv_cache_quant``), and ``cache_index``
    ``[L, B]`` carry them on axis 1; the model-level ``pos_index``
    ``[B]`` leads with them; other leaves (none today) carry no rows.
    Every per-row cache transform — beam gather/repeat here, the serving
    engine's slot grafts — must agree with this classification, so route
    through it."""
    if leaf.ndim >= 2 and leaf.shape[1] == batch_rows:
        return 1
    if leaf.ndim == 1 and leaf.shape[0] == batch_rows:
        return 0
    return None


def cache_capacity_axis(leaf, cache_len: int) -> int | None:
    """The taxonomy's second question: which axis carries the cache
    CAPACITY (the bucketed S dim the engine grows). K/V stacks
    ``[L, B, S, H, hd]`` and scale stacks ``[L, B, S, H]`` both carry it
    on axis 2; index/position bookkeeping carries none. The engine's
    bucket growth and empty-cache widening route through this (the same
    lockstep contract as ``cache_batch_axis``) — a new capacity-bearing
    leaf class added to the model extends serving by extending THIS
    function, not three ad-hoc ``ndim == 5`` checks."""
    if leaf.ndim >= 4 and leaf.shape[2] == cache_len:
        return 2
    return None


def cache_bytes_per_slot(cache, num_slots: int) -> int:
    """Per-slot HBM bytes of a decode cache tree, from the ACTUAL leaves
    — quantization scale tensors and bookkeeping included, which is what
    keeps bucket HBM estimates (engine slot accounting,
    tools/serve_bench.py bytes-per-slot) honest: an int8 cache is
    ``(hd + 2·scale_bytes/…)`` per element-row, not a free 4x."""
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(cache):
        ax = cache_batch_axis(leaf, num_slots)
        if ax is None:
            continue
        per_row = int(np.prod(leaf.shape, dtype=np.int64)) // leaf.shape[ax]
        total += per_row * jnp.dtype(leaf.dtype).itemsize
    return int(total)


def estimate_cache_bytes_per_slot(
    cfg: Any, cache_len: int, *, kv_dtype_bytes: int = 2
) -> int:
    """Analytic twin of ``cache_bytes_per_slot`` for capacity planning
    BEFORE a cache exists: per decode slot at bucket ``cache_len``, a
    GPT config costs ``L x (K + V (+ scales) + cache_index) +
    pos_index`` bytes. ``kv_dtype_bytes`` is the UNQUANTIZED element
    width (2 for bf16 serving, 4 for the fp32 sim); with
    ``cfg.kv_cache_quant`` set, K/V cost 1 byte and the per-(position,
    head) bf16 scales ride alongside. Pinned equal to the actual cache
    tree in tests/test_serving.py — if the model grows a cache leaf this
    estimate doesn't know, that regression test is what catches the
    drift."""
    h = cfg.num_heads
    hd = cfg.hidden_dim // h
    quant = getattr(cfg, "kv_cache_quant", "none") != "none"
    elem = 1 if quant else kv_dtype_bytes
    per_layer = 2 * cache_len * h * hd * elem  # K + V payloads
    if quant:
        per_layer += 2 * cache_len * h * 2  # bf16 scale per (pos, head)
    per_layer += 4  # cache_index int32
    return cfg.num_layers * per_layer + 4  # + pos_index int32


# --------------------------------------------------------- paged (block) pool
#
# The PAGED decode cache (ISSUE 10) replaces per-slot [B, S, ...] stacks
# with a shared pool of fixed-size blocks plus per-row block tables.  The
# taxonomy below is the paged extension of cache_batch_axis /
# cache_capacity_axis: pool leaves carry NO row axis (blocks are shared —
# that is the whole point) and are classified by NAME, because a pool's
# [N, bs, H, hd] shape is indistinguishable from a slot cache's
# [B, S, H, hd] by shape alone. Every block-wise cache transform — the
# engine's block grafts, the prefix-seed gather, capacity accounting —
# routes through these names, the same lockstep contract as the shape
# taxonomy.

#: Slot-cache leaf name -> its pool counterpart (the contiguous prefill
#: cache's leaves map onto pool blocks through this; the scale leaves are
#: the PR 6 format vocabulary, preserved block-wise).
POOL_LEAF_OF: dict[str, str] = {
    "cached_key": "key_pool",
    "cached_value": "value_pool",
    "key_scale": "key_pool_scale",
    "value_scale": "value_pool_scale",
}

#: Pool leaf name -> slot-cache leaf name (the reverse direction: the
#: prefix-seed gather reconstructs a contiguous prefix from pool blocks).
SLOT_LEAF_OF: dict[str, str] = {v: k for k, v in POOL_LEAF_OF.items()}


def blocks_for_tokens(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` cache positions (ceil)."""
    if tokens <= 0:
        return 0
    return -(-int(tokens) // int(block_size))


def pool_heads_axis(name: str, leaf) -> int | None:
    """The taxonomy's third question (ISSUE 15): which axis of a POOL
    leaf carries the attention heads — the only axis a serving re-spread
    may shard. Name-keyed like ``POOL_LEAF_OF`` (pool shapes are
    ambiguous): K/V pools are ``[..., N, bs, H, hd]`` (heads at
    ``ndim-2``), scale pools ``[..., N, bs, H]`` (heads at ``ndim-1``);
    every other leaf (tables, cursors) carries none. The engine's
    ``respread_pool`` derives its destination layouts through this —
    the same lockstep contract as the shape taxonomy: a new pool leaf
    class extends THIS function, not an ad-hoc ndim check."""
    if name in ("key_pool", "value_pool"):
        return leaf.ndim - 2
    if name in ("key_pool_scale", "value_pool_scale"):
        return leaf.ndim - 1
    return None


def pool_leaf_spec(name: str, leaf):
    """Destination PartitionSpec for one paged-cache leaf under a model
    axis (the ``models/gpt.py _constrain_kv_pool`` layout, derived from
    the name taxonomy): pool leaves shard heads over ``model`` and are
    REPLICATED over every batch axis (blocks are shared across slot
    rows); bookkeeping leaves replicate. ``None`` = no opinion (carry
    the leaf's current spec)."""
    from jax.sharding import PartitionSpec as P

    ax = pool_heads_axis(name, leaf)
    if ax is None:
        return None
    entries = [None] * leaf.ndim
    entries[ax] = "model"
    return P(*entries)


def splice_pool_blocks(cache, slot_cache, blk_ids, m0, slot, *,
                       block_size: int):
    """The prefill→decode HANDOFF SPLICE (ISSUE 12), over the block-pool
    taxonomy: write one prefilled (contiguous, bucketed) slot cache's
    PRIVATE blocks into their physical pool homes and set the slot's
    cursor rows. ``blk_ids [n_priv]`` are the destination physical block
    ids for the logical blocks starting at ``m0`` (shared prefix blocks
    below ``m0`` are already in the pool and are NOT touched — only the
    blocks that change owner move, the arXiv 2112.01075 discipline), and
    ``slot`` is the decode-side row whose ``cache_index``/``pos_index``
    the splice seeds.

    This is the ONLY device work in a prefill→decode handoff: ownership
    itself moves as a host-side block-table row write (a re-own, priced
    in table bytes — the perf-ledger ``serving:handoff`` row), so the
    logical cache is never copied and nothing here can reshard. The
    serving engine jits this with the pool donated (``_paged_graft_fn``);
    graft-lint's ``serving:handoff`` program lints this exact function
    (a gather-based handoff materializing the logical cache view trips
    its cache-copy budget)."""
    from flax.traverse_util import flatten_dict, unflatten_dict

    bs = block_size
    n_priv = blk_ids.shape[0]
    flat = flatten_dict(cache)
    out = dict(flat)
    sflat = flatten_dict(slot_cache)
    for kp, leaf in sflat.items():
        name = kp[-1]
        if name in POOL_LEAF_OF:
            pool_path = kp[:-1] + (POOL_LEAF_OF[name],)
            pool = out[pool_path]
            n_blk = leaf.shape[2] // bs
            chunks = leaf[:, 0].reshape(
                (leaf.shape[0], n_blk, bs) + leaf.shape[3:]
            )
            sl = jax.lax.dynamic_slice_in_dim(chunks, m0, n_priv, axis=1)
            out[pool_path] = pool.at[:, blk_ids].set(sl.astype(pool.dtype))
        elif name == "cache_index":
            out[kp] = out[kp].at[:, slot].set(leaf[:, 0])
        elif name == "pos_index":
            out[kp] = out[kp].at[slot].set(leaf[0])
    return unflatten_dict(out)


def pool_block_bytes(cache) -> int:
    """HBM bytes of ONE pool block across all layers — K/V payloads AND
    quantization-scale blocks, from the ACTUAL pool leaves (the paged
    analog of ``cache_bytes_per_slot``: the unit the engine's
    pool-utilization accounting and serve_bench's paged capacity columns
    price admissions in)."""
    import numpy as np

    total = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        name = getattr(path[-1], "key", None)
        if name in SLOT_LEAF_OF:
            # [L, N, bs, ...] stacked pool leaf: bytes per (all-layers) block.
            n = leaf.shape[1]
            total += (
                int(np.prod(leaf.shape, dtype=np.int64)) // n
            ) * jnp.dtype(leaf.dtype).itemsize
    return int(total)


def estimate_pool_block_bytes(
    cfg: Any, block_size: int, *, kv_dtype_bytes: int = 2
) -> int:
    """Analytic twin of ``pool_block_bytes`` for capacity planning BEFORE
    a pool exists: one block of ``block_size`` positions costs
    ``L x 2 x bs x H x hd`` payload bytes (+ the bf16 scale blocks under
    ``cfg.kv_cache_quant``). Pinned equal to the actual pool tree in
    tests/test_serving.py, like ``estimate_cache_bytes_per_slot``."""
    h = cfg.num_heads
    hd = cfg.hidden_dim // h
    quant = getattr(cfg, "kv_cache_quant", "none") != "none"
    elem = 1 if quant else kv_dtype_bytes
    per_layer = 2 * block_size * h * hd * elem
    if quant:
        per_layer += 2 * block_size * h * 2  # bf16 scale per (pos, head)
    return cfg.num_layers * per_layer


def _gather_cache_rows(cache, rows, batch_rows: int):
    """Reorder the per-beam KV rows of a decode cache. The per-row
    bookkeeping (``cache_index``, ``pos_index``) MUST follow its beam:
    under ragged prompts beams of different rows sit at different
    positions."""

    def leaf(x):
        ax = cache_batch_axis(x, batch_rows)
        return x if ax is None else jnp.take(x, rows, axis=ax)

    return jax.tree.map(leaf, cache)


def _repeat_cache_rows(cache, w: int, batch_rows: int):
    """Row-repeat a [B]-batch cache to [B*W] beams."""

    def leaf(x):
        ax = cache_batch_axis(x, batch_rows)
        return x if ax is None else jnp.repeat(x, w, axis=ax)

    return jax.tree.map(leaf, cache)


def beam_search(
    model: Any,
    params: Any,
    prompt: jax.Array,
    *,
    max_new_tokens: int,
    num_beams: int = 4,
    eos_id: int | None = None,
    length_penalty: float = 0.0,
    prompt_lengths: jax.Array | None = None,
    cache_len: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Deterministic beam decode; returns ``([B, Tp+new] best tokens,
    [B] scores)``.

    Same two-XLA-program shape as ``generate``: one prefill over the [B]
    prompt (the cache is then row-repeated to [B*W] — cheaper than
    prefilling W copies), one scanned decode step over all beams. Each
    step extends every beam over the full vocab, keeps the top W of W*V
    by accumulated log-prob, and reorders the KV cache rows by the
    surviving beams' parents. Finished beams (``eos_id``) are frozen:
    their only continuation is eos at zero additional log-prob.

    Scoring: beams are SEARCHED by raw summed log-prob; with
    ``length_penalty`` alpha > 0, the FINAL ranking divides each beam's
    sum by ``len_emitted**alpha`` (GNMT-style, where len counts tokens up
    to and including the first eos) — countering raw-sum's short-sequence
    bias. The returned score is the ranked quantity (raw sum when
    alpha=0).
    """
    model, params = _plain_stack(model, params)
    cfg = model.config
    b, tp = prompt.shape
    w = num_beams
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens={max_new_tokens} < 1: the returned score is "
            "the sum log-prob of the emitted tokens, so at least one must "
            "be emitted"
        )
    if tp + max_new_tokens > cfg.seq_len:
        raise ValueError(
            f"prompt ({tp}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"model context ({cfg.seq_len}) — the KV cache is sized to it"
        )
    if w < 1 or w > cfg.vocab_size:
        raise ValueError(f"num_beams={w} not in [1, vocab={cfg.vocab_size}]")
    model = _bucketed(model, cache_len, tp + max_new_tokens)
    prompt = prompt.astype(jnp.int32)

    # Same shared prefill + decode-step entry as generate(): the beam path
    # cannot drift from the greedy path's attention numerics.
    logits_last, cache0 = _prefill(model, params, prompt, prompt_lengths)
    lp0 = jax.nn.log_softmax(logits_last.astype(jnp.float32))  # [B, V]
    scores, tok = jax.lax.top_k(lp0, w)  # [B, W] each
    cache = _repeat_cache_rows(cache0, w, b)
    finished = (
        jnp.zeros((b, w), bool) if eos_id is None else tok == eos_id
    )
    buf = jnp.zeros((b, w, max_new_tokens), jnp.int32)
    buf = buf.at[:, :, 0].set(tok)
    batch_idx = jnp.arange(b)[:, None]

    def step(carry, t):
        cache, tok, scores, finished, buf = carry
        logits, new_cache = _decode_step(
            model, params, cache, tok.reshape(b * w)
        )
        lp = jax.nn.log_softmax(logits.astype(jnp.float32))
        lp = lp.reshape(b, w, -1)  # [B, W, V]
        if eos_id is not None:
            # Frozen beams may only repeat eos, for free — their score
            # stays comparable while live beams keep extending.
            eos_only = jnp.full_like(lp, jnp.finfo(jnp.float32).min)
            eos_only = eos_only.at[..., eos_id].set(0.0)
            lp = jnp.where(finished[..., None], eos_only, lp)
        total = scores[..., None] + lp  # [B, W, V]
        v = total.shape[-1]
        new_scores, flat_idx = jax.lax.top_k(total.reshape(b, w * v), w)
        src = flat_idx // v  # parent beam per survivor [B, W]
        new_tok = (flat_idx % v).astype(jnp.int32)
        rows = (batch_idx * w + src).reshape(-1)
        cache = _gather_cache_rows(new_cache, rows, b * w)
        buf = buf[batch_idx, src]  # reorder histories to surviving beams
        buf = buf.at[:, :, t].set(new_tok)
        finished = finished[batch_idx, src]
        if eos_id is not None:
            finished = finished | (new_tok == eos_id)
        return (cache, new_tok, new_scores, finished, buf), None

    if max_new_tokens > 1:
        (cache, tok, scores, finished, buf), _ = jax.lax.scan(
            step,
            (cache, tok, scores, finished, buf),
            jnp.arange(1, max_new_tokens),
        )
    if length_penalty > 0.0:
        # Re-rank by length-normalized score (search stays raw-sum: the
        # normalization is not monotone across different-length prefixes,
        # so applying it per-step would break the beam invariant).
        if eos_id is None:
            # Every beam has the same length: a constant division — no
            # reordering can occur, so don't sort (an unstable reorder on
            # f32 ties would needlessly swap equal-scored beams).
            scores = scores / float(max_new_tokens) ** length_penalty
        else:
            is_eos = buf == eos_id
            first = jnp.argmax(is_eos, axis=-1)
            lens = jnp.where(
                is_eos.any(-1), first + 1, max_new_tokens
            ).astype(jnp.float32)
            ranked = scores / lens**length_penalty
            # argsort(-x) is stable-descending: ties keep the raw-score
            # beam order instead of flipping to the worst tied beam.
            order = jnp.argsort(-ranked, axis=1)
            buf = jnp.take_along_axis(buf, order[..., None], axis=1)
            scores = jnp.take_along_axis(ranked, order, axis=1)
    # Beams are sorted by (possibly re-ranked) score: beam 0 is the argmax.
    return jnp.concatenate([prompt, buf[:, 0]], axis=1), scores[:, 0]
