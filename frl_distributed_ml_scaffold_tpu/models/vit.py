"""ViT-B/16 (BASELINE config 3: ImageNet FSDP + activation checkpointing).

Pre-LN ViT. TPU-first: patch embedding as a strided conv (one big MXU-
friendly matmul), bf16 compute with fp32 LayerNorm, learned position
embeddings, CLS or mean pooling. FSDP sharding comes entirely from the
partitioning layer (no wrapper) and remat from the trainer config — the
model itself stays strategy-agnostic.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from frl_distributed_ml_scaffold_tpu.config.schema import ViTConfig
from frl_distributed_ml_scaffold_tpu.parallel.partition import PartitionRules
from frl_distributed_ml_scaffold_tpu.precision import Policy


def vit_tp_rules() -> PartitionRules:
    """Megatron column/row sharding for the ViT encoder (SURVEY C6) — also
    used by the video classifier, which reuses ``EncoderBlock``.

    flax ``MultiHeadDotProductAttention`` kernels are (dim, heads, head_dim)
    for q/k/v and (heads, head_dim, dim) for out: sharding the HEADS dim
    over ``model`` is the column/row split — per-head attention stays local
    and GSPMD inserts one allreduce after out, one after the MLP down-proj.
    The FSDP overlay (parallel.param_sharding=fsdp) then picks the largest
    still-unsharded dim, so TP x FSDP composes without special cases.
    """
    return PartitionRules(
        rules=(
            (
                r"MultiHeadDotProductAttention_\d+/(query|key|value)/kernel",
                P(None, "model", None),
            ),
            (
                r"MultiHeadDotProductAttention_\d+/(query|key|value)/bias",
                P("model", None),
            ),
            (r"MultiHeadDotProductAttention_\d+/out/kernel", P("model", None, None)),
            (r"MlpBlock_\d+/Dense_0/kernel", P(None, "model")),
            (r"MlpBlock_\d+/Dense_0/bias", P("model")),
            (r"MlpBlock_\d+/Dense_1/kernel", P("model", None)),
        )
    )


class MlpBlock(nn.Module):
    dim: int
    mlp_ratio: int
    dropout: float
    dtype: Any = jnp.float32
    tp: Any = None  # collective-matmul TP hooks (parallel/tp_overlap.py)

    @nn.compact
    def __call__(self, x, *, train: bool):
        ag_dg = self.tp.ag_dot_general if self.tp is not None else None
        mrs_dg = self.tp.mrs_dot_general if self.tp is not None else None
        y = nn.Dense(
            self.dim * self.mlp_ratio, dtype=self.dtype, dot_general=ag_dg
        )(x)
        y = nn.gelu(y)
        y = nn.Dropout(self.dropout, deterministic=not train)(y)
        y = nn.Dense(self.dim, dtype=self.dtype, dot_general=mrs_dg)(y)
        y = nn.Dropout(self.dropout, deterministic=not train)(y)
        return y


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_ratio: int
    dropout: float
    dtype: Any = jnp.float32
    # Collective-matmul TP schedule (parallel/tp_overlap.py): the q/k/v
    # projections share one batch-chunked all-gather-matmul ring (injected
    # via flax's qkv_dot_general — param layout untouched) and the out /
    # MLP down projections become matmul-reduce-scatter rings, so the
    # residual stream between sublayers stays batch-sharded over the model
    # axis and no monolithic activation collective is exposed.
    tp: Any = None

    @nn.compact
    def __call__(self, x, *, train: bool):
        dim = x.shape[-1]
        tp = self.tp
        qkv_dg = tp.qkv_context().dot_general if tp is not None else None
        out_dg = tp.mrs_dot_general if tp is not None else None
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        if tp is not None:
            # Pre-cast so the MHA's three per-projection promote_dtype
            # calls are identities and the shared-QKV ring cache (keyed on
            # input-object identity) hits under bf16_mixed — one gather
            # ring, not three. Numerically a no-op (DenseGeneral performs
            # this exact cast internally).
            y = y.astype(self.dtype)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads,
            dtype=self.dtype,
            dropout_rate=self.dropout,
            deterministic=not train,
            qkv_dot_general=qkv_dg,
            out_dot_general=out_dg,
        )(y, y)
        x = x + y
        if tp is not None:
            x = tp.constrain_stream(x)
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        y = MlpBlock(
            dim=dim,
            mlp_ratio=self.mlp_ratio,
            dropout=self.dropout,
            dtype=self.dtype,
            tp=tp,
        )(y, train=train)
        x = x + y
        if tp is not None:
            x = tp.constrain_stream(x)
        return x


class ViT(nn.Module):
    config: ViTConfig
    policy: Policy
    # Collective-matmul ring hooks (tp_overlap.TpHooks, lowered from the
    # declared OverlapSchedule's ring rule by parallel/schedule.py),
    # attached by the Trainer for the loss path only — init always runs
    # unhooked and the params tree is identical either way (see
    # EncoderBlock).
    tp_overlap: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        cfg = self.config
        dtype = self.policy.compute_dtype
        x = x.astype(dtype)
        p = cfg.patch_size
        # Patch embedding: strided conv == per-patch linear proj, MXU-shaped.
        x = nn.Conv(
            cfg.hidden_dim, (p, p), strides=(p, p), padding="VALID", dtype=dtype
        )(x)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)

        if cfg.pool == "cls":
            cls = self.param(
                "cls_token", nn.initializers.zeros, (1, 1, cfg.hidden_dim)
            )
            x = jnp.concatenate([jnp.tile(cls, (b, 1, 1)).astype(dtype), x], axis=1)

        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], cfg.hidden_dim),
        )
        x = x + pos.astype(dtype)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)

        for _ in range(cfg.num_layers):
            x = EncoderBlock(
                num_heads=cfg.num_heads,
                mlp_ratio=cfg.mlp_ratio,
                dropout=cfg.dropout,
                dtype=dtype,
                tp=self.tp_overlap,
            )(x, train=train)

        x = nn.LayerNorm(dtype=jnp.float32)(x)
        x = x[:, 0] if cfg.pool == "cls" else jnp.mean(x, axis=1)
        x = nn.Dense(cfg.num_classes, dtype=dtype)(x)
        return x.astype(self.policy.output_dtype)
