"""Expert-parallel MoE MLP (SURVEY C9): GShard-style top-k capacity routing.

TPU-native formulation: experts live in a single stacked parameter
(E, D, H) sharded over the ``expert`` mesh axis; token dispatch/combine are
einsums against one-hot dispatch tensors, so GSPMD lowers the expert
exchange to ``all_to_all`` on ICI — no manual send/recv. Router math in
fp32. Capacity-dropped tokens pass through (residual connection carries
them). Load-balance aux loss per GShard/Switch.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from frl_distributed_ml_scaffold_tpu.config.schema import GPTConfig


class MoEMlp(nn.Module):
    config: GPTConfig
    dtype: Any

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool) -> tuple[jnp.ndarray, jnp.ndarray]:
        cfg = self.config
        moe = cfg.moe
        d = cfg.hidden_dim
        hidden = d * cfg.mlp_ratio
        e, k = moe.num_experts, moe.top_k
        b, t, _ = x.shape
        n = b * t
        # Cast to the compute dtype here (the dense MLP gets this implicitly
        # from nn.Dense(dtype=...)); expert math below runs in this dtype so
        # the residual sum keeps the block's carry dtype stable under scan.
        xf = x.reshape(n, d).astype(self.dtype)

        # Router (fp32): probabilities over experts per token.
        router_logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            xf.astype(jnp.float32)
        )
        probs = jax.nn.softmax(router_logits, axis=-1)  # (N, E)
        gate_vals, gate_idx = jax.lax.top_k(probs, k)  # (N, k)
        gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

        capacity = max(1, int(moe.capacity_factor * n * k / e))

        # Position-in-expert via cumulative counts, slot by slot.
        dispatch = jnp.zeros((n, e, capacity), self.dtype)
        combine = jnp.zeros((n, e, capacity), jnp.float32)
        prev_counts = jnp.zeros((e,), jnp.int32)
        for slot in range(k):
            onehot = jax.nn.one_hot(gate_idx[:, slot], e, dtype=jnp.int32)  # (N, E)
            pos = jnp.cumsum(onehot, axis=0) - 1 + prev_counts[None, :]  # (N, E)
            prev_counts = prev_counts + onehot.sum(axis=0)
            pos_tok = (pos * onehot).sum(-1)  # (N,)
            keep = pos_tok < capacity
            pos_oh = jax.nn.one_hot(pos_tok, capacity, dtype=self.dtype)  # (N, C)
            slot_dispatch = (
                onehot.astype(self.dtype)[:, :, None]
                * pos_oh[:, None, :]
                * keep.astype(self.dtype)[:, None, None]
            )
            dispatch = dispatch + slot_dispatch
            combine = combine + slot_dispatch.astype(jnp.float32) * gate_vals[
                :, slot
            ].astype(jnp.float32)[:, None, None]

        # Expert computation: stacked params, expert axis shardable.
        wi = self.param(
            "wi", nn.initializers.normal(stddev=0.02), (e, d, hidden)
        )
        wo = self.param(
            "wo", nn.initializers.normal(stddev=0.02), (e, hidden, d)
        )
        expert_in = jnp.einsum("nec,nd->ecd", dispatch, xf)  # all_to_all here
        h = jax.nn.gelu(
            jnp.einsum("ecd,edh->ech", expert_in, wi.astype(self.dtype))
        )
        expert_out = jnp.einsum("ech,ehd->ecd", h, wo.astype(self.dtype))
        y = jnp.einsum(
            "nec,ecd->nd", combine.astype(self.dtype), expert_out
        )  # and back

        # GShard load-balance loss: E * sum_e(frac_tokens_e * mean_prob_e).
        frac = jnp.mean(
            jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32), axis=0
        )
        mean_prob = jnp.mean(probs, axis=0)
        aux = moe.router_aux_loss * e * jnp.sum(frac * mean_prob)

        return y.reshape(b, t, d), aux
